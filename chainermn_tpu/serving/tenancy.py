"""Multi-tenant QoS: admission budgets, priority classes, degradation.

ISSUE 11 tentpole (b), jax-free and fuzzable standalone: a fleet
serving millions of users carries tenants with very different SLO
value, and "keep the paid tenant's SLO intact under a burst" means the
router must know WHO each request bills to and degrade the cheap
traffic FIRST — by explicit, machine-readable, counted steps, never by
queue collapse.  Three pieces:

* **Priority classes** — every tenant is ``paid`` or ``best_effort``
  (:data:`PRIORITIES`, ordered most- to least-protected).  The
  routers' shared SLO-burn shed gate
  (:meth:`~chainermn_tpu.serving.router.RouterBase._maybe_shed_slo`)
  sheds best-effort tenants at the configured ``shed_burn_threshold``
  but gives paid tenants ``paid_burn_headroom``× more room — so under
  overload a best-effort tenant sheds while the paid tenant's burn
  rate is still approaching the pager, not after it fired.

* **Admission budgets** (:class:`Tenant`) — a per-tenant token bucket
  on request admissions (``rate_per_s`` refill, ``burst`` capacity)
  plus a ``max_inflight`` concurrency cap.  Exhausting either refuses
  the submit with reason ``shed_tenant_budget`` carrying the tenant
  and the current degradation rung (``AdmissionError.to_dict()`` wire
  shape) — one noisy tenant cannot starve the rest even inside its
  own priority class.

* **Degradation ladder** (:class:`DegradationLadder`) — before the
  router sheds a PRIORITY tenant it walks best-effort service down
  four rungs, each a counted observable state transition (``degrade``
  flight events):

  ====  ==============  ====================================================
  rung  name            effect on best-effort tenants
  ====  ==============  ====================================================
  0     ``normal``      full service
  1     ``tight``       ``max_new_tokens`` clamped to ``tight_frac`` of the
                        request's ask (floor 1)
  2     ``throttle``    rejection ``retry_after_ms`` hints multiplied by
                        ``throttle_retry_mult`` on top of the drain-rate
                        derivation (clients back off harder than congestion
                        alone implies)
  3     ``pause``       admission refused outright (``shed_tenant_budget``)
  ====  ==============  ====================================================

  The ladder climbs on a scalar overload *pressure* (the router feeds
  ``max(burn_rate/shed_threshold, queue_depth/queue_capacity)``) with
  per-rung enter thresholds, exits a hysteresis gap LOWER, and holds
  each rung for a minimum dwell — the same no-flap discipline as the
  autoscaler (docs/ROBUSTNESS.md "Autoscaling & overload").

:class:`TenantTable` composes all three and owns the per-tenant
attribution the ISSUE requires in ``/statusz`` and ``/metricsz``:
admitted/shed counters per reason, tokens emitted, TTFT reservoirs,
degraded-request counts, and live budget consumption.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability.slo import ReservoirSample, percentile_of

#: Priority classes, most- to least-protected.  ``paid`` traffic sheds
#: only with ``paid_burn_headroom``× headroom past the best-effort shed
#: threshold; ``best_effort`` absorbs every degradation rung first.
PRIORITIES = ("paid", "best_effort")


class DegradationLadder:
    """Stepwise best-effort degradation with hysteresis (rungs 0..3).

    ``update(pressure, now)`` is a pure function of its inputs and the
    retained state — no sleeps, receiver-clocked (pass ``now``
    explicitly in tests).  Climbing requires ``pressure`` ≥ the next
    rung's enter threshold; descending requires pressure < (enter −
    ``hysteresis``) AND ``dwell_s`` elapsed since the last transition,
    so a pressure signal oscillating around one threshold cannot make
    the ladder flap.  Every transition is counted and noted
    (``degrade`` flight events carry from/to rung and the pressure that
    drove it).
    """

    RUNGS = ("normal", "tight", "throttle", "pause")

    def __init__(self, *, enter=(0.85, 1.0, 1.25), hysteresis: float = 0.2,
                 dwell_s: float = 0.5, tight_frac: float = 0.5,
                 throttle_retry_mult: float = 4.0):
        if len(enter) != len(self.RUNGS) - 1:
            raise ValueError(f"enter wants {len(self.RUNGS) - 1} "
                             f"thresholds (one per rung above normal), "
                             f"got {enter}")
        if list(enter) != sorted(enter):
            raise ValueError(f"enter thresholds must ascend, got {enter}")
        if hysteresis <= 0:
            raise ValueError("hysteresis must be > 0 (equal enter/exit "
                             "thresholds flap on a noisy signal)")
        self.enter = tuple(float(e) for e in enter)
        self.hysteresis = float(hysteresis)
        self.dwell_s = float(dwell_s)
        self.tight_frac = float(tight_frac)
        self.throttle_retry_mult = float(throttle_retry_mult)
        self.rung = 0
        self.last_pressure = 0.0
        self.transitions = 0
        self.transitions_up = 0
        self.rung_entries = {name: 0 for name in self.RUNGS}
        self._t_last_transition: Optional[float] = None
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self.RUNGS[self.rung]

    @property
    def paused(self) -> bool:
        return self.rung >= 3

    def cap_max_tokens(self, requested: int) -> int:
        """Best-effort ``max_new_tokens`` under the current rung."""
        if self.rung >= 1:
            return max(int(int(requested) * self.tight_frac), 1)
        return int(requested)

    def retry_multiplier(self) -> float:
        """Multiplier on best-effort ``retry_after_ms`` hints."""
        return self.throttle_retry_mult if self.rung >= 2 else 1.0

    def update(self, pressure: float, now: Optional[float] = None) -> int:
        """Advance/retreat at most one rung per call; returns the rung."""
        from ..observability import flight as _flight

        now = time.monotonic() if now is None else float(now)
        pressure = float(pressure)
        with self._lock:
            self.last_pressure = pressure
            old = self.rung
            dwelt = (self._t_last_transition is None
                     or now - self._t_last_transition >= self.dwell_s)
            if (self.rung < len(self.RUNGS) - 1
                    and pressure >= self.enter[self.rung]):
                self.rung += 1
            elif (self.rung > 0 and dwelt
                    and pressure < self.enter[self.rung - 1]
                    - self.hysteresis):
                self.rung -= 1
            if self.rung != old:
                self.transitions += 1
                if self.rung > old:
                    self.transitions_up += 1
                self.rung_entries[self.RUNGS[self.rung]] += 1
                self._t_last_transition = now
                new_rung, new_name = self.rung, self.name
            else:
                return self.rung
        _flight.note("degrade", event="rung_change",
                     rung=new_rung, name=new_name,
                     from_rung=old, pressure=round(pressure, 4))
        return new_rung

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rung": self.rung,
                "name": self.name,
                "pressure": round(self.last_pressure, 4),
                "enter": list(self.enter),
                "hysteresis": self.hysteresis,
                "transitions": self.transitions,
                "rung_entries": dict(self.rung_entries),
            }


class Tenant:
    """One tenant's class, budgets, bucket state, and attribution."""

    def __init__(self, name: str, priority: str = "paid", *,
                 rate_per_s: Optional[float] = None,
                 burst: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 stats_capacity: int = 512):
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        self.name = str(name)
        self.priority = priority
        self.rate_per_s = None if rate_per_s is None else float(rate_per_s)
        self.burst = (None if rate_per_s is None
                      else max(int(burst if burst is not None
                                   else max(rate_per_s, 1.0)), 1))
        self.max_inflight = (None if max_inflight is None
                             else int(max_inflight))
        # token bucket (admissions): starts full
        self._bucket = float(self.burst or 0)
        self._t_refill: Optional[float] = None
        # attribution
        self.submitted = 0
        self.admitted = 0
        self.degraded = 0                  # max_new_tokens clamped
        self.shed: Dict[str, int] = {}     # reason -> count
        self.tokens_emitted = 0
        self.ttft_ms = ReservoirSample(int(stats_capacity))
        self._tracked: List[Any] = []      # live Requests (lazy-pruned)

    # ---- budget ----
    def _refill(self, now: float) -> None:
        if self.rate_per_s is None:
            return
        if self._t_refill is None:
            self._t_refill = now
            return
        self._bucket = min(self._bucket
                           + (now - self._t_refill) * self.rate_per_s,
                           float(self.burst))
        self._t_refill = now

    def budget_check(self, now: float) -> Optional[str]:
        """Why admission must be refused NOW (a detail string), or None
        to admit (consuming one bucket token)."""
        self._prune()
        if self.max_inflight is not None \
                and len(self._tracked) >= self.max_inflight:
            return (f"tenant {self.name!r} at max_inflight "
                    f"{self.max_inflight}")
        if self.rate_per_s is not None:
            self._refill(now)
            if self._bucket < 1.0:
                return (f"tenant {self.name!r} admission budget "
                        f"exhausted ({self.rate_per_s}/s, burst "
                        f"{self.burst})")
            self._bucket -= 1.0
        return None

    # ---- attribution ----
    def _prune(self) -> None:
        self._tracked = [r for r in self._tracked
                        if r.status not in ("done", "evicted")]

    def track(self, req) -> None:
        self._tracked.append(req)

    @property
    def inflight(self) -> int:
        self._prune()
        return len(self._tracked)

    def budget_state(self, now: float) -> Dict[str, Any]:
        self._refill(now)
        return {
            "priority": self.priority,
            "rate_per_s": self.rate_per_s,
            "burst": self.burst,
            "bucket_tokens": (None if self.rate_per_s is None
                              else round(self._bucket, 3)),
            "max_inflight": self.max_inflight,
            "inflight": self.inflight,
        }


class TenantTable:
    """The router-side tenant plane: registry + ladder + attribution.

    One table is shared by a router (or a whole fleet); every method is
    thread-safe (submit threads vs the supervisor/driver thread).
    Unknown tenants auto-register at ``default_priority`` with no
    budgets — tagging traffic is enough to get attribution; budgets
    are opt-in via :meth:`register`.
    """

    def __init__(self, *, default_priority: str = "paid",
                 ladder: Optional[DegradationLadder] = None,
                 clock: Callable[[], float] = time.monotonic):
        if default_priority not in PRIORITIES:
            raise ValueError(f"default_priority must be one of "
                             f"{PRIORITIES}, got {default_priority!r}")
        self.default_priority = default_priority
        self.ladder = ladder or DegradationLadder()
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}

    # ---- registry ----
    def register(self, name: str, priority: Optional[str] = None,
                 **budgets) -> Tenant:
        with self._lock:
            t = self._tenants.get(str(name))
            if t is None:
                t = Tenant(name, priority or self.default_priority,
                           **budgets)
                self._tenants[t.name] = t
            return t

    def resolve(self, name: str,
                priority: Optional[str] = None) -> Tenant:
        """The submit-path lookup: auto-registers unknown tenants (no
        budgets) so tagging alone yields attribution."""
        return self.register(name, priority)

    def get(self, name: str) -> Optional[Tenant]:
        with self._lock:
            return self._tenants.get(str(name))

    def tenants(self) -> List[Tenant]:
        with self._lock:
            return list(self._tenants.values())

    # ---- admission plane ----
    def admission_check(self, tenant: Tenant,
                        now: Optional[float] = None
                        ) -> Optional[Tuple[str, str]]:
        """Returns ``(reason, detail)`` to refuse, or None to admit.
        Best-effort tenants additionally honor the ladder's ``pause``
        rung.  Counts the submit either way."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            tenant.submitted += 1
            if tenant.priority == "best_effort" and self.ladder.paused:
                return ("shed_tenant_budget",
                        f"best-effort admission paused at degradation "
                        f"rung {self.ladder.rung} ({self.ladder.name})")
            detail = tenant.budget_check(now)
            if detail is not None:
                return ("shed_tenant_budget", detail)
            return None

    def on_admit(self, tenant: Tenant, req,
                 capped: bool = False) -> None:
        with self._lock:
            tenant.admitted += 1
            if capped:
                tenant.degraded += 1
            tenant.track(req)

    def count_shed(self, tenant_name: Optional[str],
                   reason: str) -> None:
        if tenant_name is None:
            return
        t = self.resolve(tenant_name)
        with self._lock:
            t.shed[reason] = t.shed.get(reason, 0) + 1

    # ---- goodput/TTFT attribution ----
    def on_tokens(self, tenant_name: Optional[str], n: int) -> None:
        if tenant_name is None:
            return
        t = self.resolve(tenant_name)
        with self._lock:
            t.tokens_emitted += int(n)

    def on_ttft(self, tenant_name: Optional[str], ttft_ms: float) -> None:
        if tenant_name is None:
            return
        t = self.resolve(tenant_name)
        with self._lock:
            t.ttft_ms.add(float(ttft_ms))

    def wrap_on_token(self, tenant_name: str, t_submit: float,
                      on_token: Optional[Callable] = None) -> Callable:
        """Per-tenant attribution wrapper for routers whose engines own
        the token stream (ServingRouter/DisaggRouter): first token
        stamps the tenant's TTFT (measured from the ROUTER's submit
        stamp), every token bills the tenant, and the caller's callback
        still runs."""
        seen_first = [False]

        def cb(tok: int, rid: int) -> None:
            if not seen_first[0]:
                seen_first[0] = True
                self.on_ttft(tenant_name,
                             (time.monotonic() - t_submit) * 1e3)
            self.on_tokens(tenant_name, 1)
            if on_token is not None:
                on_token(tok, rid)

        return cb

    # ---- read-out ----
    def metrics(self) -> Dict[str, float]:
        """Flat per-tenant gauges (``tenant/<name>/*`` — the
        ``/metricsz`` and bench-section payload).  ``shed``/``degraded``
        keys gate lower-is-better."""
        out: Dict[str, float] = {}
        lad = self.ladder.state()
        out["tenant/degradation_rung"] = float(lad["rung"])
        out["tenant/degradation_transitions"] = float(lad["transitions"])
        for t in self.tenants():
            with self._lock:
                p = f"tenant/{t.name}"
                out[f"{p}/submitted_total"] = float(t.submitted)
                out[f"{p}/admitted_total"] = float(t.admitted)
                out[f"{p}/degraded_total"] = float(t.degraded)
                out[f"{p}/shed_total"] = float(sum(t.shed.values()))
                for reason, n in sorted(t.shed.items()):
                    out[f"{p}/shed/{reason}"] = float(n)
                out[f"{p}/tokens_total"] = float(t.tokens_emitted)
                out[f"{p}/inflight"] = float(t.inflight)
                vals = t.ttft_ms.values()
            if vals:
                out[f"{p}/ttft_p50_ms"] = percentile_of(vals, 50)
                out[f"{p}/ttft_p99_ms"] = percentile_of(vals, 99)
        return out

    def state(self) -> Dict[str, Any]:
        """The ``/statusz``/bundle view: ladder + per-tenant budget
        consumption and attribution (ISSUE 11 satellite: live
        introspection and the flight bundle agree on who got shed)."""
        now = self._clock()
        tenants = {}
        for t in self.tenants():
            with self._lock:
                tenants[t.name] = dict(
                    t.budget_state(now),
                    submitted=t.submitted, admitted=t.admitted,
                    degraded=t.degraded, shed=dict(t.shed),
                    tokens=t.tokens_emitted)
        return {"ladder": self.ladder.state(), "tenants": tenants}
