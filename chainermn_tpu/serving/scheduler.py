"""Admission + eviction policy for the continuous-batching engine.

Pure host-side Python (no jax import): the scheduler decides WHICH
sequences occupy the fixed slot pool each tick; the engine decides what
the chips compute.  Keeping the policy jax-free makes its invariants
directly fuzzable (tests/test_serving.py) — no compile, no devices.

Policy (deliberately simple and inspectable; knobs in docs/SERVING.md):

* **Bounded FIFO queue with backpressure.**  ``submit`` raises
  :class:`AdmissionError` with a machine-readable ``reason`` the moment
  the queue is full (``queue_full``) or the request can never fit its
  slot (``too_long``) — a loaded server must refuse work it cannot
  start, not buffer it into an OOM.
* **Prefill/decode interleaving.**  At most ``max_prefills_per_tick``
  waiting requests are prefilled before each decode tick (prefill is a
  whole-prompt forward — letting a burst of arrivals monopolize the
  engine would stall every running sequence's per-token latency).
  Admission is strictly FIFO among queued requests.
* **Eviction.**  A sequence leaves its slot when it emits ``eos_id``
  (``eos``), reaches ``max_new_tokens`` (``max_tokens``), or blows its
  deadline (``deadline`` — checked both while queued and while
  decoding).  The freed slot is recycled by the next admission, without
  reallocation.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from typing import Callable, Deque, List, Optional


class AdmissionError(Exception):
    """Backpressure signal: the request was REJECTED, with a reason.

    ``reason`` is machine-readable: ``queue_full`` (bounded queue at
    capacity — retry later / shed load upstream), ``too_long`` (the
    request can never fit: prompt + max_new_tokens exceeds the pool's
    per-slot capacity or the model's position table), or ``shed_slo``
    (the router's SLO-aware admission control shed the request BEFORE
    the burn-rate tracker pages — degrade by rejecting, not by letting
    the queues collapse; ISSUE 7).

    ``retry_after_ms`` / ``queue_depth`` ride along when the rejecting
    layer can estimate them (the router always fills both) so a client
    can back off intelligently instead of hammering; ``to_dict()`` is
    the wire shape the serving JSONL stream and HTTP 429 bodies carry.

    ``tenant`` / ``rung`` (ISSUE 11): a multi-tenant rejection names
    WHO was shed and at which degradation-ladder rung — reason
    ``shed_tenant_budget`` (per-tenant admission budget exhausted, or
    best-effort admission paused at the top rung) carries both, and
    ``shed_slo``/``queue_full`` carry tenant attribution whenever the
    submit was tagged.  Absent for untagged traffic, so pre-tenancy
    wire consumers see exactly the old shape.
    """

    def __init__(self, reason: str, detail: str = "", *,
                 retry_after_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 tenant: Optional[str] = None,
                 rung: Optional[int] = None):
        self.reason = reason
        self.detail = detail
        self.retry_after_ms = (None if retry_after_ms is None
                               else float(retry_after_ms))
        self.queue_depth = (None if queue_depth is None
                            else int(queue_depth))
        self.tenant = None if tenant is None else str(tenant)
        self.rung = None if rung is None else int(rung)
        super().__init__(f"{reason}: {detail}" if detail else reason)

    def to_dict(self) -> dict:
        out = {"reason": self.reason, "detail": self.detail}
        if self.retry_after_ms is not None:
            out["retry_after_ms"] = round(self.retry_after_ms, 3)
        if self.queue_depth is not None:
            out["queue_depth"] = self.queue_depth
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.rung is not None:
            out["rung"] = self.rung
        return out


class Request:
    """One generation request's host-side state.

    ``timestamps`` records the phase transitions (monotonic seconds):
    ``submitted`` → ``prefill_start`` → ``first_token`` → ``finished``
    — the per-request span data the observability wiring exports and
    the iteration-level-batching integration test asserts on.

    ``trace_id`` is the request's DISTRIBUTED TRACE IDENTITY (ISSUE 5):
    unique per process lifetime, stamped on every tracer span/flow
    event, flight-recorder entry, ``/requestz`` row, and streamed token
    record this request produces, so one grep correlates a request
    across the Perfetto timeline, the metrics stream, and a postmortem
    bundle.  A caller-supplied ``trace_id`` (the router mints one per
    request BEFORE dispatch, ISSUE 7) survives the hop unchanged so
    router-side and replica-side spans merge into one Perfetto lane.

    ``forced`` holds prompt-suffix tokens a prefix-cache hit still owes
    the engine: the cached prefix's K/V was copied in, and the suffix
    is consumed one token per decode tick (each tick writes the
    consumed token's K/V row; its prediction is discarded until the
    LAST prompt token, whose prediction is the first generated token).
    """

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 deadline_t: Optional[float] = None,
                 on_token: Optional[Callable] = None,
                 trace_id: Optional[str] = None,
                 temperature: float = 0.0,
                 rng=None,
                 tenant: Optional[str] = None):
        self.id = next(Request._ids)
        # pid disambiguates across engine restarts on one box; the
        # counter disambiguates within the process
        self.trace_id = trace_id or f"req-{os.getpid():x}-{self.id:08x}"
        self.prompt = prompt
        self.prompt_len = len(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline_t = deadline_t      # absolute monotonic, or None
        self.on_token = on_token
        # sampling plumbing (ISSUE 9): the lm_generate rng contract —
        # ``temperature > 0`` requires an explicit per-request rng key
        # (a (2,) uint32 PRNGKey, normalized by the frontend); greedy
        # requests carry 0.0 and None.  Both ride the transfer wire
        # unchanged so a disaggregated decode worker samples the exact
        # tokens the fused engine would.
        self.temperature = float(temperature)
        self.rng = rng
        # multi-tenant QoS (ISSUE 11): the tenant this request bills to
        # (None = untagged).  Rides the fleet wire so worker-side
        # /requestz rows and shed payloads keep the attribution.
        self.tenant = None if tenant is None else str(tenant)
        self.tokens: List[int] = []       # generated tokens, in order
        self.status = "queued"            # queued|running|done|evicted
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None
        self.timestamps = {}
        self.done_event = threading.Event()
        # prefix-cache state (ISSUE 7): set at admission on a hit
        self.forced: Deque[int] = deque()  # prompt suffix still to feed
        self.prefix_entry = None           # pinned PrefixEntry, or None
        self.prefix_len = 0                # cached tokens skipped

    def finish(self, reason: str, now: float) -> None:
        self.status = "done" if reason in ("eos", "max_tokens") else "evicted"
        self.finish_reason = reason
        self.timestamps["finished"] = now
        self.slot = None
        self.done_event.set()


class Scheduler:
    """Admission queue + slot assignment policy (host state only; the
    caller owns the actual slot pool and engine).

    Thread-safe for ``submit`` against a driver thread calling
    ``expire_queued``/``admissions`` (one lock around the queue).
    """

    def __init__(self, queue_capacity: int, slot_capacity: int,
                 max_prefills_per_tick: int = 1,
                 max_positions: Optional[int] = None):
        if queue_capacity < 1:
            raise ValueError(f"queue_capacity must be >= 1, "
                             f"got {queue_capacity}")
        self.queue_capacity = int(queue_capacity)
        self.slot_capacity = int(slot_capacity)   # max_total per slot
        self.max_prefills_per_tick = max(int(max_prefills_per_tick), 1)
        self.max_positions = max_positions        # learned-pos table bound
        self._queue: Deque[Request] = deque()
        self._lock = threading.Lock()

    # ---- admission ----
    def submit(self, req: Request, now: float) -> None:
        """Enqueue or raise :class:`AdmissionError` (backpressure)."""
        total = req.prompt_len + req.max_new_tokens
        cap = self.slot_capacity
        if self.max_positions is not None:
            cap = min(cap, self.max_positions)
        if req.prompt_len < 1:
            raise AdmissionError("too_long", "empty prompt")
        if req.max_new_tokens < 1:
            raise AdmissionError("too_long", "max_new_tokens < 1")
        if total > cap:
            raise AdmissionError(
                "too_long",
                f"prompt {req.prompt_len} + max_new {req.max_new_tokens} "
                f"= {total} exceeds per-slot capacity {cap}")
        with self._lock:
            if len(self._queue) >= self.queue_capacity:
                raise AdmissionError(
                    "queue_full",
                    f"admission queue at capacity {self.queue_capacity}")
            req.timestamps["submitted"] = now
            self._queue.append(req)

    def expire_queued(self, now: float) -> List[Request]:
        """Drop queued requests whose deadline already passed (they could
        only ever return a too-late answer); returns them, finished with
        reason ``deadline``."""
        expired: List[Request] = []
        with self._lock:
            keep: Deque[Request] = deque()
            for req in self._queue:
                if req.deadline_t is not None and now >= req.deadline_t:
                    expired.append(req)
                else:
                    keep.append(req)
            self._queue = keep
        for req in expired:
            req.finish("deadline", now)
        return expired

    def admissions(self, free_slots: int, now: float) -> List[Request]:
        """Pop the FIFO-next requests to prefill this tick: at most
        ``min(free_slots, max_prefills_per_tick)``."""
        out: List[Request] = []
        n = min(int(free_slots), self.max_prefills_per_tick)
        with self._lock:
            while n > 0 and self._queue:
                out.append(self._queue.popleft())
                n -= 1
        return out

    def requeue_front(self, req: Request) -> None:
        """Put an already-admitted request back at the queue HEAD
        (FIFO preserved) when its slot fell through — e.g. a sibling
        admission's prefix hit pinned the cached slot this one was
        counting on scavenging, or a disaggregated transfer found no
        destination (ISSUE 9).  Bypasses the capacity check: the
        request was already accepted once and must not be re-rejected."""
        with self._lock:
            self._queue.appendleft(req)

    def drain(self) -> List[Request]:
        """Remove and return every queued request, FIFO order — the
        disagg router's dead-worker sweep re-dispatches (or sheds) a
        victim's queue through this instead of stranding the handles
        un-done forever."""
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
        return out

    # ---- eviction ----
    def eviction_reason(self, req: Request, now: float) -> Optional[str]:
        """Why ``req`` must leave its slot NOW, or None to keep decoding.
        Checked after every emitted token; precedence eos > max_tokens >
        deadline (an EOS on the final permitted token reports ``eos``)."""
        if req.eos_id is not None and req.tokens \
                and req.tokens[-1] == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_new_tokens:
            return "max_tokens"
        if req.deadline_t is not None and now >= req.deadline_t:
            return "deadline"
        return None

    # ---- introspection ----
    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def queued_requests(self) -> List[Request]:
        """Snapshot of the queue, FIFO order (the /requestz view)."""
        with self._lock:
            return list(self._queue)
