"""Host-RAM spill tier for evicted prefix-cache slabs (ISSUE 12).

The radix-trie prefix cache (``prefix_cache.py``) borrows DEVICE slots;
under admission pressure the LRU rc==0 entry is scavenged and its K/V —
often a hot shared system prompt that will be asked for again within
seconds — was simply freed.  This module is the middle rung of the KV
economy: on eviction the slab is packed (``transfer.py::pack``, the
same CRC-stamped ``chainermn_tpu.kv_transfer.v1`` payload the
cross-process transfer plane ships) into a BOUNDED host-RAM LRU store,
and a later prompt that prefixes a spilled sequence re-lands it through
the pool-lifetime compiled inject program
(``KvTransferPlane.unpack_into``) instead of re-prefilling.

Failure-domain discipline (the robustness contract):

* the store is **bounded** (``capacity_bytes``): inserting past the
  budget evicts LRU-first, and a payload larger than the whole budget
  is refused — the spill tier degrades, it never OOMs the host;
* every payload carries the pack-time **CRC32**; verification happens
  at restore (inside ``unpack_into``), and a corrupt slab is refused,
  counted, and the request falls back to a normal prefill — wrong KV
  is never served;
* the store holds opaque BYTES keyed by token sequences — jax-free,
  fuzzable standalone, and a lost/cleared store is always safe (the
  engine just re-prefills).

``match`` follows the trie's semantics: longest spilled sequence that
prefixes the prompt, capped at ``len(prompt) - 1`` (the last prompt
token must run live to produce the first generated token) and at the
spilled slab's own length.  Entry count is bounded by
``capacity_bytes / slab size``, so the linear scan is cheap (tens of
entries, host microseconds) — a trie would only complicate eviction.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple


class SpillEntry:
    """One spilled slab: ``seq[:length]``'s packed K/V payload."""

    __slots__ = ("seq", "length", "payload", "nbytes")

    def __init__(self, seq: Tuple[int, ...], length: int,
                 payload: bytes):
        self.seq = tuple(int(t) for t in seq)[: int(length)]
        self.length = int(length)
        self.payload = bytes(payload)
        self.nbytes = len(self.payload)


class HostSpillStore:
    """Bounded LRU host-RAM store of packed prefix slabs.

    ``on_evict(seq, length)`` fires when a spilled entry falls out of
    the budget (capacity pressure or explicit :meth:`drop`) — the fleet
    worker uses it to announce the FINAL eviction so the router's
    global index stops advertising a prefix nobody holds anymore.
    """

    def __init__(self, capacity_bytes: int = 64 << 20,
                 on_evict: Optional[Callable[[Tuple[int, ...], int],
                                             None]] = None):
        if capacity_bytes <= 0:
            raise ValueError(
                f"capacity_bytes must be positive, got {capacity_bytes} "
                f"(pass spill_bytes=0 at the ENGINE to disable the tier)")
        self.capacity_bytes = int(capacity_bytes)
        self.on_evict = on_evict
        self._lock = threading.Lock()
        # seq tuple -> entry, LRU order (oldest first)
        self._entries: "OrderedDict[Tuple[int, ...], SpillEntry]" = \
            OrderedDict()
        self._bytes = 0
        # counters (the lease/metrics/introspect surface)
        self.spills = 0
        self.restores = 0
        self.hits = 0
        self.misses = 0
        self.crc_refusals = 0
        self.evictions = 0
        self.rejected_oversize = 0

    # ---- insertion (the eviction path's spill) ----
    def put(self, seq, length: int, payload: bytes) -> bool:
        """Spill one packed slab; returns False when the payload alone
        exceeds the whole budget (refused, counted) — the caller frees
        the slot either way."""
        entry = SpillEntry(tuple(seq), length, payload)
        if entry.nbytes > self.capacity_bytes:
            with self._lock:
                self.rejected_oversize += 1
            return False
        evicted: List[SpillEntry] = []
        with self._lock:
            old = self._entries.pop(entry.seq, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.seq] = entry
            self._bytes += entry.nbytes
            self.spills += 1
            while self._bytes > self.capacity_bytes:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evictions += 1
                evicted.append(victim)
        if self.on_evict is not None:
            for victim in evicted:
                self.on_evict(victim.seq, victim.length)
        return True

    # ---- lookup ----
    @staticmethod
    def _common_len(a, b) -> int:
        n = min(len(a), len(b))
        for i in range(n):
            if a[i] != b[i]:
                return i
        return n

    def match(self, prompt, min_len: int = 2
              ) -> Optional[Tuple[Tuple[int, ...], int]]:
        """Longest spilled prefix of ``prompt``: ``(seq, match_len)``
        with ``seq[:match_len] == prompt[:match_len]``, capped at
        ``len(prompt) - 1`` and the entry's own length — or None.
        Counts hit/miss and refreshes the winner's LRU position."""
        prompt = tuple(int(t) for t in prompt)
        cap = len(prompt) - 1
        best: Optional[SpillEntry] = None
        best_len = 0
        with self._lock:
            for entry in self._entries.values():
                m = min(self._common_len(entry.seq, prompt), cap,
                        entry.length)
                if m > best_len:
                    best, best_len = entry, m
            if best is None or best_len < max(int(min_len), 1):
                self.misses += 1
                return None
            self.hits += 1
            self._entries.move_to_end(best.seq)
            return best.seq, best_len

    def covering(self, seq) -> Optional[bytes]:
        """Payload of a spilled entry whose sequence COVERS ``seq``
        (``entry.seq[:len(seq)] == seq``) — the remote-pull serving
        face: an owner whose device cache scavenged an announced prefix
        can still serve the pull from the spill tier."""
        seq = tuple(int(t) for t in seq)
        with self._lock:
            for entry in self._entries.values():
                if entry.length >= len(seq) \
                        and entry.seq[: len(seq)] == seq:
                    self._entries.move_to_end(entry.seq)
                    return entry.payload
        return None

    def get(self, seq) -> Optional[bytes]:
        """Exact-sequence payload lookup (the restore path re-reads the
        winner :meth:`match` named)."""
        seq = tuple(int(t) for t in seq)
        with self._lock:
            entry = self._entries.get(seq)
            if entry is None:
                return None
            self._entries.move_to_end(seq)
            return entry.payload

    def drop(self, seq) -> None:
        """Remove one entry (a restore that failed CRC must never be
        retried from the same corrupt bytes)."""
        seq = tuple(int(t) for t in seq)
        with self._lock:
            entry = self._entries.pop(seq, None)
            if entry is not None:
                self._bytes -= entry.nbytes
        if entry is not None and self.on_evict is not None:
            self.on_evict(entry.seq, entry.length)

    # ---- introspection ----
    @property
    def n_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_held(self) -> int:
        with self._lock:
            return self._bytes

    def entries(self) -> List[Tuple[Tuple[int, ...], int]]:
        with self._lock:
            return [(e.seq, e.length) for e in self._entries.values()]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "entries": float(len(self._entries)),
                "bytes": float(self._bytes),
                "capacity_bytes": float(self.capacity_bytes),
                "spills": float(self.spills),
                "restores": float(self.restores),
                "hits": float(self.hits),
                "misses": float(self.misses),
                "crc_refusals": float(self.crc_refusals),
                "evictions": float(self.evictions),
                "rejected_oversize": float(self.rejected_oversize),
            }

    def state(self) -> Dict[str, Any]:
        out = self.stats()
        out["lru"] = [list(seq[:8]) for seq, _ in self.entries()]
        return out
