"""Cross-process fleet router: dispatch, supervision, failover (ISSUE 10).

The layer that makes the PR 7/9 serving fleet survive a worker death:
N :class:`~chainermn_tpu.serving.worker.WorkerRuntime` processes (or
in-process runtimes over the loopback store — same protocol) behind ONE
router that owns three planes:

* **Dispatch** — ``submit()`` mirrors the request locally (the caller's
  :class:`~chainermn_tpu.serving.frontend.RequestHandle` reads the
  mirror), picks the least-loaded LIVE worker from its lease, and sends
  the request wire down the worker's control mailbox.  Tokens stream
  back as ``token`` messages; the terminal ``result`` message carries
  the authoritative token list.  Rejections ride the uniform
  :class:`~chainermn_tpu.serving.scheduler.AdmissionError` wire shape
  (reason + ``retry_after_ms`` + ``queue_depth``) via
  :class:`~chainermn_tpu.serving.router.RouterBase`.
* **Supervision** — :meth:`supervisor_tick` ages each worker's lease by
  RECEIVER time (epoch-aware: a zombie's stale-epoch lease never
  refreshes liveness, it is refused and counted by the
  :class:`~chainermn_tpu.serving.health.EpochFence`).  A worker whose
  current-epoch lease misses the detection window is marked dead: its
  epoch is fenced, a ``worker_lost`` flight bundle naming the worker
  and its lane is dumped, and its in-flight requests fail over.
  Re-admission of a flapping worker (fresh lease under a fenced epoch)
  is governed by the per-worker
  :class:`~chainermn_tpu.serving.health.CircuitBreaker` — exponential
  hold-off, bounded retry budget, then permanent removal.
* **Failover** — an in-flight request on a dead worker is re-dispatched
  to a survivor (a re-prefill; the survivor's own prefix cache salvages
  what it has cached — generation is deterministic per request rng, so
  the result stays token-exact vs an uninterrupted run) up to
  ``max_failover_attempts``, else shed machine-readably with reason
  ``worker_lost`` + ``retry_after_ms`` attached to the handle
  (``shed_payload``).  ``drain(worker)`` is the graceful inverse: stop
  admitting, let the worker finish in-flight, collect ``drained``, and
  the process exits 0 — the rolling-restart primitive the
  ``serving_chaos`` bench section measures.

Disaggregated topologies ride the same plane: prompts dispatch to
prefill workers, their ``slab_ready`` announcements route to the
decode worker with free (lease-reported) slots, and the ``install``
forward lands the slab through the decode worker's own loop.
"""

from __future__ import annotations

import os
import pickle
import random
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import observability as obs
from ..communicators.base import DcnLaneError
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability.slo import (GoodputLedger, ReservoirSample,
                                 SLOTracker, percentile_of)
from .fleet_cache import FleetCacheIndex
from .frontend import RequestHandle, _request_row
from .health import (CircuitBreaker, EpochFence, LeaseTable,
                     detection_window_s)
from .lanes import MailboxReceiver, MailboxSender
from .router import RouterBase
from .scheduler import AdmissionError, Request
from .transfer import slab_nbytes, transfer_cost
from .worker import ctl_mailbox, out_mailbox


def submit_with_retry(submit: Callable[..., Any], *args,
                      max_attempts: int = 4,
                      base_backoff_ms: float = 5.0,
                      max_backoff_ms: float = 2000.0,
                      jitter_frac: float = 0.25,
                      jitter_rng: Optional[random.Random] = None,
                      sleep: Callable[[float], None] = time.sleep,
                      **kwargs):
    """Client-side honor of ``retry_after_ms`` (ISSUE 10 satellite):
    call ``submit(*args, **kwargs)``; on :class:`AdmissionError` wait
    ``max(retry_after_ms, base_backoff_ms · 2^(attempt-1))`` (capped)
    with ±``jitter_frac`` uniform jitter — jitter prevents a shed burst
    from re-arriving as a synchronized thundering herd — and retry up
    to ``max_attempts`` total submits.  Gives up MACHINE-READABLY by
    re-raising the last :class:`AdmissionError` (its payload still
    carries reason/retry_after_ms/queue_depth).  Returns the handle on
    success.  ``**kwargs`` (incl. a sampling ``rng=``) pass through to
    ``submit`` untouched — the jitter source is ``jitter_rng``."""
    jitter_rng = jitter_rng or random.Random()
    attempt = 0
    while True:
        attempt += 1
        try:
            return submit(*args, **kwargs)
        except AdmissionError as e:
            if attempt >= int(max_attempts):
                raise
            backoff = min(base_backoff_ms * (2 ** (attempt - 1)),
                          max_backoff_ms)
            delay_ms = max(e.retry_after_ms or 0.0, backoff)
            delay_ms = min(delay_ms, max_backoff_ms)
            delay_ms *= 1.0 + jitter_frac * (2.0 * jitter_rng.random()
                                             - 1.0)
            sleep(max(delay_ms, 0.0) / 1e3)


class WorkerClient:
    """Router-side proxy of one worker: its mailboxes, lease view,
    breaker, and in-flight registry.  ``proc`` is the Popen when the
    worker is a real process (None for in-process runtimes)."""

    STATES = ("starting", "live", "draining", "drained", "dead")

    def __init__(self, name: str, role: str, store, *, epoch: int = 1,
                 lane_config=None, proc=None, breaker=None,
                 model_id: str = "default"):
        self.name = str(name)
        self.role = str(role)
        self.epoch = int(epoch)
        # heterogeneous-fleet identity (ISSUE 18): seeded at admission,
        # then ADOPTED from every admitted lease — the worker's claim
        # on the fenced wire outranks the router's construction-time
        # guess (same discipline as queue depth)
        self.model_id = str(model_id)
        self.weights_generation = 1
        self.sender = MailboxSender(store, ctl_mailbox(name), lane_config)
        self.receiver = MailboxReceiver(store, out_mailbox(name),
                                        lane_config)
        self.proc = proc
        self.breaker = breaker or CircuitBreaker()
        self.state = "starting"
        self.t_admitted = time.monotonic()
        # epoch-aware lease aging: (seq, t_seen) of the last NEW
        # current-epoch lease — a zombie's stale-epoch beats never land
        self.last_lease: Optional[Dict[str, Any]] = None
        self._lease_seq = -1
        self._lease_t = time.monotonic()
        self.sent_since_lease = 0      # dispatch-vs-stale-lease slack
        #: last lease seq the supervisor JUDGED (accepted or refused) —
        #: a persisting stale lease file is processed exactly once
        self.judged_seq = -1

    def observe_lease(self, lease: Dict[str, Any]) -> None:
        if int(lease["seq"]) != self._lease_seq:
            self._lease_seq = int(lease["seq"])
            self._lease_t = time.monotonic()
            self.last_lease = lease
            self.sent_since_lease = 0
            if lease.get("model_id"):
                self.model_id = str(lease["model_id"])
            if lease.get("weights_generation"):
                self.weights_generation = int(
                    lease["weights_generation"])

    def lease_age_s(self) -> float:
        """Seconds since the last NEW current-epoch lease (or since
        admission, before the first one)."""
        return time.monotonic() - self._lease_t

    def reset_lease_clock(self) -> None:
        self._lease_seq = -1
        self._lease_t = time.monotonic()
        self.last_lease = None


class FleetRouter(RouterBase):
    """Supervision + dispatch over cross-process workers.

    ``lease_window_s`` defaults to
    :func:`~chainermn_tpu.serving.health.detection_window_s`
    (``beat_interval_s``, ``miss_beats``) — the worst-case detection
    latency the chaos acceptance holds the router to.
    """

    ROLE = "fleet"

    def __init__(self, workers: Sequence[WorkerClient], store, *,
                 beat_interval_s: float = 0.05, miss_beats: int = 4,
                 lease_window_s: Optional[float] = None,
                 start_grace_s: float = 60.0,
                 max_failover_attempts: int = 2,
                 default_token_latency_ms: float = 20.0,
                 slo: Optional[SLOTracker] = None,
                 shed_burn_threshold: float = 1.0,
                 tenancy=None,
                 paid_burn_headroom: float = 2.0,
                 metrics_writer=None,
                 bundle_dir: Optional[str] = None,
                 lane_config=None,
                 stats_capacity: int = 1024,
                 enable_remote_pulls: bool = True,
                 pull_min_tokens: int = 4,
                 pull_cost_per_token: float = 0.25,
                 pull_timeout_s: float = 30.0,
                 orphan_sweep_interval_s: float = 1.0,
                 orphan_grace_s: float = 5.0):
        if not workers:
            raise ValueError("need at least one worker")
        names = [w.name for w in workers]
        if len(set(names)) != len(names):
            raise ValueError(f"worker names must be unique: {names}")
        super().__init__(
            metrics_writer=metrics_writer, tenancy=tenancy, slo=slo,
            shed_burn_threshold=shed_burn_threshold,
            paid_burn_headroom=paid_burn_headroom,
            default_token_latency_ms=default_token_latency_ms)
        self.workers: Dict[str, WorkerClient] = {w.name: w
                                                for w in workers}
        self.store = store
        self.beat_interval_s = float(beat_interval_s)
        self.lease_window_s = (
            detection_window_s(beat_interval_s, miss_beats)
            if lease_window_s is None else float(lease_window_s))
        self.start_grace_s = float(start_grace_s)
        self.max_failover_attempts = int(max_failover_attempts)
        self.bundle_dir = bundle_dir
        #: attached by serving.autoscale.FleetAutoscaler (ISSUE 11);
        #: step() then drives its control loop and the fleet_health
        #: provider carries its target-size/last-decision view
        self.autoscaler = None
        self.lane_config = lane_config
        self.fence = EpochFence()
        # the health.py read face: schema-checks every lease payload
        self._leases = LeaseTable(store, lane_config)
        self._last_supervise = 0.0
        # fleet-global KV economy (ISSUE 12): the soft-state prefix
        # index workers announce into, and the remote-pull pricing
        # knobs — a pull is chosen only when the prefill tokens it
        # saves beat its transfer price in the SAME token currency as
        # the affinity score (pull_cost_per_token = moving one token's
        # KV over the lane, priced relative to re-prefilling it)
        self.cache_index = FleetCacheIndex()
        self.enable_remote_pulls = bool(enable_remote_pulls)
        self.pull_min_tokens = int(pull_min_tokens)
        self.pull_cost_per_token = float(pull_cost_per_token)
        self.pull_timeout_s = float(pull_timeout_s)
        self._remote_pulls = 0
        self.last_pull_fault: Optional[Dict[str, Any]] = None
        # orphaned-slab sweep (ISSUE 12 satellite): a worker that died
        # between pack-publish and install-ack leaks its lane tag
        # forever without this — tags unowned by any in-flight request
        # for a full grace window are GC'd
        self.orphan_sweep_interval_s = float(orphan_sweep_interval_s)
        self.orphan_grace_s = float(orphan_grace_s)
        self._orphan_seen: Dict[str, float] = {}
        self._last_orphan_sweep = 0.0
        self._orphans_swept = 0
        for w in workers:
            # adopt the worker's pre-agreed first epoch (argv-passed)
            while (self.fence.current(w.name) or 0) < w.epoch:
                self.fence.new_epoch(w.name)
        # in-flight registry: trace_id -> {"req", "worker", "attempts"}
        self._inflight: Dict[str, Dict[str, Any]] = {}
        self._pending_slabs: deque = deque()   # disagg installs awaiting
        self._rr = 0
        self._dispatched = 0
        self._redispatched = 0
        self._shed_inflight = 0
        self._readmitted = 0
        self._tokens = 0
        self._results = 0
        self._t0 = time.monotonic()
        self._ttft_ms = ReservoirSample(int(stats_capacity))
        self._failover_ttft_ms = ReservoirSample(int(stats_capacity))
        self.last_detection: Optional[Dict[str, Any]] = None
        # supervision-plane wall partition (ISSUE 10 goodput bucket)
        self.goodput = GoodputLedger()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: set to the error string when the started router thread died
        #: — submit() then rejects machine-readably instead of
        #: accepting requests nobody will ever pump
        self._router_dead: Optional[str] = None
        _flight.register_provider("fleet_health", self.introspect_state)

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------
    def _submit_role(self) -> str:
        roles = {w.role for w in self.workers.values()}
        return "prefill" if "engine" not in roles else "engine"

    def _live(self, role: Optional[str] = None) -> List[WorkerClient]:
        # snapshot: the autoscaler's add_worker mutates the dict on the
        # router thread while submit threads iterate here
        return [w for w in list(self.workers.values())
                if w.state in ("starting", "live")
                and (role is None or w.role == role)]

    def _retry_after_ms(self) -> float:
        """Drain-aware back-off hint (ISSUE 11): the least-loaded live
        worker's queued tokens priced at the fleet's MEASURED recent
        tokens/s (clamped + jittered in ``derive_retry_after_ms``)."""
        live = self._live()
        if not live:
            return 1.0
        backlog = min(
            int((w.last_lease or {}).get("backlog_tokens", 0))
            for w in live)
        with self._lock:
            tokens = self._tokens
        return self._derive_retry_ms(backlog, tokens)

    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, temperature: float = 0.0,
               rng=None, tenant: Optional[str] = None,
               priority: Optional[str] = None,
               model_id: Optional[str] = None) -> RequestHandle:
        """Dispatch to the least-loaded live worker over its lane, or
        raise :class:`AdmissionError` with the uniform machine-readable
        payload.  ``tenant``/``priority`` bill the request to a tenant
        class (ISSUE 11): budgets, ladder clamping, and paid-first SLO
        protection key off them.  ``model_id`` pins the variant in a
        heterogeneous fleet (ISSUE 18): only workers serving it are
        candidates (and failover targets); None routes across ALL
        variants (the single-model fleet's behavior, unchanged)."""
        import numpy as np

        trace_id = self._mint_trace_id()
        temperature = float(temperature)
        if temperature > 0.0 and rng is None:
            raise ValueError(
                "temperature > 0 samples tokens and needs an explicit "
                "rng: pass jax.random.PRNGKey(...) (the lm_generate "
                "contract)")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if self._router_dead is not None:
            self._reject(
                "worker_lost", trace_id,
                f"fleet router thread died: {self._router_dead}",
                retry_after_ms=1.0, queue_depth=0)
        role = self._submit_role()
        live = self._live(role)
        if model_id is not None:
            live = [w for w in live if w.model_id == model_id]
        if not live:
            self._reject(
                "worker_lost" if model_id is None else "no_model_worker",
                trace_id,
                f"no live {role} worker in the fleet "
                + (f"serving model {model_id!r} "
                   if model_id is not None else "")
                + f"({len(self.workers)} registered)",
                retry_after_ms=1.0, queue_depth=0)
        depth_of = {}
        backlog_of = {}
        fleet_cap = 0
        for w in live:
            lease = w.last_lease or {}
            depth_of[w.name] = (int(lease.get("queue_depth", 0))
                                + w.sent_since_lease)
            backlog_of[w.name] = int(lease.get("backlog_tokens", 0))
            fleet_cap += int(lease.get("queue_capacity", 0))
        candidates = [
            w for w in live
            if depth_of[w.name] < int((w.last_lease or {}).get(
                "queue_capacity", 1 << 30))]
        fleet_depth = sum(depth_of.values())
        # tenant plane + the shared SLO-burn gate (ISSUE 11): budgets
        # and the pause rung refuse best-effort work with tenant+rung
        # attribution; the burn gate sheds best-effort at the base
        # threshold and paid only with paid_burn_headroom× more room
        tenant, max_new_tokens, capped = self._admit_tenant(
            trace_id, tenant, priority, max_new_tokens,
            queue_depth=fleet_depth, queue_capacity=fleet_cap,
            retry_after_ms=self._retry_after_ms)
        self._maybe_shed_slo(trace_id, fleet_depth,
                             self._retry_after_ms, tenant)
        if not candidates:
            self._reject(
                "queue_full", trace_id,
                f"all {len(live)} live {role}-worker queues at capacity",
                retry_after_ms=self._retry_after_ms(),
                queue_depth=fleet_depth, tenant=tenant)
        # least-loaded in TOKEN units (ISSUE 18): queue depth first
        # (requests are the admission currency), then the lease's
        # backlog_tokens (variants differ in per-request work — a small
        # model's worker drains its depth faster), then round-robin
        order = sorted(
            range(len(candidates)),
            key=lambda i: (depth_of[candidates[i].name],
                           backlog_of[candidates[i].name],
                           (i - self._rr) % len(candidates)))
        wc = candidates[order[0]]
        self._rr = (self._rr + 1) % max(len(candidates), 1)

        now = time.monotonic()
        key = (None if rng is None
               else np.asarray(rng, np.uint32).reshape(2))
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      deadline_t=(now + deadline_s
                                  if deadline_s is not None else None),
                      on_token=on_token, trace_id=trace_id,
                      temperature=temperature, rng=key, tenant=tenant)
        req.status = "running"   # mirror: the worker owns queueing
        req.timestamps["submitted"] = now
        self._stamp_tenant_meta(req, tenant)
        entry = {"req": req, "worker": wc.name, "attempts": 1,
                 "model_id": wc.model_id}
        # fleet KV economy (ISSUE 12): a local miss with a remote hit
        # may be worth PULLING the prefix slab instead of re-prefilling
        # — decided here, in token units, before anything is sent
        pull = self._plan_pull(wc, prompt, trace_id)
        if pull is not None:
            entry["pull"] = dict(pull, attempts=1, state="requested",
                                 t0=now)
        with self._lock:
            # registration and the death handler's strand snapshot
            # share this lock, so every accepted request is either in
            # that snapshot (and shed) or refused here — none slips
            # through to hang
            dead = self._router_dead
            if dead is None:
                self._inflight[trace_id] = entry
                self._dispatched += 1
                # locked with its peers: submit threads, the supervisor
                # (failover, lease reset) all read-modify-write this
                wc.sent_since_lease += 1
        if dead is not None:
            self._reject(
                "worker_lost", trace_id,
                f"fleet router thread died: {dead}",
                retry_after_ms=1.0, queue_depth=0, tenant=tenant)
        # the registration event anchors the request's causal story
        # (ISSUE 17): every accepted entry journals exactly one
        # "submitted" before any dispatch/pull/failover touches it
        _flight.note("fleet", event="submitted", trace_id=trace_id,
                     worker=wc.name, tenant=tenant)
        if pull is not None:
            # the pull path holds the submit back until the prefix
            # lands (or the pull degrades): the owner packs the slab,
            # the destination installs it into its own prefix cache,
            # and only then does the request dispatch — so its
            # admission is a plain local hit, never a re-prefill race
            owner_wc = self.workers.get(pull["owner"])
            try:
                self._send_cache_pull(owner_wc, req, pull)
            except Exception as e:  # noqa: BLE001 — a broken OWNER
                # lane must not reject the caller: degrade to plain
                # dispatch on the chosen worker, counted.  Pop-or-bail:
                # a supervisor running _cancel_pulls_on between the
                # registration and this send may have ALREADY resolved
                # the pull and dispatched the request — re-sending here
                # would run the same trace twice on the worker
                with self._lock:
                    owned_pull = entry.pop("pull", None)
                if owned_pull is None:
                    _flight.note("fleet",
                                 event="pull_send_superseded",
                                 trace_id=trace_id, error=str(e))
                    if self.tenancy is not None and tenant is not None:
                        self.tenancy.on_admit(
                            self.tenancy.resolve(tenant), req,
                            capped=capped)
                    obs.async_event("b", "request", trace_id,
                                    cat="serving_request",
                                    request=req.id,
                                    prompt_len=req.prompt_len)
                    return RequestHandle(req)
                self.cache_index.count_stale("owner_lane")
                _flight.note("fleet", event="remote_pull_fallback",
                             trace_id=trace_id, reason="owner_lane",
                             owner=pull["owner"], error=str(e))
                pull = None
            else:
                _flight.note("fleet", event="remote_pull_requested",
                             trace_id=trace_id, owner=pull["owner"],
                             dst=wc.name, prefix_len=pull["length"],
                             gain_tokens=pull["gain"],
                             price_tokens=round(pull["price_tokens"], 2),
                             ledger_bytes=pull["ledger_bytes"])
                if self.tenancy is not None and tenant is not None:
                    self.tenancy.on_admit(self.tenancy.resolve(tenant),
                                          req, capped=capped)
                obs.async_event("b", "request", trace_id,
                                cat="serving_request", request=req.id,
                                prompt_len=req.prompt_len)
                return RequestHandle(req)
        try:
            self._send_submit(wc, req)
        except Exception as e:  # noqa: BLE001 — no half-registered state
            with self._lock:
                # roll back ONLY while we still own the entry: a long
                # retrying send can lose the race to the supervisor's
                # orphan sweep, which may have already failed the entry
                # over to a survivor (or shed it) — popping it then
                # would orphan the redispatched request's result
                cur = self._inflight.get(trace_id)
                owned = (cur is entry and entry["attempts"] == 1
                         and entry["worker"] == wc.name)
                if owned:
                    self._inflight.pop(trace_id, None)
                    # never dispatched: rolling both back keeps the
                    # offered count (dispatched + rejected) at one per
                    # request and the worker's estimated depth honest
                    self._dispatched -= 1
                    wc.sent_since_lease = max(
                        wc.sent_since_lease - 1, 0)
            if not owned:
                _flight.note("fleet", event="submit_send_superseded",
                             trace_id=trace_id, error=str(e))
                if self.tenancy is not None and tenant is not None:
                    self.tenancy.on_admit(self.tenancy.resolve(tenant),
                                          req, capped=capped)
                return RequestHandle(req)
            if isinstance(e, DcnLaneError):
                # the uniform machine-readable rejection instead of a
                # raw lane fault: the caller can submit_with_retry it
                # (tenant attribution rides like every other reject)
                self._reject(
                    "worker_lost", trace_id,
                    f"control-lane send to worker {wc.name} failed "
                    f"permanently: {e}",
                    retry_after_ms=self._retry_after_ms(),
                    queue_depth=fleet_depth, tenant=tenant)
            raise
        # tracked only once the send stuck (a rejected submit must not
        # occupy the tenant's inflight budget with a phantom forever)
        if self.tenancy is not None and tenant is not None:
            self.tenancy.on_admit(self.tenancy.resolve(tenant), req,
                                  capped=capped)
        obs.async_event("b", "request", trace_id, cat="serving_request",
                        request=req.id, prompt_len=req.prompt_len)
        _flight.note("fleet", event="dispatched", trace_id=trace_id,
                     worker=wc.name)
        return RequestHandle(req)

    def _wire(self, req: Request) -> Dict[str, Any]:
        import numpy as np

        now = time.monotonic()
        return {
            "trace_id": req.trace_id,
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "eos_id": req.eos_id,
            "deadline_rel_s": (None if req.deadline_t is None
                               else max(req.deadline_t - now, 0.0)),
            "temperature": float(req.temperature),
            "rng": (None if req.rng is None
                    else [int(x) for x in np.asarray(req.rng)
                          .reshape(2)]),
            "tenant": req.tenant,
        }

    def _send_submit(self, wc: WorkerClient, req: Request) -> None:
        wc.sender.send({"kind": "submit", "epoch": wc.epoch,
                        "req": self._wire(req)})

    # ------------------------------------------------------------------
    # fleet KV economy: remote prefix pulls (ISSUE 12)
    # ------------------------------------------------------------------
    def _plan_pull(self, wc: WorkerClient, prompt,
                   trace_id: str) -> Optional[Dict[str, Any]]:
        """Transfer-vs-re-prefill decision, in token units.  The gain
        is the prefill tokens a pull saves (remote match beyond the
        local match); the price is the transfer's wire cost converted
        through ``pull_cost_per_token`` (what moving one token's KV
        over the lane costs relative to recomputing it) via the SAME
        ``transfer_cost`` statics the ledger reconciles against.
        Returns the pull plan, or None for plain dispatch."""
        if not self.enable_remote_pulls or wc.role != "engine":
            return None
        # model-keyed claims (ISSUE 18): only same-variant slabs are
        # candidates — the index counts the cross-model near-miss
        # under stale_fallbacks/model_mismatch
        live = {w.name for w in self._live("engine")
                if w.model_id == wc.model_id}
        rec, best_len = self.cache_index.match(prompt, workers=live,
                                               model_id=wc.model_id)
        if rec is None:
            return None
        local_len = self.cache_index.match_for(wc.name, prompt)
        if rec.worker == wc.name or best_len <= local_len:
            return None     # the local cache is already as good
        gain = best_len - local_len
        geom = rec.geom or {}
        # slab-geometry key, belt to the model_id braces: a claim whose
        # layer/kv/dtype shape disagrees with the DESTINATION's lease
        # geometry would install garbage — counted, refused, re-prefill
        dst_geom = (wc.last_lease or {}).get("geom")
        if geom and dst_geom and any(
                geom.get(k) != dst_geom.get(k)
                for k in ("n_layers", "kv_dim", "dtype")):
            self.cache_index.count_stale("geometry_mismatch")
            return None
        ledger_bytes = None
        if geom:
            cost = transfer_cost(geom["n_layers"], best_len,
                                 geom["kv_dim"], geom["dtype"],
                                 mode="lanes")
            ledger_bytes = cost["ledger_bytes"]
            per_token = max(slab_nbytes(geom["n_layers"], 1,
                                        geom["kv_dim"], geom["dtype"]),
                            1)
            price_tokens = (self.pull_cost_per_token
                            * ledger_bytes / per_token)
        else:
            # geometry never announced (old worker): price by rows
            price_tokens = self.pull_cost_per_token * best_len
        if gain < self.pull_min_tokens or gain <= price_tokens:
            return None
        return {"owner": rec.worker,
                "seq": [int(t) for t in prompt[:best_len]],
                # the index record's own key: a stale nack must drop
                # the CLAIM that matched, not the (shorter) pull prefix
                "rec_seq": list(rec.seq),
                "length": int(best_len), "local_len": int(local_len),
                "gain": int(gain), "price_tokens": float(price_tokens),
                "ledger_bytes": ledger_bytes,
                "tag": f"pfx/{trace_id}"}

    def _send_cache_pull(self, owner_wc: WorkerClient, req: Request,
                         pull: Dict[str, Any]) -> None:
        if owner_wc is None or owner_wc.state not in ("starting", "live"):
            raise RuntimeError(
                f"slab owner {pull['owner']} is not live")
        owner_wc.sender.send({"kind": "cache_pull",
                              "epoch": owner_wc.epoch,
                              "trace_id": req.trace_id,
                              "prefix": pull["seq"],
                              "length": pull["length"],
                              "tag": pull["tag"]})

    def _pull_fallback(self, entry: Dict[str, Any], reason: str,
                       detail: str, *, lane=None, worker=None,
                       fault: bool = False) -> None:
        """The counted degrade-to-re-prefill path — every way a pull
        can fail funnels here: pop the pull, count per reason, dump a
        ``remote_pull_fault`` bundle naming worker+lane on the fault
        reasons, GC the slab tag, and dispatch the request normally to
        its already-chosen worker (failover owns it from there if even
        that fails).  Done-XOR-shed holds throughout: the entry never
        leaves ``_inflight`` here."""
        req = entry["req"]
        with self._lock:
            pull = entry.pop("pull", None)
        if pull is None:
            return    # already resolved (installed, or raced a failover)
        self.cache_index.count_stale(reason)
        _flight.note("fleet", event="remote_pull_fallback",
                     trace_id=req.trace_id, reason=reason,
                     detail=detail,
                     **({"worker": worker} if worker else {}),
                     **({"lane": lane} if lane else {}))
        if fault:
            detection = {"trace_id": req.trace_id, "reason": reason,
                         "detail": detail, "worker": worker,
                         "lane": lane, "owner": pull["owner"],
                         "dst": entry["worker"],
                         "prefix_len": pull["length"]}
            self.last_pull_fault = detection
            _flight.note("fleet", event="remote_pull_fault", **detection)
            if self.bundle_dir:
                _flight.dump_bundle(
                    self.bundle_dir, "remote_pull_fault",
                    extra={"remote_pull_fault": detection})
        self._gc_slab(pull["tag"])
        wc = self.workers.get(entry["worker"])
        if wc is not None and wc.state in ("starting", "live"):
            try:
                self._send_submit(wc, req)
                _flight.note("fleet", event="dispatched",
                             trace_id=req.trace_id, worker=wc.name,
                             after_pull_fallback=reason)
                return
            except Exception as e:  # noqa: BLE001
                detail = (f"{detail}; fallback submit to {wc.name} "
                          f"failed: {e}")
        self._failover(entry, f"remote pull fell back ({reason}): "
                              f"{detail}")

    def _cancel_pulls_on(self, worker: str, why: str,
                         fault: bool = True) -> None:
        """A dead/drained worker can never serve its pending pulls:
        resolve every in-flight pull it owns to the counted fallback
        (the mid-pull owner-death failure domain — chaos-proven by
        SIGKILLing the slab owner)."""
        with self._lock:
            affected = [e for e in self._inflight.values()
                        if e.get("pull") is not None
                        and e["pull"]["owner"] == worker]
        for entry in affected:
            self._pull_fallback(
                entry, "owner_lost", f"slab owner {worker} {why}",
                worker=worker,
                lane=f"worker_lane/{out_mailbox(worker)}/recv",
                fault=fault)

    def _check_pull_deadlines(self, now: float) -> None:
        """Backstop: a pull neither completed nor failed within
        ``pull_timeout_s`` (e.g. a silently wedged owner the lease
        window has not caught yet) degrades instead of wedging the
        request forever."""
        with self._lock:
            stuck = [e for e in self._inflight.values()
                     if e.get("pull") is not None
                     and now - e["pull"]["t0"] > self.pull_timeout_s]
        for entry in stuck:
            self._pull_fallback(
                entry, "timeout",
                f"pull did not complete within {self.pull_timeout_s}s")

    def _on_cache_announce(self, wc: WorkerClient,
                           msg: Dict[str, Any]) -> None:
        op = str(msg.get("op"))
        if op == "insert":
            self.cache_index.insert(wc.name, wc.epoch, msg["prefix"],
                                    msg["length"],
                                    geom=msg.get("geom"))
        elif op == "evict":
            if msg.get("spilled"):
                # device slot scavenged but the slab spilled to host
                # RAM: still pullable, just from the colder tier
                self.cache_index.demote(wc.name, msg["prefix"])
            else:
                # tier-scoped when the announce says so (a spill-store
                # eviction must not drop a re-donated HOT claim)
                self.cache_index.evict(wc.name, msg["prefix"],
                                       tier=msg.get("tier"))
        elif op == "snapshot":
            self.cache_index.snapshot(wc.name, wc.epoch,
                                      msg.get("entries") or [],
                                      geom=msg.get("geom"))
        else:
            _flight.note("fleet", event="unknown_cache_announce",
                         worker=wc.name, op=op)

    def _live_pull(self, entry, wc_name: Optional[str] = None,
                   owner: Optional[str] = None):
        """The entry's pull iff it is still the CURRENT attempt's (a
        failover since the request left supersedes every pull message
        still in flight)."""
        if entry is None:
            return None
        pull = entry.get("pull")
        if pull is None or pull["attempts"] != entry["attempts"]:
            return None
        if owner is not None and pull["owner"] != owner:
            return None
        if wc_name is not None and entry["worker"] != wc_name:
            return None
        return pull

    def _on_cache_slab_ready(self, wc: WorkerClient,
                             msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        pull = self._live_pull(entry, owner=wc.name)
        if pull is None or pull.get("state") != "requested":
            self._gc_slab(msg.get("tag"))
            return
        pull["state"] = "installing"
        dst = self.workers.get(entry["worker"])
        if dst is None or dst.state not in ("starting", "live"):
            # the destination died since; its failover owns the request
            self._gc_slab(msg.get("tag"))
            return
        try:
            dst.sender.send({"kind": "install_prefix",
                             "epoch": dst.epoch,
                             "trace_id": msg["trace_id"],
                             "tag": msg["tag"],
                             "length": msg.get("length")})
        except Exception as e:  # noqa: BLE001
            self._pull_fallback(
                entry, "dst_lane",
                f"install_prefix send to {dst.name} failed: {e}",
                worker=dst.name,
                lane=f"worker_lane/{ctl_mailbox(dst.name)}/send",
                fault=isinstance(e, DcnLaneError))

    def _on_cache_pull_nack(self, wc: WorkerClient,
                            msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        pull = self._live_pull(entry, owner=wc.name)
        if pull is None:
            self._gc_slab(msg.get("tag"))
            return
        reason = str(msg.get("reason"))
        if reason == "stale":
            # evicted since the announce: drop the claim so the next
            # submit does not re-plan the same dead pull
            self.cache_index.evict(wc.name,
                                   pull.get("rec_seq") or pull["seq"])
        self._pull_fallback(
            entry, reason,
            f"owner {wc.name} nacked the pull: {reason}",
            worker=wc.name, lane=msg.get("lane"),
            fault=(reason == "publish_fault"))

    def _on_prefix_installed(self, wc: WorkerClient,
                             msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        pull = self._live_pull(entry, wc_name=wc.name)
        if pull is None:
            return
        with self._lock:
            entry.pop("pull", None)
            self._remote_pulls += 1
        req = entry["req"]
        _flight.note("fleet", event="remote_pull_done",
                     trace_id=req.trace_id, owner=pull["owner"],
                     dst=wc.name, prefix_len=pull["length"],
                     pull_ms=round((time.monotonic() - pull["t0"]) * 1e3,
                                   2))
        try:
            self._send_submit(wc, req)
        except Exception as e:  # noqa: BLE001
            self._failover(entry, f"submit after remote pull to "
                                  f"{wc.name} failed: {e}")

    def _on_prefix_nack(self, wc: WorkerClient,
                        msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        pull = self._live_pull(entry, wc_name=wc.name)
        if pull is None:
            self._gc_slab(msg.get("tag"))
            return
        reason = str(msg.get("reason"))
        self._pull_fallback(
            entry, reason,
            f"destination {wc.name} could not land the prefix slab: "
            f"{reason}",
            worker=wc.name, lane=msg.get("lane"),
            fault=(reason == "lane_fault"))

    # ------------------------------------------------------------------
    # pump: worker -> router messages
    # ------------------------------------------------------------------
    def pump(self) -> int:
        """Drain every worker's outbox; returns messages handled.
        Every message is fence-gated: a stale epoch (zombie, or a
        fenced worker's buffered sends) is refused and counted — the
        zombie-fencing acceptance."""
        handled = 0
        for wc in list(self.workers.values()):
            for msg in wc.receiver.drain():
                handled += 1
                kind = str(msg.get("kind"))
                if kind == "drained":
                    # always honored: the drain handshake ends the
                    # worker's life, fenced or not
                    self._on_drained(wc)
                    continue
                if not self.fence.admit(wc.name, msg.get("epoch", -1),
                                        kind):
                    _flight.note("fleet", event="fenced_refusal",
                                 worker=wc.name, msg_kind=kind,
                                 msg_epoch=msg.get("epoch"))
                    continue
                if kind == "token":
                    self._on_token(msg)
                elif kind == "result":
                    self._on_result(wc, msg)
                elif kind == "shed":
                    self._on_shed(wc, msg)
                elif kind == "slab_ready":
                    entry = self._entry(msg.get("trace_id"))
                    if entry is None:
                        self._gc_slab(msg.get("tag"))
                    else:
                        entry["slab_src"] = wc.name
                        self._pending_slabs.append(
                            {"msg": msg, "src": wc.name,
                             "attempts": entry["attempts"]})
                elif kind == "install_ok":
                    pass   # ownership already moved at forward time
                elif kind == "install_nack":
                    self._on_install_nack(wc, msg)
                elif kind == "cache_announce":
                    self._on_cache_announce(wc, msg)
                elif kind == "cache_slab_ready":
                    self._on_cache_slab_ready(wc, msg)
                elif kind == "cache_pull_nack":
                    self._on_cache_pull_nack(wc, msg)
                elif kind == "prefix_installed":
                    self._on_prefix_installed(wc, msg)
                elif kind == "prefix_nack":
                    self._on_prefix_nack(wc, msg)
                else:
                    _flight.note("fleet", event="unknown_msg",
                                 worker=wc.name, msg_kind=kind)
        self._route_pending_slabs()
        return handled

    def _entry(self, trace_id) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._inflight.get(trace_id)

    def _on_token(self, msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        if entry is None or entry["worker"] != msg.get("worker"):
            return   # late stream from a superseded dispatch
        req = entry["req"]
        tok = int(msg["token"])
        req.tokens.append(tok)
        now = time.monotonic()
        if "first_token" not in req.timestamps:
            req.timestamps["first_token"] = now
            ttft = (now - req.timestamps.get("submitted", now)) * 1e3
            with self._lock:
                self._ttft_ms.add(ttft)
                if entry["attempts"] > 1:
                    self._failover_ttft_ms.add(ttft)
            if self.slo is not None:
                self.slo.observe_ttft(ttft)
            if self.tenancy is not None:
                self.tenancy.on_ttft(req.tenant, ttft)
        with self._lock:
            self._tokens += 1
        if req.on_token is not None:
            req.on_token(tok, req.id)

    def _on_result(self, wc: WorkerClient, msg: Dict[str, Any]) -> None:
        trace_id = msg.get("trace_id")
        entry = self._entry(trace_id)
        if entry is None or entry["worker"] != wc.name:
            _flight.note("fleet", event="orphan_result", worker=wc.name,
                         trace_id=trace_id)
            return
        req = entry["req"]
        now = time.monotonic()
        # the result's token list is AUTHORITATIVE (streamed tokens are
        # hints that may trail it by a message or two)
        req.tokens = [int(t) for t in msg.get("tokens", [])]
        if req.tokens and "first_token" not in req.timestamps:
            req.timestamps["first_token"] = now
        req.finish(msg.get("finish_reason") or "max_tokens", now)
        if self.tenancy is not None:
            # the authoritative token list bills the tenant (streamed
            # token messages are latency hints that may trail it)
            self.tenancy.on_tokens(req.tenant, len(req.tokens))
        with self._lock:
            self._inflight.pop(trace_id, None)
            self._results += 1
        obs.async_event("e", "request", trace_id, cat="serving_request",
                        reason=req.finish_reason,
                        n_tokens=len(req.tokens))
        _flight.note("fleet", event="finished", trace_id=trace_id,
                     worker=wc.name, reason=req.finish_reason)

    def _on_shed(self, wc: WorkerClient, msg: Dict[str, Any]) -> None:
        """The worker refused an already-dispatched request (admission
        race, drain overlap, prefill error): fail it over like a death
        would, bounded by the same attempt budget."""
        entry = self._entry(msg.get("trace_id"))
        if entry is None or entry["worker"] != wc.name:
            return
        self._failover(entry, f"worker {wc.name} shed: "
                              f"{msg.get('payload', {}).get('reason')}")

    # ---- disagg: slab routing ----
    def _gc_slab(self, tag) -> None:
        """Best-effort GC of an orphaned slab tag (shed / superseded by
        a failover re-prefill) so it never sits in the lane store
        forever; a delete fault must not hurt the router."""
        if not tag:
            return
        try:
            self.store.delete(tag)
        except Exception:  # noqa: BLE001
            pass

    def _route_pending_slabs(self) -> None:
        """Forward announced slabs to decode workers with free
        (lease-reported) slots; slabs with no destination stay pending
        (slots free up — the supervisor tick retries)."""
        still: deque = deque()
        while self._pending_slabs:
            item = self._pending_slabs.popleft()
            msg = item["msg"]
            entry = self._entry(msg.get("trace_id"))
            if entry is None or entry["attempts"] != item["attempts"]:
                # shed, or failed over SINCE the announce: the request
                # was re-dispatched (a fresh re-prefill will produce its
                # own slab) — forwarding this one would install a
                # DUPLICATE generation for the same trace
                self._gc_slab(msg.get("tag"))
                continue
            decodes = [w for w in self._live("decode")
                       if int((w.last_lease or {}).get("free_slots", 0))
                       > 0]
            if not decodes:
                still.append(item)
                continue
            dw = max(decodes,
                     key=lambda w: int(w.last_lease.get("free_slots", 0)))
            dw.last_lease["free_slots"] = (
                int(dw.last_lease.get("free_slots", 1)) - 1)
            entry["worker"] = dw.name   # decode side owns it now
            dw.sender.send({"kind": "install", "epoch": dw.epoch,
                            "trace_id": msg["trace_id"],
                            "tag": msg["tag"],
                            "length": msg.get("length"),
                            "meta": msg.get("meta")})
            _flight.note("fleet", event="slab_routed",
                         trace_id=msg["trace_id"], src=item["src"],
                         dst=dw.name)
        self._pending_slabs = still

    #: install nacks tolerated per request before the slab is given up
    #: on and the request re-prefills (a decode worker whose lease
    #: over-reports free slots could otherwise nack forever).
    MAX_INSTALL_NACKS = 3

    def _on_install_nack(self, wc: WorkerClient,
                         msg: Dict[str, Any]) -> None:
        entry = self._entry(msg.get("trace_id"))
        if entry is None:
            self._gc_slab(msg.get("tag"))
            return
        nacks = entry.get("install_nacks", 0) + 1
        entry["install_nacks"] = nacks
        if msg.get("reason") == "no_free_slot" \
                and nacks <= self.MAX_INSTALL_NACKS:
            # transient: back to the pending queue for another worker /
            # a later round (ownership reverts to routing limbo)
            self._pending_slabs.append(
                {"msg": {"trace_id": msg["trace_id"],
                         "tag": msg.get("tag"),
                         "length": msg.get("length"),
                         "meta": msg.get("meta")},
                 "src": entry.get("slab_src", entry["worker"]),
                 "attempts": entry["attempts"]})
            return
        # lane fault / nack budget spent: the slab is unusable —
        # re-prefill on a survivor (failover bumps attempts, so any
        # copy still pending is dropped and GC'd by the router)
        self._gc_slab(msg.get("tag"))
        self._failover(entry, f"decode worker {wc.name} could not land "
                              f"slab: {msg.get('reason')} "
                              f"({nacks} nack(s))")

    # ------------------------------------------------------------------
    # supervision
    # ------------------------------------------------------------------
    def supervisor_tick(self) -> None:
        """One health sweep: epoch-aware lease aging, death detection
        within the configured window, zombie refusal, breaker-governed
        re-admission."""
        with self.goodput.measure("supervise"):
            self._supervise()

    def _supervise(self) -> None:
        now = time.monotonic()
        # lease files refresh only every beat interval — polling them on
        # the 2ms dispatch loop would be >95% wasted I/O booked straight
        # into the supervise bucket it exists to measure honestly
        if now - self._last_supervise < self.beat_interval_s / 2.0:
            return
        self._last_supervise = now
        for wc in list(self.workers.values()):
            if wc.state in ("drained",):
                continue
            try:
                lease = self._leases.read(wc.name)
            except ValueError as e:      # foreign/corrupt lease payload
                _flight.note("fleet", event="lease_refused",
                             worker=wc.name, error=str(e))
                lease = None
            # process each published seq ONCE: a dead worker's lease
            # file persists (nothing deletes it at SIGKILL), and
            # re-judging the same stale payload every poll would both
            # inflate the fenced_refusals counters with wall-clock time
            # and re-admit the corpse — only a NEW beat (a resumed
            # zombie, a recovered flapper) is evidence of life
            if lease is not None \
                    and int(lease.get("seq", -1)) != wc.judged_seq:
                wc.judged_seq = int(lease.get("seq", -1))
                admitted = self.fence.admit(
                    wc.name, lease.get("epoch", -1), "lease")
                # merge the beat's HLC: the publisher's write
                # happens-before this judgment in the fleet timeline,
                # and the admitted flag is what conformance replays
                # against the lease_fence model (ISSUE 17)
                _journal.recv_emit(
                    lease.get("hlc"), "lease_judged", worker=wc.name,
                    epoch=lease.get("epoch"), lseq=wc.judged_seq,
                    admitted=admitted)
                if admitted:
                    with self._lock:   # resets sent_since_lease, which
                        # submit threads increment under the same lock
                        wc.observe_lease(lease)
                    if wc.state == "starting":
                        wc.state = "live"
                        wc.breaker.record_success()
                elif wc.state == "dead":
                    # a fenced worker is beating AGAIN: re-admission is
                    # the breaker's call
                    if wc.breaker.allow():
                        self._readmit(wc)
            if wc.state in ("live", "draining"):
                window = self.lease_window_s
                if wc.lease_age_s() > window:
                    self._mark_dead(
                        wc, f"missed lease window ({window:.3f}s)")
            elif wc.state == "starting":
                if now - wc.t_admitted > self.start_grace_s:
                    self._mark_dead(
                        wc, f"never published a lease within the "
                            f"start grace ({self.start_grace_s}s)")
        self._sweep_orphaned_inflight()
        self._check_pull_deadlines(now)
        self._sweep_orphan_tags(now)

    def _sweep_orphan_tags(self, now: float) -> None:
        """Periodic lane-dir sweep (ISSUE 12 satellite): a worker that
        died between publishing a slab (``slab/``/``pfx/`` tag) and the
        install-ack leaks the tag forever — only the CAUGHT fault path
        GC'd before this.  A tag owned by no in-flight request for a
        full ``orphan_grace_s`` window is deleted; the grace window
        keeps a tag published a beat before its announce arrives from
        being swept out from under a live transfer."""
        if now - self._last_orphan_sweep < self.orphan_sweep_interval_s:
            return
        self._last_orphan_sweep = now
        tags_fn = getattr(self.store, "tags", None)
        if tags_fn is None:
            return
        try:
            tags = tags_fn()
        except Exception as e:  # noqa: BLE001 — a sweep must never
            # hurt the supervisor
            _flight.note("fleet", event="orphan_sweep_failed",
                         error=str(e))
            return
        with self._lock:
            live = set(self._inflight)
        present = set()
        for tag in tags:
            if not (tag.startswith("slab/") or tag.startswith("pfx/")):
                continue
            present.add(tag)
            trace_id = tag.split("/", 1)[1]
            if trace_id in live:
                self._orphan_seen.pop(tag, None)
                continue
            t0 = self._orphan_seen.setdefault(tag, now)
            if now - t0 >= self.orphan_grace_s:
                self._gc_slab(tag)
                self._orphan_seen.pop(tag, None)
                self._orphans_swept += 1
                _flight.note("fleet", event="orphan_slab_swept",
                             tag=tag)
        for tag in list(self._orphan_seen):
            if tag not in present:
                self._orphan_seen.pop(tag, None)

    def _sweep_orphaned_inflight(self) -> None:
        """Fail over in-flight entries owned by a dead/drained worker.

        Closes the submit/_mark_dead TOCTOU: a client thread can
        snapshot a live worker, lose the race to the supervisor (which
        enumerates ``_inflight`` for failover BEFORE the entry exists),
        and then register+send to the corpse — without this sweep that
        request would hang forever with its worker never re-judged.
        Runs on the supervisor thread only, like every other
        ``_failover`` call site, so an entry cannot be failed over
        twice concurrently."""
        dead_states = ("dead", "drained")
        with self._lock:
            orphans = [
                e for e in self._inflight.values()
                if getattr(self.workers.get(e["worker"]), "state", None)
                in dead_states]
        for entry in orphans:
            wc = self.workers[entry["worker"]]
            self._failover(
                entry, f"dispatch raced worker {wc.name} going "
                       f"{wc.state} (orphan sweep)")

    def _readmit(self, wc: WorkerClient) -> None:
        wc.epoch = self.fence.new_epoch(wc.name)
        wc.state = "live"
        wc.reset_lease_clock()
        with self._lock:
            self._readmitted += 1
        wc.sender.send({"kind": "hello", "epoch": wc.epoch})
        _flight.note("fleet", event="readmitted", worker=wc.name,
                     epoch=wc.epoch,
                     breaker=wc.breaker.state())

    def _mark_dead(self, wc: WorkerClient, why: str) -> None:
        """Death: fence, fail over every in-flight request, evidence."""
        age = wc.lease_age_s()
        wc.state = "dead"
        self.fence.fence(wc.name)
        wc.breaker.record_failure()
        # the fleet cache index is SOFT state of this corpse: drop
        # every entry for the fenced epoch in one sweep, and resolve
        # every pull it owed to the counted re-prefill fallback (the
        # mid-pull owner-death failure domain, ISSUE 12)
        self.cache_index.drop_worker(wc.name)
        self._cancel_pulls_on(wc.name, f"died ({why})")
        lane = f"worker_lane/{out_mailbox(wc.name)}/recv"
        detection = {
            "worker": wc.name,
            "role": wc.role,
            "lane": lane,
            "why": why,
            "lease_age_s": round(age, 4),
            "detection_window_s": round(self.lease_window_s, 4),
            "epoch_fenced": self.fence.current(wc.name),
        }
        # the detection note goes down BEFORE the failover sweep: the
        # causal journal must show worker_lost happens-before every
        # redispatched/shed it triggers, or the conformance replay
        # (observability/conform.py) sees a failover of a worker the
        # router never declared dead
        _flight.note("fleet", event="worker_lost", **detection)
        outcomes = []
        with self._lock:
            owned = [e for e in self._inflight.values()
                     if e["worker"] == wc.name]
        for entry in owned:
            outcomes.append(self._failover(entry, why))
        detection["in_flight"] = outcomes
        self.last_detection = detection
        if self.bundle_dir:
            _flight.dump_bundle(self.bundle_dir, "worker_lost",
                                extra={"worker_lost": detection})

    def _failover(self, entry: Dict[str, Any], why: str) -> Dict[str, Any]:
        """Re-dispatch one in-flight request to a survivor, or shed it
        machine-readably; returns the outcome row the bundle records."""
        req = entry["req"]
        role = self._submit_role()
        mid = entry.get("model_id")
        survivors = [w for w in self._live(role)
                     if w.name != entry["worker"]
                     and (mid is None or w.model_id == mid)]
        with self._lock:
            # ownership test + attempts bump are ATOMIC with the
            # submit-path rollback's (membership, attempts==1) check:
            # either the rollback pops first and we bail here, or we
            # bump first and the rollback sees a disowned entry — a
            # half-raced entry can never be both rejected to its caller
            # AND redispatched to a survivor
            if self._inflight.get(req.trace_id) is not entry:
                return {"trace_id": req.trace_id,
                        "outcome": "already_resolved"}
            redispatch = bool(
                survivors
                and entry["attempts"] < 1 + self.max_failover_attempts)
            if redispatch:
                entry["attempts"] += 1
        if redispatch:
            entry["install_nacks"] = 0     # fresh budget per attempt
            # any slab the dead attempt published is superseded by the
            # re-prefill; drop it from the lane store (no-op for
            # engine-role fleets — they publish no slabs), and any
            # pending prefix pull is superseded too (its messages are
            # refused by the attempts stamp)
            with self._lock:
                entry.pop("pull", None)
            self._gc_slab(f"slab/{req.trace_id}")
            self._gc_slab(f"pfx/{req.trace_id}")
            # deterministic re-generation: reset streamed state, keep
            # the original submit stamp so the failover TTFT penalty is
            # measured end to end
            req.tokens = []
            req.timestamps.pop("first_token", None)
            # least-loaded first, but a failed send must not shed while
            # a healthy survivor remains untried — and an unhandled
            # raise here would kill the supervisor/router thread and
            # silently wedge the WHOLE fleet (no pump, no detection)
            order = sorted(
                survivors,
                key=lambda w: int((w.last_lease or {}).get(
                    "queue_depth", 0)) + w.sent_since_lease)
            for wc in order:
                with self._lock:
                    entry["worker"] = wc.name
                    wc.sent_since_lease += 1
                try:
                    self._send_submit(wc, req)
                except Exception as e:  # noqa: BLE001
                    # un-dispatch: keep this survivor's depth estimate
                    # honest (mirrors the submit-path rollback)
                    with self._lock:
                        wc.sent_since_lease = max(
                            wc.sent_since_lease - 1, 0)
                    _flight.note("fleet", event="failover_send_failed",
                                 trace_id=req.trace_id, to=wc.name,
                                 error=str(e))
                    why = (f"{why}; re-dispatch send to {wc.name} "
                           f"failed: {e}")
                    continue
                with self._lock:
                    self._redispatched += 1
                _flight.note("fleet", event="redispatched",
                             trace_id=req.trace_id, to=wc.name,
                             attempt=entry["attempts"], why=why)
                return {"trace_id": req.trace_id,
                        "outcome": "redispatched", "to": wc.name}
        return self._shed_entry(
            entry,
            f"{why}; not re-dispatched ({entry['attempts']} attempt(s) "
            f"used, {len(survivors)} survivor(s))")

    def _shed_entry(self, entry: Dict[str, Any],
                    why: str) -> Dict[str, Any]:
        """Terminal machine-readable shed of one in-flight entry (the
        no-re-dispatch half of :meth:`_failover`, also called directly
        when re-dispatch is pointless — e.g. the router thread died and
        nobody will ever pump a result again)."""
        req = entry["req"]
        with self._lock:
            # claim-or-bail: a concurrent submit rollback may have
            # already resolved this entry to its caller — shedding it
            # again would finish the request twice and double-count
            if self._inflight.get(req.trace_id) is not entry:
                return {"trace_id": req.trace_id,
                        "outcome": "already_resolved"}
            self._inflight.pop(req.trace_id)
            self._rejected["worker_lost"] = \
                self._rejected.get("worker_lost", 0) + 1
            self._shed_inflight += 1
        if self.tenancy is not None:
            self.tenancy.count_shed(req.tenant, "worker_lost")
        shed = AdmissionError(
            "worker_lost", why,
            retry_after_ms=self._retry_after_ms(),
            queue_depth=sum(
                int((w.last_lease or {}).get("queue_depth", 0))
                for w in self._live()),
            tenant=req.tenant,
            rung=(None if self.tenancy is None
                  else self.tenancy.ladder.rung))
        req.shed_payload = shed.to_dict()
        req.finish("shed", time.monotonic())
        self._gc_slab(f"slab/{req.trace_id}")
        self._gc_slab(f"pfx/{req.trace_id}")
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                dict(reason="worker_lost", trace_id=req.trace_id,
                     **{f"fleet/{k}": v for k, v in shed.to_dict().items()
                        if not isinstance(v, str)}),
                kind="fleet_shed")
        _flight.note("fleet", event="shed", trace_id=req.trace_id,
                     payload=req.shed_payload)
        obs.async_event("e", "request", req.trace_id,
                        cat="serving_request", reason="shed",
                        n_tokens=0)
        return {"trace_id": req.trace_id, "outcome": "shed"}

    # ---- drain: the graceful rolling-restart half ----
    def drain(self, worker: str) -> None:
        """Stop admitting to ``worker`` and ask it to finish in-flight
        work, release its lease, and exit 0.  :meth:`pump` collects the
        ``drained`` handshake; :meth:`wait_drained` blocks on it."""
        wc = self.workers[worker]
        wc.state = "draining"
        wc.sender.send({"kind": "drain"})
        _flight.note("fleet", event="drain_requested", worker=worker)

    def _on_drained(self, wc: WorkerClient) -> None:
        wc.state = "drained"
        self.fence.fence(wc.name)   # nothing further may land
        self.cache_index.drop_worker(wc.name)
        self._cancel_pulls_on(wc.name, "drained", fault=False)
        _flight.note("fleet", event="drained", worker=wc.name)
        if self.bundle_dir:
            _flight.dump_bundle(
                self.bundle_dir, "drain",
                extra={"drain": {
                    "worker": wc.name, "role": wc.role,
                    "lane": f"worker_lane/{out_mailbox(wc.name)}/recv",
                    "lease_age_s": round(wc.lease_age_s(), 4),
                    "in_flight": [],      # drained == nothing shed
                }})

    def wait_drained(self, worker: str, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            self.step()
            if self.workers[worker].state == "drained":
                return True
            time.sleep(0.005)
        return False

    def add_worker(self, wc: WorkerClient) -> None:
        """Admit a replacement worker (the second half of a rolling
        restart)."""
        if wc.name in self.workers:
            raise ValueError(f"worker name {wc.name!r} already "
                             f"registered (restarted workers need fresh "
                             f"names — their mailbox cursors died with "
                             f"the old process)")
        while (self.fence.current(wc.name) or 0) < wc.epoch:
            self.fence.new_epoch(wc.name)
        self.workers[wc.name] = wc

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------
    def step(self) -> int:
        """One router round: pump worker messages, the supervisor
        tick, then the autoscaler's control loop when one is attached
        (ISSUE 11) — the router's driver thread IS the supervisor
        thread the autoscale policy runs on."""
        handled = self.pump()
        self.supervisor_tick()
        if self.autoscaler is not None:
            self.autoscaler.maybe_tick()
        return handled

    def start(self, poll_s: float = 0.002) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            try:
                while not self._stop.is_set():
                    if self.step() == 0:
                        time.sleep(poll_s)
            except BaseException as e:  # noqa: BLE001
                # the PR 9 driver discipline: a dying router thread is
                # LOUD and BOUNDED — note + bundle + stop flag, every
                # in-flight request shed machine-readably (nobody will
                # ever pump a result again) and further submits
                # rejected, never a silent half-wedged fleet with
                # callers blocking forever
                err = f"{type(e).__name__}: {e}"
                self._stop.set()
                _flight.note("fleet", event="router_thread_death",
                             error=err)
                with self._lock:
                    # same lock as submit's registration: every
                    # accepted entry is in this snapshot, every
                    # later submit sees the flag and rejects
                    self._router_dead = err
                    stranded = list(self._inflight.values())
                for entry in stranded:
                    try:
                        self._shed_entry(
                            entry, f"fleet router thread died: {err}")
                    except Exception:  # noqa: BLE001 — PER-ENTRY
                        pass  # best-effort: one failing shed must not
                        #     strand every remaining caller
                if self.bundle_dir:
                    _flight.dump_bundle(
                        self.bundle_dir, "fleet_router_death",
                        extra={"error": err})
                raise

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-router")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def shutdown(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Stop every live worker (``stop`` message; processes reaped
        with their exit codes) and the driver thread."""
        self.stop()
        for wc in self.workers.values():
            if wc.state not in ("dead", "drained"):
                try:
                    wc.sender.send({"kind": "stop"})
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
        codes = {}
        deadline = time.monotonic() + float(timeout_s)
        for wc in self.workers.values():
            if wc.proc is None:
                continue
            left = max(deadline - time.monotonic(), 0.1)
            try:
                codes[wc.name] = wc.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                wc.proc.kill()
                codes[wc.name] = wc.proc.wait()
        return codes

    def close(self) -> None:
        self.stop()
        if _flight._PROVIDERS.get("fleet_health") == self.introspect_state:
            _flight.unregister_provider("fleet_health")

    @property
    def busy(self) -> bool:
        with self._lock:
            if self._inflight or self._pending_slabs:
                return True
        return any(
            int((w.last_lease or {}).get("queue_depth", 0))
            + int((w.last_lease or {}).get("busy_slots", 0)) > 0
            for w in self._live())

    # ------------------------------------------------------------------
    # metrics / introspection
    # ------------------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        """Fleet summary under ``fleet/*``: liveness, dispatch/failover
        counters, fencing refusals, detection latency — the
        ``serving_chaos`` bench section's source.  ``*_ms``/``shed``/
        ``rejected``/``refus`` keys gate lower-is-better."""
        with self._lock:
            rejected = dict(self._rejected)
            dispatched = self._dispatched
            redispatched = self._redispatched
            shed_inflight = self._shed_inflight
            readmitted = self._readmitted
            tokens = self._tokens
            ttft = self._ttft_ms.values()
            fttft = self._failover_ttft_ms.values()
        states = [w.state for w in self.workers.values()]
        out: Dict[str, float] = {
            "fleet/workers": float(len(states)),
            "fleet/live_workers": float(
                sum(s in ("starting", "live") for s in states)),
            "fleet/dead_workers": float(states.count("dead")),
            "fleet/drained_workers": float(states.count("drained")),
            "fleet/dispatched_total": float(dispatched),
            "fleet/redispatched_total": float(redispatched),
            "fleet/shed_inflight_total": float(shed_inflight),
            "fleet/readmitted_total": float(readmitted),
            "fleet/rejected_total": float(sum(rejected.values())),
            "fleet/tokens_total": float(tokens),
            "fleet/tokens_per_sec": tokens / max(
                time.monotonic() - self._t0, 1e-9),
        }
        for reason, n in sorted(rejected.items()):
            out[f"fleet/rejected/{reason}"] = float(n)
        for kind, n in sorted(self.fence.refusal_counts().items()):
            out[f"fleet/fenced_refusals/{kind}"] = float(n)
        # fleet KV economy (ISSUE 12): index + pull counters, plus the
        # worker-side spill/restore/CRC counters aggregated from the
        # leases (the workers count their own refusals; the router
        # never double-books them)
        idx = self.cache_index
        out["fleet/cache/index_entries"] = float(idx.n_entries)
        out["fleet/cache/hits"] = float(idx.hits)
        out["fleet/cache/misses"] = float(idx.misses)
        with self._lock:
            out["fleet/cache/remote_pulls"] = float(self._remote_pulls)
        stale = dict(idx.stale_fallbacks)
        out["fleet/cache/stale_fallbacks"] = float(sum(stale.values()))
        for reason, n in sorted(stale.items()):
            out[f"fleet/cache/stale_fallbacks/{reason}"] = float(n)
        out["fleet/cache/orphan_tags_swept"] = float(self._orphans_swept)
        agg = {"spills": 0, "restores": 0, "crc_refusals": 0,
               "prefill_calls": 0, "pull_serves": 0, "pull_installs": 0}
        for w in self.workers.values():
            c = (w.last_lease or {}).get("cache") or {}
            for k in agg:
                agg[k] += int(c.get(k, 0))
        for k, v in agg.items():
            out[f"fleet/cache/{k}"] = float(v)
        offered = dispatched + sum(rejected.values()) - shed_inflight
        out["fleet/shed_rate"] = (
            sum(rejected.values()) / offered if offered else 0.0)
        if ttft:
            out["fleet/ttft_p50_ms"] = percentile_of(ttft, 50)
            out["fleet/ttft_p99_ms"] = percentile_of(ttft, 99)
        if fttft:
            out["fleet/failover_ttft_p99_ms"] = percentile_of(fttft, 99)
        if self.last_detection is not None:
            out["fleet/detection_ms"] = round(
                self.last_detection["lease_age_s"] * 1e3, 3)
        out.update(self.goodput.gauges("fleet/goodput"))
        if self.tenancy is not None:
            out.update(self.tenancy.metrics())
        if self.autoscaler is not None:
            out.update(self.autoscaler.metrics())
        return out

    def reset_stats(self) -> None:
        with self._lock:
            self._dispatched = 0
            self._redispatched = 0
            self._shed_inflight = 0
            self._readmitted = 0
            self._tokens = 0
            self._results = 0
            self._t0 = time.monotonic()
            self._rejected = {r: 0 for r in self._rejected}
            self._remote_pulls = 0
            self._orphans_swept = 0
            self._ttft_ms = ReservoirSample(self._ttft_ms.capacity)
            self._failover_ttft_ms = ReservoirSample(
                self._failover_ttft_ms.capacity)
        # one epoch for every cache-economy rate counter: warm-up
        # hits/misses/stale fallbacks must not leak into the measured
        # window the bench gates on
        self.cache_index.reset_counters()
        self.goodput.reset()

    def requests_table(self) -> Dict[str, Any]:
        with self._lock:
            rows = [_request_row(e["req"])
                    for e in self._inflight.values()]
        return {"schema": "chainermn_tpu.requestz.v1",
                "fleet": True, "in_flight": rows}

    def introspect_state(self) -> Dict[str, Any]:
        """The ``fleet_health`` flight/statusz provider: per-worker
        liveness, lease age, epoch, breaker state, and the supervision
        counters — the first thing a fleet postmortem reads."""
        with self._lock:
            inflight_by: Dict[str, int] = {}
            for e in self._inflight.values():
                inflight_by[e["worker"]] = \
                    inflight_by.get(e["worker"], 0) + 1
            state: Dict[str, Any] = {
                "dispatched": self._dispatched,
                "redispatched": self._redispatched,
                "shed_inflight": self._shed_inflight,
                "readmitted": self._readmitted,
                "rejected": dict(self._rejected),
                "in_flight": len(self._inflight),
                "pending_slabs": len(self._pending_slabs),
            }
        state["lease_window_s"] = self.lease_window_s
        state["fenced_refusals"] = self.fence.refusal_counts()
        state["last_detection"] = self.last_detection
        # the fleet cache-index block (ISSUE 12): who claims which
        # prefixes at which tier, pull counters, and the last pull
        # fault — what a KV-economy postmortem reads first
        with self._lock:
            remote_pulls = self._remote_pulls
            pending_pulls = sum(
                1 for e in self._inflight.values() if "pull" in e)
        state["cache_index"] = dict(
            self.cache_index.state(),
            remote_pulls=remote_pulls,
            pending_pulls=pending_pulls,
            orphan_tags_swept=self._orphans_swept,
            last_pull_fault=self.last_pull_fault)
        # the autoscaler's view (ISSUE 11 satellite): live /statusz and
        # the flight bundle agree on WHY the fleet is its current size
        # — target per role, last decision + reason, and every tenant's
        # budget consumption
        if self.autoscaler is not None:
            state["autoscale"] = self.autoscaler.state()
        if self.tenancy is not None:
            state["tenancy"] = self.tenancy.state()
        state["workers"] = {
            w.name: {
                "role": w.role,
                "state": w.state,
                "epoch": w.epoch,
                "lease_age_s": round(w.lease_age_s(), 4),
                "breaker": w.breaker.state(),
                "in_flight": inflight_by.get(w.name, 0),
                "lease": w.last_lease,
            }
            for w in self.workers.values()}
        return state

    def finalize_metrics(self) -> None:
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(),
                                      kind="fleet_summary")

    def write_prometheus(self, path: str) -> str:
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())


# ---------------------------------------------------------------------------
# fleet construction
# ---------------------------------------------------------------------------

def write_params_file(path: str, params, *, head_dim: int,
                      **worker_kwargs) -> str:
    """Pickle the worker-build spec (host numpy params + engine kwargs)
    for the process entry (``python -m chainermn_tpu.serving.worker``)."""
    import jax
    import numpy as np

    spec = dict(worker_kwargs, head_dim=int(head_dim),
                params=jax.tree_util.tree_map(np.asarray, params))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(spec, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    return path


def spawn_worker(lane_dir: str, params_file: str, name: str, role: str,
                 *, epoch: int = 1, beat_interval_s: float = 0.05,
                 bundle_dir: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 stdout=None) -> subprocess.Popen:
    """Exec one worker process (detached role loop over the file
    lanes)."""
    cmd = [sys.executable, "-m", "chainermn_tpu.serving.worker",
           "--name", name, "--role", role, "--lane-dir", lane_dir,
           "--params", params_file, "--epoch", str(epoch),
           "--beat-interval-s", str(beat_interval_s)]
    if bundle_dir:
        cmd += ["--bundle-dir", bundle_dir]
    if journal_dir:
        cmd += ["--journal-dir", journal_dir]
    penv = dict(os.environ)
    penv.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        penv.update(env)
    if stdout is None:
        # keep the PARENT's stdout clean (the serve CLI's summary JSON
        # lives there); worker stderr inherits so crashes stay visible
        return subprocess.Popen(cmd, env=penv,
                                stdout=subprocess.DEVNULL)
    return subprocess.Popen(cmd, env=penv, stdout=stdout,
                            stderr=subprocess.STDOUT)


def _resolve_topology(topology, registry):
    """Normalize ``{role: count-or-[model_id, ...]}`` to per-worker
    ``(role, index, model_id-or-None)`` rows.  A model_id list needs a
    :class:`~chainermn_tpu.serving.models.ModelRegistry` (ISSUE 18 —
    the heterogeneous fleet); a plain int keeps the homogeneous
    behavior byte-for-byte."""
    rows = []
    for role, count in topology.items():
        if isinstance(count, int):
            rows += [(role, i, None) for i in range(count)]
            continue
        if registry is None:
            raise ValueError(
                f"topology role {role!r} lists model_ids {count!r} "
                f"but no registry= was given")
        for i, mid in enumerate(count):
            registry.get(mid)      # refuse unknown ids up front
            rows.append((role, i, str(mid)))
    return rows


def build_proc_fleet(params, topology: Dict[str, Any], lane_dir: str, *,
                     head_dim: Optional[int] = None,
                     beat_interval_s: float = 0.05,
                     miss_beats: int = 4,
                     bundle_dir: Optional[str] = None,
                     journal_dir: Optional[str] = None,
                     worker_kwargs: Optional[Dict[str, Any]] = None,
                     registry=None,
                     env: Optional[Dict[str, str]] = None,
                     **router_kwargs) -> FleetRouter:
    """Spawn and wire a cross-process gang: ``topology`` maps role →
    count (``{"engine": N}`` for ``serve --fleet-procs N``,
    ``{"prefill": P, "decode": D}`` for ``--disagg P:D --procs``) OR
    role → list of model_ids resolved through ``registry`` (ISSUE 18:
    a heterogeneous fleet — each worker loads ITS variant's params
    from a per-variant pickle, and ``params``/``head_dim`` may be
    None).  The caller drives :meth:`FleetRouter.step` (or
    ``start()``) and finishes with :meth:`FleetRouter.shutdown`.
    ``journal_dir`` turns on the causal HLC journal (ISSUE 17) in the
    router process AND every spawned worker — merge with
    :func:`~chainermn_tpu.observability.journal.merge_journals`."""
    from .lanes import FileLaneStore

    os.makedirs(lane_dir, exist_ok=True)
    if journal_dir:
        _journal.configure(journal_dir, "router")
    rows = _resolve_topology(topology, registry)
    params_files: Dict[Optional[str], str] = {}
    for _, _, mid in rows:
        if mid in params_files:
            continue
        if mid is None:
            if params is None or head_dim is None:
                raise ValueError("int topology counts need params= "
                                 "and head_dim=")
            params_files[None] = write_params_file(
                os.path.join(lane_dir, "fleet_params.pkl"), params,
                head_dim=head_dim, **(worker_kwargs or {}))
        else:
            var = registry.get(mid)
            params_files[mid] = write_params_file(
                os.path.join(lane_dir, f"fleet_params.{mid}.pkl"),
                var.params, head_dim=var.head_dim,
                model_id=var.model_id,
                weights_generation=var.generation,
                **dict(worker_kwargs or {}, **var.worker_kwargs))
    store = FileLaneStore(lane_dir)
    clients = []
    for role, i, mid in rows:
        name = f"{role}{i}" if mid is None else f"{role}.{mid}.{i}"
        proc = spawn_worker(lane_dir, params_files[mid], name, role,
                            epoch=1, beat_interval_s=beat_interval_s,
                            bundle_dir=bundle_dir,
                            journal_dir=journal_dir, env=env)
        clients.append(WorkerClient(name, role, store, epoch=1,
                                    proc=proc,
                                    model_id=mid or "default"))
    return FleetRouter(clients, store,
                       beat_interval_s=beat_interval_s,
                       miss_beats=miss_beats, bundle_dir=bundle_dir,
                       **router_kwargs)


def build_local_fleet(params, topology: Dict[str, Any], *,
                      head_dim: Optional[int] = None, store=None,
                      beat_interval_s: float = 0.02, miss_beats: int = 4,
                      bundle_dir: Optional[str] = None,
                      worker_kwargs: Optional[Dict[str, Any]] = None,
                      registry=None,
                      **router_kwargs):
    """In-process twin of :func:`build_proc_fleet` over the loopback
    store: returns ``(router, runtimes)`` with every worker a
    :class:`~chainermn_tpu.serving.worker.WorkerRuntime` the caller
    steps (or drives on threads).  Same protocol, same fault
    discipline — the fast-tier tests and the ``serving_chaos`` bench
    exercise the real lanes/fencing/failover code without process
    spawn cost.  ``topology`` role values may be model_id lists
    resolved through ``registry`` (heterogeneous fleet, ISSUE 18)."""
    from .transfer import InProcessLaneStore
    from .worker import WorkerRuntime

    store = store or InProcessLaneStore()
    runtimes, clients = [], []
    for role, i, mid in _resolve_topology(topology, registry):
        if mid is None:
            if params is None or head_dim is None:
                raise ValueError("int topology counts need params= "
                                 "and head_dim=")
            name = f"{role}{i}"
            rt = WorkerRuntime(
                name, role, params, store, head_dim=head_dim, epoch=1,
                beat_interval_s=beat_interval_s,
                **(worker_kwargs or {}))
        else:
            var = registry.get(mid)
            name = f"{role}.{mid}.{i}"
            rt = WorkerRuntime(
                name, role, var.params, store,
                head_dim=var.head_dim, epoch=1,
                beat_interval_s=beat_interval_s,
                model_id=var.model_id,
                weights_generation=var.generation,
                **dict(worker_kwargs or {}, **var.worker_kwargs))
        # leases flow even when the caller steps the loop manually
        # (a first-prefill compile blocks a step for seconds —
        # without the side thread that reads as a missed window);
        # kill() still silences the thread, preserving the chaos
        # semantics
        rt.start_heartbeat()
        runtimes.append(rt)
        clients.append(WorkerClient(name, role, store, epoch=1,
                                    model_id=mid or "default"))
    router = FleetRouter(clients, store,
                         beat_interval_s=beat_interval_s,
                         miss_beats=miss_beats, bundle_dir=bundle_dir,
                         **router_kwargs)
    return router, runtimes


def rolling_upgrade(router: FleetRouter, runtimes: List[Any],
                    checkpoint_shards, src_layout, *,
                    generation: int, head_dim: int,
                    model_id: Optional[str] = None,
                    worker_kwargs: Optional[Dict[str, Any]] = None,
                    beat_interval_s: Optional[float] = None,
                    timeout_s: float = 60.0) -> Dict[str, Any]:
    """Install a new checkpoint generation across a LIVE fleet with
    zero restart and zero shed (ISSUE 18 tentpole b).

    The checkpoint arrives as its saved host shards; ``reshard_host``
    (the portable-redistribution primitive, arxiv 2112.01075 / PR 8)
    re-partitions them to each worker's layout with the documented
    exactness contract — so the installed weights are bit-identical to
    the checkpoint however it was sharded, and a pinned greedy request
    decodes token-exactly across the upgrade when the values match.

    Per target engine worker (oldest generation first, one at a time):

    1. spawn the replacement with the NEW params and
       ``weights_generation=generation`` under a FRESH name (mailbox
       cursors die with the old incarnation — the rolling-restart
       rule) and admit it via :meth:`FleetRouter.add_worker`;
    2. wait until its lease makes it ``live`` — capacity never dips,
       which is what makes the shed-free guarantee structural rather
       than lucky;
    3. ``drain`` the old worker and wait for the drained handshake
       (in-flight work finishes on the old weights; nothing is shed —
       the PR 10/11 drain discipline).

    In-process fleets only (``runtimes`` of
    :class:`~chainermn_tpu.serving.worker.WorkerRuntime`): each
    replacement runs on a daemon thread and is appended to
    ``runtimes``.  Safe with a started router thread (the same
    concurrent-``step`` contract as :meth:`FleetRouter.wait_drained`).
    Returns ``{generation, upgraded: [{old, new}...], drain_shed,
    rejected_delta}`` — the acceptance gates ``drain_shed == 0``.
    """
    import threading as _threading

    from ..parallel.reshard import reshard_host
    from .worker import WorkerRuntime

    new_params = reshard_host(list(checkpoint_shards), src_layout,
                              None, 1)[0]
    targets = [w for w in router.workers.values()
               if w.role == "engine" and w.state in ("starting", "live")
               and (model_id is None or w.model_id == model_id)
               and w.weights_generation < int(generation)]
    if not targets:
        raise ValueError(
            f"rolling_upgrade: no live engine worker below generation "
            f"{generation}"
            + (f" for model {model_id!r}" if model_id else ""))
    targets.sort(key=lambda w: (w.weights_generation, w.name))
    m0 = router.metrics()
    upgraded = []
    for old in targets:
        new_name = f"{old.name}.g{int(generation)}"
        rt = WorkerRuntime(
            new_name, old.role, new_params, router.store,
            head_dim=int(head_dim), epoch=1,
            beat_interval_s=(router.beat_interval_s
                             if beat_interval_s is None
                             else float(beat_interval_s)),
            model_id=old.model_id,
            weights_generation=int(generation),
            **(worker_kwargs or {}))
        _threading.Thread(target=rt.run, daemon=True,
                          name=f"upgrade-{new_name}").start()
        runtimes.append(rt)
        router.add_worker(WorkerClient(new_name, old.role, router.store,
                                       epoch=1, lane_config=router.lane_config,
                                       model_id=old.model_id))
        deadline = time.monotonic() + float(timeout_s)
        while router.workers[new_name].state != "live":
            router.step()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rolling_upgrade: replacement {new_name} not live "
                    f"within {timeout_s}s")
            time.sleep(0.005)
        router.drain(old.name)
        if not router.wait_drained(old.name, timeout_s=timeout_s):
            raise TimeoutError(
                f"rolling_upgrade: {old.name} not drained within "
                f"{timeout_s}s")
        upgraded.append({"old": old.name, "new": new_name})
        _flight.note("fleet", event="weights_upgraded", old=old.name,
                     new=new_name, generation=int(generation))
    m1 = router.metrics()
    return {
        "generation": int(generation),
        "upgraded": upgraded,
        "drain_shed": int(m1.get("fleet/shed_inflight_total", 0)
                          - m0.get("fleet/shed_inflight_total", 0)),
        "rejected_delta": int(m1.get("fleet/rejected_total", 0)
                              - m0.get("fleet/rejected_total", 0)),
    }
