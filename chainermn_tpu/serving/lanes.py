"""Cross-process object lanes + mailboxes for the serving fleet.

ISSUE 10's worker processes speak to the router over the hardened
object lanes (``communicators/base.py::lane_call`` retry + transient/
permanent classification, faults NAMING the lane) — but the
jax.distributed KV store that backs ``XlaCommunicator
.kv_lane_transport()`` needs every process inside ONE fixed-size
distributed runtime, which is exactly the wrong shape for an elastic
serving fleet whose whole point is that members die, drain, and join
independently.  :class:`FileLaneStore` is the elastic wire: the same
``put(tag, bytes) / get(tag, timeout_s) / delete(tag)`` face over a
shared directory, usable by UNRELATED processes (atomic tmp-then-rename
publishes, so a reader sees a payload completely or not at all — the
flight-bundle discipline applied to the wire).  A multi-controller
deployment swaps the communicator-backed store in without touching the
protocol above it.

On top of any lane store, :class:`MailboxSender`/:class:`MailboxReceiver`
make an ordered, at-most-once message channel: every mailbox has exactly
ONE writer OBJECT (the fleet wiring guarantees it: the router writes
each worker's control inbox, each worker writes its own outbox), so a
sender-side sequence counter + receiver-side cursor give total order
without collectives — the sender serializes its own threads (router
client threads and the supervisor share one control-mailbox sender)
under a local lock.  Messages are pickled dicts stamped with
``MSG_SCHEMA`` — a receiver refuses a payload it cannot interpret,
never guesses.  Every store operation goes through :func:`lane_call`,
so retries/backoff/fault-injection ride the PR 8 discipline and a
permanent fault raises :class:`~chainermn_tpu.communicators.base
.DcnLaneError` naming ``worker_lane/<mailbox>/<op>``.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
import time
from typing import Any, Dict, Optional

#: Wire schema of one mailbox message (bump on layout change).
MSG_SCHEMA = "chainermn_tpu.worker_lane.v1"


def _safe_tag(tag: str) -> str:
    """Filesystem-safe INJECTIVE encoding of a lane tag (tags use '/'
    and '.').  ASCII alnum and '-.' pass verbatim; everything else —
    including '_', the escape lead, and non-ASCII — becomes fixed-width
    per-UTF-8-byte '_XX' escapes.  Fixed width matters: a variable-
    length escape (f'_{ord(c):x}') has no terminator, so 'a\\u263a'
    ('_263a') would alias 'a&3a' ('_26' + '3a') — caller-supplied
    worker names must never make two distinct mailboxes/leases share
    one lane file."""
    return "".join(
        c if (c.isascii() and c.isalnum()) or c in "-." else
        "".join(f"_{b:02x}" for b in c.encode("utf-8"))
        for c in str(tag))


def _unsafe_tag(name: str) -> str:
    """Inverse of :func:`_safe_tag`: decode a lane filename back into
    its logical tag (the supervisor's orphan-slab sweep enumerates the
    lane directory and must reason about TAGS, not filenames).  The
    encoding is injective and fixed-width, so decoding is unambiguous;
    a malformed name (torn tmp file, foreign debris) raises
    ``ValueError`` — the sweeper skips it rather than guessing."""
    out = bytearray()
    i, n = 0, len(name)
    while i < n:
        c = name[i]
        if c == "_":
            if i + 3 > n:
                raise ValueError(f"truncated escape in lane name {name!r}")
            out.extend(bytes([int(name[i + 1:i + 3], 16)]))
            i += 3
        else:
            out.extend(c.encode("utf-8"))
            i += 1
    return out.decode("utf-8")


class FileLaneStore:
    """Directory-backed object lane: the cross-process transport for
    fleets of unrelated processes (no fixed-size gang, no coordinator).

    ``put`` is atomic (tmp file + ``os.rename`` in one directory), so a
    concurrent ``get`` never observes a torn payload.  ``get`` polls at
    ``poll_s`` until the tag appears or ``timeout_s`` elapses — the
    TimeoutError's text matches the lanes' TRANSIENT fingerprints
    ("deadline exceeded"), so a ``lane_call``-wrapped get retries under
    the standard backoff before dying loudly.
    """

    def __init__(self, root: str, poll_s: float = 0.005):
        self.root = str(root)
        self.poll_s = float(poll_s)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, tag: str) -> str:
        return os.path.join(self.root, _safe_tag(tag))

    def put(self, tag: str, payload: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(bytes(payload))
            os.replace(tmp, self._path(tag))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def get(self, tag: str, timeout_s: float = 10.0) -> bytes:
        deadline = time.monotonic() + float(timeout_s)
        path = self._path(tag)
        while True:
            try:
                with open(path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                pass
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"lane tag {tag!r} not published within {timeout_s}s "
                    f"(deadline exceeded)")
            time.sleep(self.poll_s)

    def delete(self, tag: str) -> None:
        try:
            os.unlink(self._path(tag))
        except FileNotFoundError:
            pass

    def tags(self):
        """Every currently published tag (decoded lane filenames; tmp
        files and undecodable debris skipped) — the supervisor's
        orphan-slab sweep face (ISSUE 12)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if name.startswith(".tmp-"):
                continue
            try:
                out.append(_unsafe_tag(name))
            except (ValueError, UnicodeDecodeError):
                continue
        return out


def lane_try_get(store, lane: str, tag: str,
                 config=None) -> Optional[bytes]:
    """Non-blocking lane read under the hardened discipline: the
    payload, or None when the tag is simply absent (an empty mailbox is
    not a fault).  Real store faults still classify/retry/raise through
    :func:`~chainermn_tpu.communicators.base.lane_call` with the lane
    named."""
    from ..communicators.base import lane_call

    def _try():
        try:
            return store.get(tag, timeout_s=0.0)
        except (TimeoutError, KeyError):
            return None

    return lane_call(lane, _try, config)


def _msg_journal_fields(msg: Dict[str, Any]) -> Dict[str, Any]:
    """The identity fields a journaled mailbox event carries: enough to
    thread one request's causal story across processes (trace id, the
    worker and epoch the protocol stamps) without copying payloads."""
    out: Dict[str, Any] = {}
    trace_id = msg.get("trace_id")
    if trace_id is None and isinstance(msg.get("req"), dict):
        trace_id = msg["req"].get("trace_id")
    if trace_id is not None:
        out["trace_id"] = trace_id
    for k in ("worker", "epoch"):
        if msg.get(k) is not None:
            out[k] = msg[k]
    return out


class MailboxSender:
    """The single writer of one named mailbox (ordered, at-most-once).

    ``seq`` persists only in this sender — the single-writer contract
    makes it the mailbox's total order.  "Single writer" means one
    SENDER OBJECT, not one thread: the router's control mailboxes are
    written from client threads (submit) and the supervisor thread
    (failover, drain) through the same sender, so :meth:`send` holds a
    lock across the seq read, the put, and the increment — two
    concurrent sends minting the same seq would have the second put
    silently overwrite the first message.  A re-created sender for a
    live mailbox (e.g. a restarted router) must pass the old cursor via
    ``start_seq`` or use a fresh mailbox name (a new worker epoch gets
    a new mailbox in the fleet wiring, which is what fencing wants
    anyway: a zombie's stale mailbox is simply never read again).
    """

    def __init__(self, store, name: str, config=None, start_seq: int = 0):
        self.store = store
        self.name = str(name)
        self.config = config
        self.seq = int(start_seq)
        self._lock = threading.Lock()

    def send(self, msg: Dict[str, Any]) -> int:
        """Publish one message; returns its sequence number.
        Thread-safe: concurrent sends serialize and get distinct seqs."""
        from ..communicators.base import lane_call
        from ..observability import journal as _journal

        with self._lock:
            seq = self.seq
            wire = dict(msg, schema=MSG_SCHEMA, seq=seq)
            if _journal.enabled():
                # the HLC rides as ONE extra field in the worker_lane.v1
                # dict (ISSUE 17): the stamp is the journaled send
                # event's own, so the receiver's merge orders the
                # receive strictly after this line in the fleet timeline
                wire["hlc"] = _journal.wire_emit(
                    "mbx_send", mailbox=self.name, mseq=seq,
                    msg_kind=msg.get("kind"),
                    **_msg_journal_fields(msg))
            payload = pickle.dumps(wire,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            tag = f"mbx/{self.name}/{seq}"
            lane_call(f"worker_lane/{self.name}/send",
                      lambda: self.store.put(tag, payload), self.config)
            self.seq = seq + 1
        return seq


class MailboxReceiver:
    """The single reader of one named mailbox: consumes messages in
    sequence order, deleting each behind the cursor (at-most-once)."""

    def __init__(self, store, name: str, config=None):
        self.store = store
        self.name = str(name)
        self.config = config
        self.next_seq = 0

    def recv(self) -> Optional[Dict[str, Any]]:
        """Next message, or None when the mailbox is empty."""
        tag = f"mbx/{self.name}/{self.next_seq}"
        payload = lane_try_get(self.store,
                               f"worker_lane/{self.name}/recv", tag,
                               self.config)
        if payload is None:
            return None
        msg = pickle.loads(payload)
        if msg.get("schema") != MSG_SCHEMA:
            raise ValueError(
                f"refusing worker-lane message with schema "
                f"{msg.get('schema')!r} on mailbox {self.name!r} "
                f"(this receiver speaks {MSG_SCHEMA})")
        from ..observability import journal as _journal
        if _journal.enabled():
            # merge the sender's HLC so cross-process causality is
            # captured on the existing wire (send happens-before recv)
            _journal.recv_emit(
                msg.get("hlc"), "mbx_recv", mailbox=self.name,
                mseq=self.next_seq, msg_kind=msg.get("kind"),
                **_msg_journal_fields(msg))
        from ..communicators.base import lane_call
        lane_call(f"worker_lane/{self.name}/gc",
                  lambda: self.store.delete(tag), self.config)
        self.next_seq += 1
        return msg

    def drain(self, limit: int = 256):
        """Every pending message up to ``limit`` (bounded so a flooding
        peer cannot wedge the caller's loop)."""
        out = []
        for _ in range(int(limit)):
            msg = self.recv()
            if msg is None:
                break
            out.append(msg)
        return out
