"""JAX version-compat shims.

The codebase targets current JAX (top-level ``jax.shard_map``, vma
tracking, ``jax.lax.axis_size``), but deployment floors — including this
container's jax 0.4.37 — predate those.  Everything internal imports
``shard_map`` from here instead of from ``jax`` so the package imports
and the core SPMD paths (communicators, train steps, collectives) run on
both sides of the rename.

``install()`` additionally publishes the shims onto the ``jax`` module
itself (``jax.shard_map``, ``jax.lax.axis_size``) when missing, so
sibling code and tests written against new JAX (`from jax import
shard_map`) keep working.  It never overwrites an existing attribute.
"""

from __future__ import annotations

import functools

import jax

try:  # new JAX: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map
    _LEGACY = False
except ImportError:  # jax <= 0.4.x: experimental module, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY = True


@functools.wraps(_shard_map)
def shard_map(f, **kwargs):
    """``jax.shard_map`` on new JAX; the experimental one on old JAX.

    On legacy JAX the ``check_vma`` argument is dropped and the old
    replication checker (``check_rep``) DEFAULTS to off: it predates
    ``pallas_call`` (no replication rule) and the newer scan-carry vma
    typing, so programs that type-check under the current vma system —
    what this codebase targets — are rejected by its rules even though
    their math is correct (the parity/oracle tests exercise the
    numerics directly).  A caller that explicitly passes ``check_rep``
    is legacy-aware and keeps whatever it asked for; ``check_vma`` is
    honored verbatim on new JAX.
    """
    if _LEGACY:
        kwargs.pop("check_vma", None)
        kwargs.setdefault("check_rep", False)
    return _shard_map(f, **kwargs)


# Resolved ONCE at import (before install() can publish our own shim
# onto jax.lax — reading it lazily would recurse into ourselves).
_NATIVE_AXIS_SIZE = getattr(jax.lax, "axis_size", None)


def axis_size(axis_name):
    """``jax.lax.axis_size`` where it exists; the ``psum(1, axis)``
    identity (which lowers to the static axis size) everywhere else."""
    if _NATIVE_AXIS_SIZE is not None:
        return _NATIVE_AXIS_SIZE(axis_name)
    return jax.lax.psum(1, axis_name)


_NATIVE_PCAST = getattr(jax.lax, "pcast", None)
_NATIVE_PVARY = getattr(jax.lax, "pvary", None)


def pcast_varying(x, axis_names):
    """Promote a replicated value to varying over ``axis_names`` where
    vma tracking exists (``pcast`` on current JAX, ``pvary`` on the
    interim releases); identity on jax without vma tracking (0.4.x),
    where the replicated/varying distinction does not exist and autodiff
    of a replicated input already yields per-rank local cotangents
    (verified against 0.4.37)."""
    if _NATIVE_PCAST is not None:
        return _NATIVE_PCAST(x, axis_names, to="varying")
    if _NATIVE_PVARY is not None:
        return _NATIVE_PVARY(x, axis_names)
    return x


def ad_inserts_replicated_psum() -> bool:
    """Whether autodiff of a shard_map with REPLICATED params inserts the
    cross-rank cotangent psum into the traced program.

    True on vma-tracking jax (native ``pcast``/``pvary``): replicated
    inputs carry a type-level broadcast whose transpose is a psum, so the
    gradient all-reduce is a visible jaxpr equation.  False on 0.4.x
    (``check_rep=False`` legacy shard_map): cotangents of replicated
    inputs stay per-rank local and NO psum equation exists — which is why
    ``train.py`` books that traffic via ``observability.comm.note`` and
    why the shard-flow reconciliation (analysis/shardflow.py) gates the
    noted row's expected equation on this probe.
    """
    return _NATIVE_PCAST is not None or _NATIVE_PVARY is not None


try:
    import inspect
    _SDS_HAS_VMA = "vma" in inspect.signature(
        jax.ShapeDtypeStruct.__init__).parameters
except (ValueError, TypeError):  # pragma: no cover - exotic builds
    _SDS_HAS_VMA = False


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with the ``vma`` annotation dropped on
    jax versions whose avals carry no varying-mesh-axes type."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


_NATIVE_TYPEOF = getattr(jax, "typeof", None)


def typeof(x):
    """``jax.typeof`` (current JAX) / ``jax.core.get_aval`` (0.4.x).

    Legacy avals carry no ``vma`` field, which is exactly right: callers
    read ``getattr(typeof(x), "vma", frozenset())`` and take their
    no-vma-tracking fallback path."""
    if _NATIVE_TYPEOF is not None:
        return _NATIVE_TYPEOF(x)
    return jax.core.get_aval(x)


def _diffable_optimization_barrier():
    """Whether this jax can differentiate ``optimization_barrier``
    (rule added after 0.4.37); probed once, lazily, with a scalar jvp."""
    global _OPT_BARRIER_DIFFABLE
    if _OPT_BARRIER_DIFFABLE is None:
        try:
            jax.jvp(jax.lax.optimization_barrier, (1.0,), (1.0,))
            _OPT_BARRIER_DIFFABLE = True
        except NotImplementedError:
            _OPT_BARRIER_DIFFABLE = False
    return _OPT_BARRIER_DIFFABLE


_OPT_BARRIER_DIFFABLE = None
_BARRIER_VJP = None


def optimization_barrier(args):
    """Differentiable ``jax.lax.optimization_barrier``.

    Native where the differentiation rule exists; on legacy jax (0.4.37:
    ``NotImplementedError: Differentiation rule for 'optimization_barrier'``)
    a ``custom_vjp`` wrapper with the same semantics — value identity,
    scheduling edge on the forward, and the cotangents barriered too so
    the BACKWARD pass keeps the ordering edge (the reference
    pseudo_connect's whole point was backward ordering)."""
    if _diffable_optimization_barrier():
        return jax.lax.optimization_barrier(args)

    global _BARRIER_VJP
    if _BARRIER_VJP is None:
        @jax.custom_vjp
        def barrier(a):
            return jax.lax.optimization_barrier(a)

        def fwd(a):
            return barrier(a), None

        def bwd(_, ct):
            return (jax.lax.optimization_barrier(ct),)

        barrier.defvjp(fwd, bwd)
        _BARRIER_VJP = barrier
    return _BARRIER_VJP(args)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (current name) / ``TPUCompilerParams``
    (pre-rename) — resolved lazily so importing this module never pulls
    Pallas in."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def install() -> None:
    """Idempotently publish missing new-JAX names onto ``jax`` itself."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = axis_size
