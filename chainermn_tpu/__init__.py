"""chainermn_tpu — a TPU-native distributed-training framework.

Capability parity with ChainerMN (reference: ``okuta/chainermn``; see
SURVEY.md) built idiomatically on JAX/XLA: communicators lower to XLA
collectives over ICI/DCN instead of NCCL/MPI, gradient averaging fuses into
one jitted SPMD step instead of eager bucketed allreduce, and model
parallelism is sharding + ppermute instead of MPI send/recv.  No CUDA, NCCL
or mpi4py anywhere in the import graph.
"""

from . import _compat

_compat.install()  # jax version shims (shard_map name, axis_size) — must
# run before any submodule resolves those symbols.

from . import extensions, functions, global_except_hook, iterators, links, observability, ops, parallel, runtime, serving, training  # noqa: F401,E402
from .runtime import (FileDataset, PrefetchIterator,  # noqa: F401
                      write_file_dataset)
from .parallel import (  # noqa: F401
    column_parallel_dense,
    make_moe_mlp,
    make_pipeline,
    make_ring_attention,
    make_tensor_parallel_mlp,
    make_ulysses_attention,
    moe_mlp,
    pipeline_apply,
    ring_attention,
    row_parallel_dense,
    stack_stage_params,
    tp_mlp,
    ulysses_attention,
    vocab_parallel_embedding,
)
from .extensions import (  # noqa: F401
    AllreducePersistent,
    ObservationAggregator,
    create_multi_node_checkpointer,
    multi_node_snapshot,
)
from .iterators import (  # noqa: F401
    SerialIterator,
    create_multi_node_iterator,
    create_synchronized_iterator,
)
from .datasets import (  # noqa: F401
    ScatteredDataset,
    SubDataset,
    create_empty_dataset,
    scatter_dataset,
    scatter_index,
)
from .evaluators import (  # noqa: F401
    accuracy_evaluator,
    bleu_evaluator,
    corpus_bleu,
    create_multi_node_evaluator,
)
from .optimizers import (  # noqa: F401
    ErrorFeedbackState,
    compressed_mean,
    create_multi_node_optimizer,
    error_feedback_layout,
    fold_error_feedback,
    gradient_average,
    hierarchical_gradient_average,
    opt_state_partition_specs,
)
from .train import (  # noqa: F401
    make_flax_train_step,
    make_train_step,
    replicate,
    shard_batch,
    shard_batch_local,
)
from .communicators import (  # noqa: F401
    CommunicatorBase,
    NaiveCommunicator,
    XlaCommunicator,
    create_communicator,
)
from .topology import (  # noqa: F401
    DEFAULT_AXIS_NAME,
    Topology,
    init_distributed,
    make_mesh,
    make_multislice_mesh,
    make_nd_mesh,
)

__version__ = "0.1.0"
