"""Dataset scattering.

Reference parity: ``chainermn/datasets/`` [uv] (SURVEY.md §2.5):
``scatter_dataset`` (root shuffles, slices into per-rank SubDatasets,
scatters pickled shards over MPI) and ``create_empty_dataset`` (length-only
placeholder for non-input ranks in model parallel).

TPU-native: the permutation is drawn at the root and broadcast via the
communicator's object lane (DCN under multi-controller); shards are *index*
sets over the original dataset rather than pickled data copies — each host
only materializes the rows its chips consume.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..communicators.base import CommunicatorBase


class SubDataset:
    """A view of ``dataset`` through an index array, wrap-padded to
    ``virtual_length`` (reference: chainer SubDataset equal-length trick so
    every rank runs the same number of iterations)."""

    def __init__(self, dataset, indices: np.ndarray, virtual_length: Optional[int] = None):
        self._dataset = dataset
        self._indices = np.asarray(indices, dtype=np.int64)
        self._virtual_length = int(virtual_length or len(self._indices))

    def __len__(self) -> int:
        return self._virtual_length

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if not -len(self) <= i < len(self):
            raise IndexError(i)
        i %= len(self)  # normalize negatives against the VIRTUAL length
        return self._dataset[int(self._indices[i % len(self._indices)])]

    @property
    def indices(self) -> np.ndarray:
        return self._indices


class ScatteredDataset:
    """All ranks' shards at once (single-controller owns every rank).

    ``shard(r)`` is what reference rank ``r`` would have received from
    ``scatter_dataset``; ``local()`` is this process's shard (parity face
    under multi-controller).
    """

    def __init__(self, dataset, shards: Sequence[np.ndarray], equal_length: bool,
                 local_rank: int = 0):
        vlen = max(len(s) for s in shards) if equal_length else None
        self._subs = [SubDataset(dataset, s, vlen) for s in shards]
        self._local_rank = local_rank

    def __len__(self) -> int:
        return len(self._subs)

    def shard(self, rank: int) -> SubDataset:
        return self._subs[rank]

    def local(self) -> SubDataset:
        """This process's shard (rank-parity face under multi-controller)."""
        return self._subs[self._local_rank]

    def __iter__(self):
        return iter(self._subs)


def scatter_dataset(
    dataset,
    comm: CommunicatorBase,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
) -> ScatteredDataset:
    """Partition ``dataset`` across ranks (reference: ``scatter_dataset`` [uv]).

    The root draws the permutation and broadcasts it object-wise so every
    rank agrees on the split (the reference scattered pickled SubDatasets;
    we scatter indices — same contract, no payload duplication).
    """
    n = len(dataset)
    if n == 0:
        raise ValueError("cannot scatter an empty dataset")
    if shuffle:
        order = np.random.RandomState(seed).permutation(n)
    else:
        order = np.arange(n)
    order = np.asarray(comm.bcast_obj(order, root=root))

    size = comm.size
    # Reference split: first (n % size) ranks get one extra element.
    base, extra = divmod(n, size)
    maxlen = base + (1 if extra else 0)
    shards, start, wrap = [], 0, 0
    for r in range(size):
        ln = base + (1 if r < extra else 0)
        shard = order[start:start + ln]
        if force_equal_length and ln < maxlen:
            # Pad short/empty shards by round-robining the permutation circle
            # (reference: SubDataset wrap-padding so every rank runs the same
            # number of iterations).  The rotating cursor keeps pad elements
            # DISTINCT across ranks — padding every short shard from the same
            # position would oversample one element.
            pad = order[[(wrap + k) % n for k in range(maxlen - ln)]]
            wrap += maxlen - ln
            shard = np.concatenate([shard, pad]) if ln else pad
        shards.append(shard)
        start += ln
    return ScatteredDataset(dataset, shards, force_equal_length,
                            local_rank=comm.rank)


def scatter_index(n_total: int, comm: CommunicatorBase, root: int = 0):
    """Scatter just an index range (reference: ``scatter_index`` [uv])."""
    base, extra = divmod(n_total, comm.size)
    out = []
    start = 0
    for r in range(comm.size):
        ln = base + (1 if r < extra else 0)
        out.append((start, start + ln))
        start += ln
    return out


class _Empty:
    def __init__(self, length: int):
        self._length = length

    def __len__(self):
        return self._length

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [()] * len(range(*i.indices(self._length)))
        if not -self._length <= i < self._length:
            raise IndexError(i)
        return ()


def create_empty_dataset(dataset) -> _Empty:
    """Length-preserving, payload-free dataset (reference:
    ``create_empty_dataset`` [uv]) — feeds non-input ranks in model-parallel
    graphs so every rank's iterator agrees on epoch boundaries."""
    return _Empty(len(dataset))
