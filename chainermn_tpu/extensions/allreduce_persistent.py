"""Synchronize persistent (non-gradient) model state across ranks.

Reference parity: ``chainermn/extensions/allreduce_persistent.py ::
AllreducePersistent(model, comm)`` [uv] (SURVEY.md §2.6) — a trainer
extension that allreduce-averages a model's *persistent* values (BatchNorm
running mean/var, counters) so evaluation is consistent across data-parallel
ranks whose local batches produced different statistics.

TPU adaptation: operates on a rank-major stacked pytree (the eager
communicator contract, ``communicators/base.py``); the usual target is a
flax ``batch_stats`` collection stacked per rank out of a ``shard_map``-ped
train step.  For fully in-jit training the same sync is a one-line
``ops.pmean`` inside the step — this extension exists for eager parity and
for state kept outside the jitted program.
"""

from __future__ import annotations

from typing import Any

import jax

from ..communicators.base import CommunicatorBase


def allreduce_persistent(tree: Any, comm: CommunicatorBase) -> Any:
    """Mean every leaf of a rank-major stacked pytree across ranks."""
    return jax.tree_util.tree_map(lambda x: comm.allreduce(x, op="mean"), tree)


class AllreducePersistent:
    """Trainer extension: average persistent state across ranks.

    ``state_getter``/``state_setter`` pull and push the persistent pytree on
    the trainer (default: ``trainer.persistent_state`` attribute), keeping
    this decoupled from any one model library the way the reference walked
    Chainer ``Link._persistent`` names [uv].
    """

    def __init__(self, comm: CommunicatorBase,
                 state_getter=None, state_setter=None):
        self.comm = comm
        self._get = state_getter or (lambda t: getattr(t, "persistent_state", None))
        self._set = state_setter or (lambda t, v: setattr(t, "persistent_state", v))

    def __call__(self, trainer) -> None:
        tree = self._get(trainer)
        if tree is not None:
            self._set(trainer, allreduce_persistent(tree, self.comm))
