"""Self-healing training gang: rank health plane + checkpoint-free shrink.

ISSUE 13 brings the serving fleet's supervision story (PRs 10-12) to the
TRAINING side.  ChainerMN inherited MPI's failure semantics: one dead
rank wedges every collective and the only recovery is killing the gang
and restarting from a checkpoint — PR 8 made that restart elastic, but a
SIGKILLed rank still costs the full gang teardown + disk round trip.
:class:`SelfHealingGang` closes the gap in three layers
(docs/ROBUSTNESS.md "Training failure domains"):

1. **Rank health plane** — every rank runs a
   :class:`~chainermn_tpu.health.HeartbeatPublisher` on a side thread
   over the hardened KV side channel (a ``FileLaneStore`` for elastic
   gangs, or ``comm.gang_lease_store()`` over the jax.distributed KV
   store), the ``allgather_obj_eventual`` pattern applied to liveness:
   a dead rank is ABSENT, never a wedge.  Detection is receiver-clocked
   (:class:`~chainermn_tpu.health.LeaseTable`) and epoch-fenced
   (:class:`~chainermn_tpu.health.EpochFence`): a SIGSTOPped zombie's
   late lease/collective writes are refused and counted.

2. **Collective watchdog** — the gang's object collectives
   (:meth:`allgather` / :meth:`allreduce`) poll with a bounded window;
   on expiry they consult the lease table and raise
   :class:`~chainermn_tpu.health.RankLostError` NAMING the missing
   rank(s), plus a ``rank_lost`` flight bundle — where a mid-allreduce
   death used to surface as an anonymous lane timeout minutes later.
   :meth:`install_collective_guard` extends the same bound to the
   communicator/device hot path through the accounted collective face.

3. **Checkpoint-free live shrink** — :meth:`heal` runs the
   deterministic :class:`~chainermn_tpu.health.MembershipConsensus`
   over the lease side channel (all survivors agree on the same new
   gang or die loudly), mints a fresh epoch fencing the dead ranks,
   collects every member's **shard lease** (the per-rank non-replicated
   state block each rank re-publishes at every completed optimizer step
   via :meth:`publish_shard` — in-window state redundancy on the side
   channel, NOT a disk checkpoint), and returns a
   :class:`GangReconfig` the caller re-partitions with
   ``parallel.reshard_host`` before continuing from the last completed
   step.  Survivors' per-step losses allclose-match an uninterrupted
   gang of the new size (tests/test_chaos_gang.py proves it against a
   real SIGKILL mid-allreduce).  Below the ``min_world`` floor,
   :meth:`heal` raises :class:`~chainermn_tpu.health
   .GangBelowFloorError` and the caller falls back to the PR 8
   checkpoint restart — the shrink-vs-restart decision table.

The hand-rolled-loop shape (the :class:`~.preemption.PreemptionHandler`
convention)::

    gang = SelfHealingGang(store, rank=i, world=n, min_world=2,
                           dump_dir=out)
    gang.start()
    it = 0
    while it < steps:
        try:
            grad = gang.allreduce(local_grad, label=f"grad{it}")
            state = update(state, grad)
            gang.publish_shard(it, {"m": state["m_block"]})
            it += 1
        except RankLostError:
            rc = gang.heal()            # GangBelowFloorError -> ckpt restart
            state = repartition(state, rc)   # reshard_host over rc.shards
            # `it` unchanged: re-run the failed step on the new gang
    gang.stop()
"""

from __future__ import annotations

import pickle
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..health import (CollectiveGuard, EpochFence, GangBelowFloorError,
                      GangConsensusError, GangFencedError,
                      GangStateLossError, LeaseTable, HeartbeatPublisher,
                      MembershipConsensus, RankLostError,
                      collective_guard, detection_window_s,
                      set_collective_guard)
from ..observability import flight as _flight

#: Wire schema of one gang collective / shard-lease payload.
GANG_SCHEMA = "chainermn_tpu.gang.v1"


class GangReconfig:
    """The outcome of one live shrink: who died, the agreed new gang,
    this member's new coordinates, and the shard leases the caller
    re-partitions (``reshard_host``) to continue checkpoint-free."""

    def __init__(self, *, old_members: List[int], members: List[int],
                 old_epoch: int, epoch: int, member_id: int,
                 shards: Dict[int, Dict[str, Any]],
                 detection_ms: Optional[float],
                 consensus_wall_ms: float):
        self.old_members = list(old_members)
        self.members = list(members)
        self.dead = [m for m in old_members if m not in members]
        self.old_world = len(old_members)
        self.new_world = len(members)
        self.old_epoch = int(old_epoch)
        self.epoch = int(epoch)
        self.member_id = int(member_id)
        self.old_rank = self.old_members.index(member_id)
        self.new_rank = self.members.index(member_id)
        #: member_id -> {"iteration": int, "payload": Any} — the shard
        #: leases at the last completed step, OLD-member order preserved
        #: in ``old_members``.
        self.shards = shards
        self.detection_ms = detection_ms
        self.consensus_wall_ms = consensus_wall_ms
        self.reshard_wall_ms: Optional[float] = None
        self.repartitioned: Any = None

    def resume_iteration(self) -> Optional[int]:
        """The common last-completed step across shard leases, or None
        when no member published one (nothing non-replicated to carry).
        A disagreement means some member completed a step the others did
        not — the caller must roll back to the MINIMUM (keeping a
        one-step shadow of its own state), so the minimum is returned
        and per-member iterations stay readable on ``shards``."""
        its = [v["iteration"] for v in self.shards.values()
               if v.get("iteration") is not None]
        return min(its) if its else None

    def summary(self) -> Dict[str, Any]:
        return {
            "old_world": self.old_world, "new_world": self.new_world,
            "old_members": self.old_members, "members": self.members,
            "dead": self.dead, "old_epoch": self.old_epoch,
            "epoch": self.epoch, "member": self.member_id,
            "old_rank": self.old_rank, "new_rank": self.new_rank,
            "resume_iteration": self.resume_iteration(),
            "shard_iterations": {m: v.get("iteration")
                                 for m, v in self.shards.items()},
            "detection_ms": self.detection_ms,
            "consensus_wall_ms": self.consensus_wall_ms,
            "reshard_wall_ms": self.reshard_wall_ms,
            "decision": "live_shrink",
        }


class SelfHealingGang:
    """One training rank's half of the self-healing plane.

    Parameters
    ----------
    store:
        A lane store (``serving.lanes.FileLaneStore`` for elastic gangs
        of unrelated processes, ``comm.gang_lease_store()`` over the
        jax.distributed KV store for gangs sharing a coordinator, or the
        in-process loopback for tests/bench).  Every operation rides
        :func:`~chainermn_tpu.communicators.base.lane_call`.
    rank / world:
        This member's ORIGINAL rank and the launch world size.  Member
        ids are stable identities; after a shrink the data-parallel rank
        is the index into the surviving membership (:attr:`rank`).
    beat_interval_s / miss_beats:
        The detection-window knobs (see
        :func:`~chainermn_tpu.health.detection_window_s`).
    min_world:
        The live-shrink floor: :meth:`heal` refuses to shrink below it
        (``GangBelowFloorError`` — fall back to checkpoint restart).
    op_timeout_s:
        Hard cap on any one collective (default ``max(4 × window, 5 s)``)
        — a peer that is neither fresh nor absent (wedged store, lost
        message) still produces a bounded, named ``RankLostError``.
    """

    def __init__(self, store, rank: int, world: int, *,
                 name: str = "gang", epoch: int = 1,
                 beat_interval_s: float = 0.05, miss_beats: int = 4,
                 op_timeout_s: Optional[float] = None,
                 consensus_timeout_s: Optional[float] = None,
                 min_world: int = 1,
                 dump_dir: Optional[str] = None,
                 lane_config=None,
                 register_provider: bool = True,
                 clock=time.monotonic):
        if world < 1 or not 0 <= int(rank) < int(world):
            raise ValueError(f"bad rank/world {rank}/{world}")
        self.store = store
        self.name = str(name)
        self.member_id = int(rank)
        self.members: List[int] = list(range(int(world)))
        self.epoch = int(epoch)
        self.beat_interval_s = float(beat_interval_s)
        self.miss_beats = int(miss_beats)
        self.window_s = detection_window_s(beat_interval_s, miss_beats)
        self.op_timeout_s = float(op_timeout_s if op_timeout_s is not None
                                  else max(4 * self.window_s, 5.0))
        self.consensus_timeout_s = float(
            consensus_timeout_s if consensus_timeout_s is not None
            else max(10 * self.window_s, 5.0))
        self.min_world = int(min_world)
        self.dump_dir = dump_dir
        self.lane_config = lane_config
        self.register_provider = register_provider
        self._clock = clock
        self.poll_s = max(self.beat_interval_s / 4, 0.002)

        self._publisher = HeartbeatPublisher(
            store, self._tag(self.member_id), role="trainer",
            epoch=self.epoch, beat_interval_s=beat_interval_s,
            lane_config=lane_config)
        self._leases = LeaseTable(store, lane_config=lane_config)
        self._fence = EpochFence()
        for m in self.members:
            self._fence.set_epoch(self._tag(m), self.epoch)
        self._fenced: List[int] = []          # dead member ids, fenced
        self._fenced_seq: Dict[int, int] = {}  # last counted lease seq
        self._suspects: Dict[int, Optional[float]] = {}  # id -> lease age
        self._seq = 0
        self._my_keys: deque = deque()        # my published x-keys (GC)
        self._last_step: Optional[int] = None
        self._last_consensus: Optional[Dict[str, int]] = None
        self._last_rank_lost: Optional[Dict[str, Any]] = None
        self._last_reconfig: Optional[Dict[str, Any]] = None
        self.rank_lost_events = 0
        self.reconfigs = 0
        self._guard: Optional[CollectiveGuard] = None
        self._stop = threading.Event()
        self._beat_thread: Optional[threading.Thread] = None
        self._start_t: Optional[float] = None

    # ---- identities & keys ----
    def _tag(self, member: int) -> str:
        return f"{self.name}-r{int(member)}"

    def _xkey(self, epoch: int, seq: int, member: int) -> str:
        return f"gangx/{self.name}/{int(epoch)}/{int(seq)}/{int(member)}"

    def _ckey(self, epoch: int, member: int) -> str:
        return f"gangc/{self.name}/{int(epoch)}/{int(member)}"

    def _skey(self, member: int) -> str:
        return f"gangs/{self.name}/{int(member)}"

    @property
    def world(self) -> int:
        return len(self.members)

    @property
    def rank(self) -> int:
        """Current data-parallel rank: index into the live membership."""
        return self.members.index(self.member_id)

    # ---- lifecycle ----
    def start(self) -> "SelfHealingGang":
        """Publish the first lease and start the side heartbeat thread
        (a long device call must not read as death; SIGKILL/SIGSTOP take
        the thread with the process, so real death still silences the
        lease within one beat)."""
        if self._beat_thread is not None:
            return self
        self._start_t = self._clock()
        self._publisher.beat(step=self._last_step, world=self.world,
                             members=list(self.members))
        self._stop.clear()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"chainermn-tpu-gang-beat-"
            f"{self.name}-r{self.member_id}", daemon=True)
        self._beat_thread.start()
        if self.register_provider:
            _flight.register_provider("gang_health", self.stats)
        return self

    def stop(self, release: bool = True) -> None:
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=5)
            self._beat_thread = None
        if self._guard is not None:
            self._guard.stop()
            # clear the process-global slot only if it is still OURS —
            # another gang may have installed its own guard since
            if collective_guard() is self._guard:
                set_collective_guard(None)
            self._guard = None
        if self.register_provider:
            _flight.unregister_provider("gang_health")
        if release:
            try:
                self._publisher.release()
            except Exception:
                pass  # a dying store must not mask the caller's exit path

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.beat_interval_s / 2):
            try:
                self._publisher.maybe_beat(step=self._last_step,
                                           world=self.world,
                                           members=list(self.members))
            except BaseException as e:  # noqa: BLE001 — fail toward death
                # a permanently faulted lease lane means THIS member will
                # read as dead to its peers — the correct direction; say
                # why and stop beating rather than spinning on the fault
                import sys
                print(f"[chainermn_tpu gang] heartbeat lane failed for "
                      f"{self._tag(self.member_id)}: {e!r} — lease will "
                      f"go stale", file=sys.stderr, flush=True)
                return

    def wait_for_members(self, timeout_s: float = 30.0) -> None:
        """Join barrier: block until every member's lease is fresh (the
        gang processes may boot with arbitrary skew — a peer that has
        not STARTED yet must not read as a death).  Raises a named
        :class:`RankLostError` when a member never shows up inside
        ``timeout_s``; on success, the absence clock re-arms from the
        join point."""
        deadline = self._clock() + float(timeout_s)
        pending = {m for m in self.members if m != self.member_id}
        while pending:
            for m in list(pending):
                try:
                    lease, age = self._read_lease(m)
                except GangFencedError:
                    raise
                except Exception:
                    continue
                if (lease is not None and age is not None
                        and age <= self.window_s):
                    pending.discard(m)
            if not pending:
                break
            if self._clock() > deadline:
                self._raise_rank_lost(sorted(pending), f"{self.name}/join",
                                      float(timeout_s))
            time.sleep(self.poll_s)
        self._start_t = self._clock()

    def install_collective_guard(self, timeout_s: Optional[float] = None,
                                 action=None) -> CollectiveGuard:
        """Extend the bounded-timeout watchdog to the communicator /
        device hot path: every eager accounted collective
        (``observability/comm.py``) is guarded; on expiry the guard
        names this gang's stale members and aborts loudly (exit 44)."""
        if self._guard is not None:
            self._guard.stop()  # re-install must not leak a watcher
        guard = CollectiveGuard(
            timeout_s if timeout_s is not None else self.op_timeout_s,
            lost_ranks_fn=self.stale_members, action=action,
            dump_dir=self.dump_dir, rank=self.member_id).start()
        set_collective_guard(guard)
        self._guard = guard
        return guard

    # ---- lease reading ----
    def _read_lease(self, member: int):
        """(lease dict or None, age_s or None) for ``member``, with the
        epoch gate applied.  Reconfigurations are not atomic across the
        gang, so the comparison must distinguish three cases:

        * ``lease.epoch > ours`` and WE are in the lease's ``members``
          — the peer merely finished the reconfig ahead of us (we are
          mid-heal); its lease is live evidence, not a fence.
        * ``lease.epoch > ours`` and we are EXCLUDED — the gang agreed
          on a membership without us: raise :class:`GangFencedError`
          (we may be the zombie; dying loudly beats splitting).
        * ``lease.epoch == ours`` but the lease's ``members`` EXCLUDE us
          — two partitions independently reconfigured onto the same
          epoch number (divergent decisions): equally a fence, raised
          loudly so a split brain cannot persist behind an equal epoch.
        * ``lease.epoch < ours`` from a FENCED member — a zombie's late
          write: refused + counted (once per new seq), reads as absent.
          From a live member it just means the peer has not finished
          the reconfig yet — still live evidence.
        """
        tag = self._tag(member)
        lease = self._leases.read(tag)
        if lease is None:
            return None, None
        ep = int(lease["epoch"])
        if ep >= self.epoch:
            mem = lease.get("members")
            if mem is not None and self.member_id not in mem:
                raise GangFencedError(
                    f"member {member}'s lease carries epoch {ep} "
                    f"{'>' if ep > self.epoch else '=='} our epoch "
                    f"{self.epoch} with membership {mem} excluding "
                    f"member {self.member_id}: the gang "
                    f"{'reconfigured without us' if ep > self.epoch else 'split into divergent memberships'}"
                    f" — dying loudly (gang {self.name!r})")
            if ep > self.epoch:
                return lease, self._leases.age_of_seen(tag)
        if ep < self.epoch and self._fence.is_fenced(tag):
            # count once per NEW stale seq, not per poll
            if self._fenced_seq.get(member) != lease["seq"]:
                self._fenced_seq[member] = lease["seq"]
                self._fence.admit(tag, ep, "lease")
                _flight.note("gang", event="fenced_refusal",
                             what="lease", member=member, epoch=ep,
                             current_epoch=self.epoch)
            return None, None
        return lease, self._leases.age_of_seen(tag)

    def _lease_stale(self, member: int) -> bool:
        lease, age = self._read_lease(member)
        if lease is None:
            # never beat (or fenced): stale once the gang is old enough
            # that a live member MUST have published
            return (self._start_t is not None
                    and self._clock() - self._start_t > 2 * self.window_s)
        return age is not None and age > self.window_s

    def _seen_stale(self, member: int) -> bool:
        """Staleness from the ALREADY-OBSERVED lease state (no store
        read) — the hot poll loop's face: the warm lease poll refreshes
        the receiver clock at beat/2 cadence, so re-reading the store
        per poll iteration would only add lane I/O, not information."""
        age = self._leases.age_of_seen(self._tag(member))
        if age is None:
            return (self._start_t is not None
                    and self._clock() - self._start_t > 2 * self.window_s)
        return age > self.window_s

    def _poll_fenced(self) -> None:
        """Poll fenced (dead) members' lease keys so a resumed zombie's
        late writes are refused and COUNTED (the acceptance evidence)."""
        for m in list(self._fenced):
            try:
                self._read_lease(m)
            except GangFencedError:
                raise
            except Exception:
                pass  # a torn zombie write is not our failure

    def fenced_refusals(self) -> Dict[str, int]:
        return self._fence.refusal_counts()

    def await_fenced_refusals(self, min_count: int = 1,
                              timeout_s: float = 10.0) -> int:
        """Linger until ≥ ``min_count`` stale-epoch writes were refused
        (bounded) — the chaos test's zombie-evidence wait."""
        deadline = self._clock() + float(timeout_s)
        while self._clock() < deadline:
            self._poll_fenced()
            n = sum(self._fence.refusal_counts().values())
            if n >= min_count:
                return n
            time.sleep(self.poll_s)
        return sum(self._fence.refusal_counts().values())

    def stale_members(self) -> List[int]:
        """Members whose lease fell out of the window right now — the
        collective guard's ``lost_ranks_fn``."""
        out = []
        for m in self.members:
            if m == self.member_id:
                continue
            try:
                if self._lease_stale(m):
                    out.append(m)
            except GangFencedError:
                raise
            except Exception:
                out.append(m)
        return out

    # ---- the watchdog-guarded collectives ----
    def allgather(self, obj: Any, label: Optional[str] = None
                  ) -> Dict[int, Any]:
        """Epoch-scoped object allgather over the live membership.

        Publishes my payload under a (epoch, seq, member) key, polls
        peers' keys, and consults the lease table while waiting: a peer
        absent past the detection window raises
        :class:`RankLostError` NAMING it (plus a ``rank_lost`` flight
        bundle); the hard ``op_timeout_s`` cap bounds even a
        neither-fresh-nor-stale pathology.  Stale-epoch payloads are
        refused and counted, never adopted.  Returns ``{member: obj}``
        over the CURRENT membership."""
        from ..communicators.base import lane_call
        from ..serving.lanes import lane_try_get

        self._seq += 1
        seq = self._seq
        op = f"{self.name}/{label or f'op{seq}'}"
        payload = pickle.dumps(
            {"schema": GANG_SCHEMA, "epoch": self.epoch,
             "member": self.member_id, "seq": seq, "obj": obj},
            protocol=pickle.HIGHEST_PROTOCOL)
        key = self._xkey(self.epoch, seq, self.member_id)
        lane_call(f"gang/{self.name}/x/{label or seq}/put",
                  lambda: self.store.put(key, payload), self.lane_config)
        # loop-progress beat from the MAIN thread (the serving workers'
        # maybe_beat contract): a wedged step loop then misses leases
        # even while the side thread breathes, and a resumed zombie
        # provably writes ≥1 post-fence lease BEFORE it discovers the
        # fence below — the write the survivors refuse and count.
        self._publisher.maybe_beat(step=self._last_step, world=self.world,
                                   members=list(self.members))
        self._my_keys.append(key)
        # GC my own key two collectives back: by the time any peer reads
        # seq s, every peer finished reading s-2 (it published s-1, which
        # required completing s-2) — the lockstep-GC argument of
        # ``_kv_exchange_obj``, applied to the gang lane.
        while len(self._my_keys) > 2:
            old = self._my_keys.popleft()
            lane_call(f"gang/{self.name}/x/gc",
                      lambda o=old: self.store.delete(o), self.lane_config)

        out = {self.member_id: obj}
        pending = [m for m in self.members if m != self.member_id]
        t0 = self._clock()
        last_lease_poll = 0.0
        while pending:
            # keep the receiver clock WARM: observe peers' lease seqs at
            # beat cadence even while payloads flow, so a death's age
            # counts from its last beat — not from the first post-window
            # read (which would double the effective detection window)
            if self._clock() - last_lease_poll >= self.beat_interval_s / 2:
                last_lease_poll = self._clock()
                for m in pending:
                    try:
                        self._read_lease(m)
                    except GangFencedError:
                        raise
                    except Exception:
                        pass
                # zombie-refusal evidence rides the same throttle: one
                # lane read per fenced member per beat/2, not per poll
                self._poll_fenced()
            for m in list(pending):
                data = lane_try_get(
                    self.store, f"gang/{self.name}/x/{label or seq}/get",
                    self._xkey(self.epoch, seq, m), self.lane_config)
                if data is None:
                    continue
                msg = pickle.loads(data)
                if (msg.get("schema") != GANG_SCHEMA
                        or int(msg.get("epoch", -1)) != self.epoch):
                    self._fence.admit(self._tag(m),
                                      msg.get("epoch", -1), "collective")
                    _flight.note("gang", event="fenced_refusal",
                                 what="collective", member=m,
                                 epoch=msg.get("epoch"),
                                 current_epoch=self.epoch)
                    continue
                out[m] = msg["obj"]
                pending.remove(m)
            if not pending:
                break
            elapsed = self._clock() - t0
            if elapsed > self.window_s:
                stale = [m for m in pending if self._seen_stale(m)]
                if stale:
                    self._raise_rank_lost(stale, op, elapsed,
                                          sticky=True)
                if elapsed > self.op_timeout_s:
                    # neither fresh nor stale is still BOUNDED: name the
                    # pending peers — but a fresh-leased peer (alive,
                    # merely slow/wedged) must NOT become a sticky
                    # suspect: evicting it would secede a live member.
                    # heal()'s consensus will observe it alive, miss its
                    # proposal, and die loudly (GangConsensusError)
                    # instead of splitting the gang.
                    self._raise_rank_lost(list(pending), op, elapsed,
                                          sticky=False)
            time.sleep(self.poll_s)
        return out

    def _raise_rank_lost(self, lost: Sequence[int], op: str,
                         elapsed: float, sticky: bool = True) -> None:
        """``sticky=True`` (the stale-lease path) records the ranks as
        suspects so a mid-consensus lease revival cannot re-admit them;
        the hard op-timeout path passes ``sticky=False`` — a peer whose
        lease is FRESH is alive, and suspecting it would let a slow step
        secede a live member."""
        ages = {}
        for m in lost:
            try:
                _, ages[m] = self._read_lease(m)
            except Exception:
                ages[m] = None
        if sticky:
            for m in lost:
                self._suspects[m] = ages.get(m)
        self.rank_lost_events += 1
        info = {
            "missing": sorted(int(m) for m in lost),
            "op": op, "epoch": self.epoch,
            "elapsed_s": round(elapsed, 3),
            "lease_age_s": {m: (None if a is None else round(a, 3))
                            for m, a in ages.items()},
            "detection_window_s": self.window_s,
            "step": self._last_step,
            "world": self.world,
        }
        self._last_rank_lost = info
        _flight.note("rank_lost", source="gang", **info)
        if self.dump_dir:
            _flight.dump_bundle(self.dump_dir, "rank_lost",
                                rank=self.member_id,
                                extra={"rank_lost": info})
        raise RankLostError(lost, op=op, lease_age_s=ages,
                            window_s=self.window_s, epoch=self.epoch)

    def allreduce(self, value: Any, op: Optional[Callable] = None,
                  label: Optional[str] = None) -> Any:
        """Object allreduce: allgather + a deterministic member-ordered
        fold (default ``+``) — every member computes the identical
        result."""
        got = self.allgather(value, label=label)
        vals = [got[m] for m in sorted(got)]
        out = vals[0]
        for v in vals[1:]:
            out = op(out, v) if op is not None else out + v
        return out

    def step_completed(self, iteration: int) -> None:
        """Stamp loop progress (rides the lease, shows on /statusz)."""
        self._last_step = int(iteration)

    # ---- shard leases: in-window state redundancy on the side channel --
    def publish_shard(self, iteration: int, payload: Any) -> None:
        """Publish this member's NON-REPLICATED state block as of the
        just-completed ``iteration`` (one overwritten key per member —
        the lease pattern applied to state).  This is what makes the
        shrink checkpoint-free: when a member dies, the survivors
        recover its block from here instead of a disk generation."""
        from ..communicators.base import lane_call

        data = pickle.dumps(
            {"schema": GANG_SCHEMA, "epoch": self.epoch,
             "member": self.member_id, "iteration": int(iteration),
             "payload": payload},
            protocol=pickle.HIGHEST_PROTOCOL)
        lane_call(f"gang/{self.name}/shard/put",
                  lambda: self.store.put(self._skey(self.member_id), data),
                  self.lane_config)
        self._last_step = int(iteration)

    def _collect_shards(self, members: Sequence[int]
                        ) -> Dict[int, Dict[str, Any]]:
        from ..serving.lanes import lane_try_get

        out: Dict[int, Dict[str, Any]] = {}
        for m in members:
            data = lane_try_get(self.store, f"gang/{self.name}/shard/get",
                                self._skey(m), self.lane_config)
            if data is None:
                continue
            msg = pickle.loads(data)
            if msg.get("schema") != GANG_SCHEMA:
                continue
            out[int(msg["member"])] = {"iteration": msg.get("iteration"),
                                       "payload": msg.get("payload")}
        return out

    # ---- the live shrink ----
    def heal(self, repartition: Optional[Callable[["GangReconfig"], Any]]
             = None) -> GangReconfig:
        """Membership consensus → fence the dead → fresh epoch → shard
        collection; returns the :class:`GangReconfig` to continue from.

        ``repartition(rc)`` (optional) runs between consensus and the
        ``gang_reconfig`` bundle dump, its wall time recorded as
        ``reshard_wall_ms`` and its return stored at
        ``rc.repartitioned`` — pass the ``reshard_host`` closure so the
        bundle prices the whole reconfiguration.

        Raises :class:`GangBelowFloorError` when the survivors would
        fall below ``min_world`` (fall back to checkpoint restart),
        :class:`GangFencedError` when the gang reconfigured without us,
        :class:`GangConsensusError` when agreement cannot be reached
        inside ``consensus_timeout_s`` — all loud, never a hang."""
        detection_ms = None
        if self._last_rank_lost is not None:
            ages = [a for a in
                    self._last_rank_lost["lease_age_s"].values()
                    if a is not None]
            if ages:
                detection_ms = round(max(ages) * 1e3, 1)
        t0 = self._clock()
        old_members = list(self.members)
        old_epoch = self.epoch
        decision = self._run_consensus()
        consensus_wall_ms = round((self._clock() - t0) * 1e3, 1)
        if len(decision) < self.min_world:
            info = {"old_world": len(old_members),
                    "survivors": decision, "min_world": self.min_world,
                    "old_epoch": old_epoch,
                    "decision": "checkpoint_restart"}
            _flight.note("gang_reconfig", source="gang", **info)
            if self.dump_dir:
                _flight.dump_bundle(self.dump_dir, "gang_reconfig",
                                    rank=self.member_id,
                                    extra={"gang_reconfig": info})
            raise GangBelowFloorError(decision, self.min_world)

        dead = [m for m in old_members if m not in decision]
        shards = self._collect_shards(old_members)
        # NO shard leases at all means nothing non-replicated to carry
        # (a replicated-state gang) — fine.  PARTIAL coverage, or
        # iterations diverging beyond the documented one-step skew,
        # means the side-channel redundancy cannot rebuild the logical
        # state: refuse the shrink LOUDLY rather than hand the caller a
        # silently incomplete rc.shards to corrupt the optimizer with.
        if shards:
            missing = [m for m in old_members if m not in shards]
            its = sorted({int(v["iteration"]) for v in shards.values()
                          if v.get("iteration") is not None})
            skew = (its[-1] - its[0]) if its else 0
            if missing or skew > 1:
                info = {"old_world": len(old_members),
                        "old_epoch": old_epoch,
                        "survivors": decision,
                        "missing_shards": missing,
                        "shard_iterations": {m: v.get("iteration")
                                             for m, v in shards.items()},
                        "decision": "checkpoint_restart"}
                _flight.note("gang_reconfig", source="gang", **info)
                if self.dump_dir:
                    _flight.dump_bundle(self.dump_dir, "gang_reconfig",
                                        rank=self.member_id,
                                        extra={"gang_reconfig": info})
                raise GangStateLossError(
                    f"live shrink refused: shard leases are incomplete "
                    f"(missing from members {missing}) or diverge "
                    f"{skew} steps across {its} — fall back to "
                    f"checkpoint restart (gang {self.name!r}, epoch "
                    f"{old_epoch})")
        # install the agreed gang under a fresh epoch; fence the dead
        self.epoch = old_epoch + 1
        self.members = list(decision)
        self._publisher.epoch = self.epoch
        for m in decision:
            self._fence.set_epoch(self._tag(m), self.epoch)
        for d in dead:
            tag = self._tag(d)
            self._fence.fence(tag)
            if d not in self._fenced:
                self._fenced.append(d)
                # baseline the corpse's LAST seen seq: only leases the
                # zombie writes AFTER the fence count as refusals — its
                # pre-death lease file is evidence of life, not a write
                try:
                    self._leases.read(tag)
                except Exception:
                    pass
                self._fenced_seq[d] = self._leases.last_seq(tag)
        self._suspects.clear()
        self._publisher.beat(step=self._last_step, world=self.world,
                             members=list(self.members))

        rc = GangReconfig(
            old_members=old_members, members=list(decision),
            old_epoch=old_epoch, epoch=self.epoch,
            member_id=self.member_id, shards=shards,
            detection_ms=detection_ms,
            consensus_wall_ms=consensus_wall_ms)
        if repartition is not None:
            tr0 = self._clock()
            rc.repartitioned = repartition(rc)
            rc.reshard_wall_ms = round((self._clock() - tr0) * 1e3, 1)
        self.reconfigs += 1
        info = rc.summary()
        self._last_reconfig = info
        _flight.note("gang_reconfig", source="gang", **info)
        if self.dump_dir:
            _flight.dump_bundle(self.dump_dir, "gang_reconfig",
                                rank=self.member_id,
                                extra={"gang_reconfig": info})
        return rc

    def _run_consensus(self) -> List[int]:
        """Drive :class:`MembershipConsensus` over the lease side
        channel until every survivor proves unanimity (or die loudly).

        Suspicion is STICKY: members named by the triggering
        ``RankLostError`` stay excluded even if their lease revives
        mid-consensus (a rank absent in-window during a collective has
        lost its step-lockstep regardless; a revived zombie is fenced by
        the fresh epoch and dies loudly on its next op)."""
        from ..communicators.base import lane_call
        from ..serving.lanes import lane_try_get

        cons = MembershipConsensus(self.member_id, self.members,
                                   self.epoch)
        deadline = self._clock() + self.consensus_timeout_s
        while True:
            alive = {self.member_id}
            for m in self.members:
                if m == self.member_id or m in self._suspects:
                    continue
                try:
                    if not self._lease_stale(m):
                        alive.add(m)
                except GangFencedError:
                    raise
                except Exception:
                    pass
            cons.observe(alive)
            msg = cons.proposal()
            payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
            lane_call(f"gang/{self.name}/consensus/put",
                      lambda: self.store.put(
                          self._ckey(self.epoch, self.member_id), payload),
                      self.lane_config)
            for m in self.members:
                if m == self.member_id:
                    continue
                data = lane_try_get(
                    self.store, f"gang/{self.name}/consensus/get",
                    self._ckey(self.epoch, m), self.lane_config)
                if data is not None:
                    cons.deliver(pickle.loads(data))
            decision = cons.decide()   # may raise GangFencedError
            self._last_consensus = cons.stats()
            if decision is not None:
                return decision
            if self._clock() > deadline:
                raise GangConsensusError(
                    f"membership consensus for gang {self.name!r} epoch "
                    f"{self.epoch} did not converge within "
                    f"{self.consensus_timeout_s}s: my view {sorted(alive)}, "
                    f"proposals {cons.stats()} — dying loudly")
            time.sleep(self.poll_s)

    # ---- observability ----
    def stats(self) -> Dict[str, Any]:
        """The ``gang_health`` provider: /statusz + every flight bundle
        carries this block."""
        return {
            "name": self.name,
            "member": self.member_id,
            "rank": self.rank,
            "epoch": self.epoch,
            "members": list(self.members),
            "world": self.world,
            "min_world": self.min_world,
            "beat_interval_s": self.beat_interval_s,
            "miss_beats": self.miss_beats,
            "detection_window_s": self.window_s,
            "op_timeout_s": self.op_timeout_s,
            "last_step": self._last_step,
            "suspects": sorted(self._suspects),
            "fenced_members": list(self._fenced),
            "fenced_refusals": self._fence.refusal_counts(),
            "rank_lost_events": self.rank_lost_events,
            "reconfigs": self.reconfigs,
            "last_rank_lost": self._last_rank_lost,
            "last_reconfig": self._last_reconfig,
            "consensus": self._last_consensus,
        }
