"""Cross-rank averaging of reported observations (losses/metrics).

Reference parity: merged-era ``chainermn/extensions/_observation_aggregator.py
:: ObservationAggregator`` [uv] (SURVEY.md §2.6) — averages Trainer
observation scalars across ranks before LogReport so rank 0's log reflects
the whole job, not its local shard.

TPU adaptation: scalar dicts ride the DCN object lane (``allgather_obj``);
under a single controller the values are already global and the mean is an
identity.  Tensor leaves are averaged elementwise.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..communicators.base import CommunicatorBase


def aggregate_observations(observation: Dict[str, Any],
                           comm: CommunicatorBase) -> Dict[str, Any]:
    """Return the across-rank mean of each entry of ``observation``."""
    gathered = comm.allgather_obj(observation)
    keys: list = []
    for g in gathered:  # union, so metrics reported by only some ranks survive
        keys.extend(k for k in g if k not in keys)
    out: Dict[str, Any] = {}
    for key in keys:
        vals = [np.asarray(g[key], dtype=np.float64) for g in gathered
                if key in g]
        out[key] = (np.mean(vals, axis=0) if vals[0].ndim
                    else float(np.mean(vals)))
    return out


class ObservationAggregator:
    """Trainer extension: replace ``trainer.observation`` with rank means."""

    def __init__(self, comm: CommunicatorBase):
        self.comm = comm

    def __call__(self, trainer) -> None:
        trainer.observation = aggregate_observations(trainer.observation, self.comm)
