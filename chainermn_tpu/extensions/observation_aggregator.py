"""Cross-rank averaging of reported observations (losses/metrics).

Reference parity: merged-era ``chainermn/extensions/_observation_aggregator.py
:: ObservationAggregator`` [uv] (SURVEY.md §2.6) — averages Trainer
observation scalars across ranks before LogReport so rank 0's log reflects
the whole job, not its local shard.

TPU adaptation: scalar dicts ride the DCN object lane (``allgather_obj``);
under a single controller the values are already global and the mean is an
identity.  Tensor leaves are averaged elementwise.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..communicators.base import CommunicatorBase


def _as_numeric(v) -> "np.ndarray | None":
    """float64 view of ``v``, or None when it is not numeric (strings,
    dicts, arbitrary objects riding the observation)."""
    try:
        a = np.asarray(v, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    if a.dtype == object:
        return None
    return a


def aggregate_observations(observation: Dict[str, Any],
                           comm: CommunicatorBase) -> Dict[str, Any]:
    """Return the across-rank mean of each entry of ``observation``.

    Non-numeric entries (status strings, config echoes — anything
    ``float64`` cannot hold) are passed through from the first rank that
    reported them instead of crashing the whole aggregation; numeric
    entries whose shapes disagree across ranks raise a ``ValueError``
    that NAMES the offending key (a silent broadcast-mean over mismatched
    shapes would log garbage as if it were a metric).
    """
    gathered = comm.allgather_obj(observation)
    keys: list = []
    for g in gathered:  # union, so metrics reported by only some ranks survive
        keys.extend(k for k in g if k not in keys)
    out: Dict[str, Any] = {}
    for key in keys:
        raw = [g[key] for g in gathered if key in g]
        vals = [_as_numeric(v) for v in raw]
        if any(v is None for v in vals):
            # non-numeric on at least one rank: rank-0's (first reporting
            # rank's) value wins, unaveraged
            out[key] = raw[0]
            continue
        shapes = {v.shape for v in vals}
        if len(shapes) > 1:
            raise ValueError(
                f"observation key {key!r} has mismatched shapes across "
                f"ranks: {sorted(shapes)} — ranks must report the same "
                f"shape (or rename per-rank variants)")
        out[key] = (np.mean(vals, axis=0) if vals[0].ndim
                    else float(np.mean(vals)))
    return out


class ObservationAggregator:
    """Trainer extension: replace ``trainer.observation`` with rank means."""

    def __init__(self, comm: CommunicatorBase):
        self.comm = comm

    def __call__(self, trainer) -> None:
        trainer.observation = aggregate_observations(trainer.observation, self.comm)
