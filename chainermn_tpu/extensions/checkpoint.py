"""Fault-tolerant distributed checkpointing.

Reference parity: ``chainermn/extensions/checkpoint.py ::
create_multi_node_checkpointer(name, comm, cp_interval, gc_interval, path)``
[uv] (SURVEY.md §2.6, §5 "failure detection / recovery") — each rank
snapshots its own shard of state, old generations are garbage-collected, and
``maybe_load`` auto-resumes from the newest generation that is *consistent
across all ranks* after a restart with the same world size.

TPU adaptation: sharding is per *controller process* (multi-controller JAX
has one process per host, vs one per GPU under MPI), and cross-process
consistency agreement rides the DCN object lane (``allgather_obj``) instead
of MPI.  State is any picklable pytree — train state, optimizer state, and
iterator ``state_dict`` all qualify; device arrays are pulled to host first
so a checkpoint never pins HBM.

Async writes (orbax-style, SURVEY.md §5 build note): ``save`` detaches the
state to host (the only device sync) and hands serialize+write to a
single background thread; the train loop continues immediately.  Depth is
bounded at one in-flight write (a new save waits out the previous one),
every read/consistency operation joins the writer first, and writer errors
re-raise at the next checkpoint call instead of vanishing.

Format v2 — world-size-independent checkpoints (ISSUE 8)
--------------------------------------------------------
Each generation now carries a per-generation MANIFEST
(``{name}.iter{it}.world{n}.manifest.json``, written by the process
owning rank 0) recording the schema, world size, partition LAYOUT
(dotted leaf path → ``replicated`` / ``per_rank`` / ``["sharded",
axis]``), logical leaf shapes, and a CRC32 per shard.  The checksum
exchange rides ``allgather_obj_eventual`` — the BOUNDED, non-lockstep
DCN side channel — never a gang collective: ``save()`` stays a LOCAL
operation, so a peer that skips a generation, is mid-preemption, or is
already dead degrades the manifest (its checksum is simply absent,
``_verify_shard`` accepts that shard unverified) instead of wedging
every survivor's save.  Two things fall out:

* **Torn-shard tolerance** — ``_consistent_generations`` verifies every
  local shard against its manifest checksum and silently excludes a
  generation with a corrupt/truncated shard, so resume falls back to the
  previous consistent one instead of unpickling garbage (a torn write at
  the instant of death can no longer poison resume).
* **Elastic resume** — ``maybe_load`` on a DIFFERENT process count finds
  the newest gang-agreed old-world generation, reads ALL its shards
  (shared filesystem assumed, as every elastic scheduler provides),
  re-partitions them host-side via
  :func:`chainermn_tpu.parallel.reshard.reshard_host` per the manifest
  layout, and resumes the exact trajectory — iterator and optimizer
  state included.  ChainerMN's fault-tolerant checkpoint required the
  original rank count [uv]; here a preempted n=8 job continues on the
  n=4 that survives (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import json
import os
import pickle
import re
import sys
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..communicators.base import CommunicatorBase

#: Manifest schema stamp (bump on layout-incompatible changes).
MANIFEST_SCHEMA = "chainermn_tpu.ckpt_manifest.v2"


def _atomic_write(directory: str, target: str, payload: bytes) -> None:
    """Write-then-rename so a crash mid-write never corrupts ``target``."""
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp_ckpt_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, target)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _to_host(tree):
    """Detach a pytree from devices: jax.Array → numpy on host."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
        tree)


def _crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


def _leaf_paths_and_shapes(state, layout: Optional[Dict[str, Any]],
                           world: int) -> List[Dict[str, Any]]:
    """``[{path, shape, dtype}]`` with LOGICAL shapes: a leaf the layout
    declares sharded on axis ``a`` has its local axis-``a`` extent
    multiplied by the world size (shards partition the logical array)."""
    layout = layout or {}
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        dotted = jax.tree_util.keystr(path)
        arr = np.asarray(leaf) if not isinstance(leaf, np.ndarray) else leaf
        shape = list(getattr(arr, "shape", ()))
        spec = layout.get(dotted, "replicated")
        if isinstance(spec, (list, tuple)) and spec and spec[0] == "sharded":
            ax = int(spec[1])
            if ax < len(shape):
                shape[ax] = shape[ax] * world
        out.append({"path": dotted, "shape": shape,
                    "dtype": str(getattr(arr, "dtype", type(leaf).__name__))})
    return out


def _layout_spec_tree(state, layout: Optional[Dict[str, Any]]):
    """Translate a dotted-path layout map into the per-leaf spec pytree
    :func:`~chainermn_tpu.parallel.reshard.reshard_host` consumes:
    ``None`` (replicated, the default), ``"per_rank"``, or an int axis."""
    layout = layout or {}

    def spec_of(dotted):
        spec = layout.get(dotted, "replicated")
        if spec in (None, "replicated"):
            return None
        if spec == "per_rank":
            return "per_rank"
        if isinstance(spec, (list, tuple)) and spec and spec[0] == "sharded":
            return int(spec[1])
        if isinstance(spec, int):
            return spec
        raise ValueError(f"unknown layout spec {spec!r} for {dotted!r}")

    paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_of(jax.tree_util.keystr(p)) for p, _ in paths])


class MultiNodeCheckpointer:
    """Sharded generation-based checkpointer with consistent auto-resume.

    Knobs (reference signature + one TPU addition):

    * ``cp_interval`` — trainer-extension save frequency, in iterations.
    * ``gc_interval`` — run GC once every this many ``save`` calls.
    * ``keep`` — how many newest generations GC retains (the reference
      conflated this with ``cp_interval`` [uv]; a separate knob avoids
      "checkpoint every 1000 iters" implying "keep 1000 generations").
    """

    def __init__(self, name: str, comm: CommunicatorBase, path: str,
                 cp_interval: int = 5, gc_interval: int = 5, keep: int = 5,
                 async_write: bool = True,
                 layout: Optional[Dict[str, Any]] = None,
                 manifest: bool = True):
        self.name = name
        self.comm = comm
        self.path = path
        self.cp_interval = int(cp_interval)
        self.gc_interval = int(gc_interval)
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError("keep must be >= 1 (GC may never delete the "
                             "newest generation)")
        self._saves_since_gc = 0
        self._async = bool(async_write)
        self._executor = None
        self._pending = None  # Future of the one in-flight write
        #: dotted leaf path → "replicated" (default) | "per_rank" |
        #: ["sharded", axis] — recorded in the generation manifest and
        #: consumed by the elastic-restore reshard (docs/ROBUSTNESS.md).
        self.layout = dict(layout or {})
        self._manifest = bool(manifest)
        #: How long the rank-0 owner waits for peer checksums before
        #: writing a (possibly partial) manifest.  Only the owner pays
        #: it, and only for peers that never publish — a skipped or dead
        #: peer costs one bounded wait, never a wedge.
        self.manifest_timeout_s = 5.0
        self._sum_prev_tag: Optional[str] = None
        # iteration of the last shard THIS process put on disk (the
        # preemption bundle reports it)
        self.last_saved_iteration: Optional[int] = None
        os.makedirs(path, exist_ok=True)

    # ---- naming ----
    @property
    def _process(self) -> int:
        return jax.process_index()

    @property
    def _nproc(self) -> int:
        return jax.process_count()

    def _filename(self, iteration: int, process: Optional[int] = None) -> str:
        p = self._process if process is None else process
        return os.path.join(
            self.path,
            f"{self.name}.iter{iteration:012d}.proc{p}of{self._nproc}")

    _PAT = re.compile(
        r"^(?P<name>.+)\.iter(?P<it>\d{12})\.proc(?P<proc>\d+)of(?P<nproc>\d+)$")

    def _local_files(self, any_world_size: bool = False) -> List[Tuple[int, str]]:
        """(iteration, filename) shards THIS process has on disk (matching
        the current world size unless ``any_world_size``)."""
        out = []
        for fn in os.listdir(self.path):
            m = self._PAT.match(fn)
            if (m and m.group("name") == self.name
                    and int(m.group("proc")) == self._process
                    and (any_world_size or int(m.group("nproc")) == self._nproc)):
                out.append((int(m.group("it")), os.path.join(self.path, fn)))
        return sorted(out)

    def _local_generations(self, any_world_size: bool = False) -> List[int]:
        return [it for it, _ in self._local_files(any_world_size)]

    # ---- manifest (format v2) ----
    def _manifest_path(self, iteration: int, nproc: Optional[int] = None
                       ) -> str:
        n = self._nproc if nproc is None else nproc
        return os.path.join(
            self.path,
            f"{self.name}.iter{iteration:012d}.world{n}.manifest.json")

    _MANIFEST_PAT = re.compile(
        r"^(?P<name>.+)\.iter(?P<it>\d{12})\.world(?P<n>\d+)"
        r"\.manifest\.json$")

    def _read_manifest(self, iteration: int, nproc: Optional[int] = None
                       ) -> Optional[Dict[str, Any]]:
        p = self._manifest_path(iteration, nproc)
        try:
            with open(p) as f:
                man = json.load(f)
        except (FileNotFoundError, ValueError, OSError):
            return None
        if man.get("schema") != MANIFEST_SCHEMA:
            return None
        return man

    def _write_manifest(self, iteration: int,
                        checksums: Dict[int, int],
                        leaves: List[Dict[str, Any]]) -> None:
        man = {
            "schema": MANIFEST_SCHEMA,
            "name": self.name,
            "iteration": iteration,
            "world_size": self._nproc,
            "kind": "proc",
            "layout": self.layout,
            "leaves": leaves,
            "checksums": {str(p): int(c) for p, c in checksums.items()},
        }
        _atomic_write(
            self.path, self._manifest_path(iteration),
            json.dumps(man, sort_keys=True, indent=1).encode())

    def _verify_shard(self, fname: str, manifest: Dict[str, Any],
                      shard_key: str) -> bool:
        """CRC the shard against the manifest; a missing manifest entry
        counts as unverifiable-but-accepted (v1 compat), a mismatch or an
        unreadable file as torn."""
        want = (manifest.get("checksums") or {}).get(shard_key)
        if want is None:
            return True
        try:
            with open(fname, "rb") as f:
                return _crc(f.read()) == int(want)
        except OSError:
            return False

    # ---- async writer plumbing ----
    def _join_writer(self) -> None:
        """Wait out the in-flight write; re-raise its error if it failed."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def _submit(self, fn, *args):
        from concurrent.futures import ThreadPoolExecutor

        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"ckpt-{self.name}")
        self._pending = self._executor.submit(fn, *args)

    def flush(self) -> None:
        """Block until the in-flight async write (if any) is on disk."""
        self._join_writer()

    # ---- save / load ----
    def save(self, state: Any, iteration: int) -> None:
        """Snapshot this process's shard of ``state`` at ``iteration``.

        Atomic per shard (tmp file + rename) so a crash mid-save never
        corrupts an older generation — the reference relied on the same
        write-then-rename discipline [uv].  With ``async_write`` (default)
        the device→host detach AND the pickle happen here, synchronously —
        serializing on the writer thread would capture live references to
        mutable state (iterator orders, log accumulators) that the train
        loop keeps mutating; only the disk IO is deferred.
        """
        host_state = _to_host(state)
        payload = pickle.dumps(host_state, protocol=pickle.HIGHEST_PROTOCOL)
        manifest_task = None
        if self._manifest:
            # NOT a gang collective: each process publishes its shard
            # checksum on the bounded best-effort side channel
            # (``allgather_obj_eventual``) and only the rank-0 owner —
            # the manifest writer — waits (``manifest_timeout_s``) to
            # collect them.  A peer that skips this generation or died
            # mid-step is simply absent from the manifest (its shard
            # loads unverified, v1-style); it can never wedge this
            # process's save — the seed's skipped-save gang test and the
            # preemption final save both depend on that.
            checksum = _crc(payload)
            tag = f"{self.name}.it{iteration}.w{self._nproc}"
            owner = self.comm.owns_rank(0)
            per_proc = self.comm.allgather_obj_eventual(
                tag, checksum,
                timeout_s=self.manifest_timeout_s if owner else 0.0,
                discard_tag=self._sum_prev_tag)
            self._sum_prev_tag = tag
            checksums = {int(p): int(c) for p, c in per_proc.items()}
            if owner:
                leaves = _leaf_paths_and_shapes(host_state, self.layout,
                                                self._nproc)
                manifest_task = (iteration, checksums, leaves)
        if not self._async:
            self._write(payload, iteration, manifest_task)
            return
        self._join_writer()  # bounded depth: one write in flight
        self._submit(self._write, payload, iteration, manifest_task)

    def _write(self, payload: bytes, iteration: int,
               manifest_task=None) -> None:
        _atomic_write(self.path, self._filename(iteration), payload)
        if manifest_task is not None:
            self._write_manifest(*manifest_task)
        self.last_saved_iteration = iteration
        self._saves_since_gc += 1
        if self._saves_since_gc >= self.gc_interval:
            self._gc()
            self._saves_since_gc = 0

    def _gc(self) -> None:
        """Drop all but the newest ``keep`` local generations (plus the
        manifests of dropped generations, if this process wrote them)."""
        gens = self._local_generations()
        for it in gens[:-self.keep]:
            try:
                os.unlink(self._filename(it))
            except FileNotFoundError:
                pass
            if self.comm.owns_rank(0):
                try:
                    os.unlink(self._manifest_path(it))
                except FileNotFoundError:
                    pass
        self._gc_other_worlds()

    def _gc_other_worlds(self) -> None:
        """After an elastic resume the OLD world's shards have no owning
        process in the new world (`_gc` above matches only
        ``proc{me}of{nproc}``), so a preempted n=8 job resumed at n=4
        would leak ranks 4-7's shards forever.  The rank-0 owner deletes
        other-world generations once a NEWER same-world save exists —
        `_gc` only runs after a save, and saves only happen once every
        process has passed ``maybe_load`` (training is collective), so
        nobody is still reading them."""
        if not self.comm.owns_rank(0) or self.last_saved_iteration is None:
            return
        newest = self.last_saved_iteration
        for fn in os.listdir(self.path):
            m = self._PAT.match(fn)
            if (m and m.group("name") == self.name
                    and int(m.group("nproc")) != self._nproc
                    and int(m.group("it")) <= newest):
                try:
                    os.unlink(os.path.join(self.path, fn))
                except FileNotFoundError:
                    pass
                continue
            m = self._MANIFEST_PAT.match(fn)
            if (m and m.group("name") == self.name
                    and int(m.group("n")) != self._nproc
                    and int(m.group("it")) <= newest):
                try:
                    os.unlink(os.path.join(self.path, fn))
                except FileNotFoundError:
                    pass

    def _consistent_generations(self) -> List[int]:
        """Generations every process has with a CHECKSUM-CLEAN local
        shard (set intersection over DCN).  A generation whose shard
        fails its manifest CRC — the torn write of a process killed
        mid-save — is excluded HERE, before the gang intersection, so
        every process falls back to the same previous consistent
        generation instead of unpickling garbage.  Generations without a
        manifest (v1 / ``manifest=False``) are accepted unverified."""
        local = set()
        for it, fname in self._local_files():
            man = self._read_manifest(it)
            if man is not None and not self._verify_shard(
                    fname, man, str(self._process)):
                print(f"[chainermn_tpu checkpoint] shard {fname} fails "
                      f"its manifest checksum (torn write?) — skipping "
                      f"generation {it}", file=sys.stderr, flush=True)
                continue
            local.add(it)
        all_lists = self.comm.allgather_obj(sorted(local))
        consistent = local
        for other in all_lists:
            consistent &= set(other)
        return sorted(consistent)

    # ---- elastic resume (format v2 + reshard_host) ----
    def _elastic_candidates(self) -> List[Tuple[int, int]]:
        """(iteration, old_world) pairs this process can FULLY restore
        from local/shared disk: a manifest exists for a DIFFERENT world
        size and every one of its shards is present and checksum-clean."""
        out = []
        for fn in os.listdir(self.path):
            m = self._MANIFEST_PAT.match(fn)
            if not m or m.group("name") != self.name:
                continue
            old_n = int(m.group("n"))
            it = int(m.group("it"))
            if old_n == self._nproc:
                continue
            man = self._read_manifest(it, old_n)
            if man is None:
                continue
            ok = True
            for p in range(old_n):
                shard = os.path.join(
                    self.path,
                    f"{self.name}.iter{it:012d}.proc{p}of{old_n}")
                if not (os.path.exists(shard)
                        and self._verify_shard(shard, man, str(p))):
                    ok = False
                    break
            if ok:
                out.append((it, old_n))
        return sorted(out)

    def _elastic_load(self, iteration: int, old_n: int) -> Any:
        """Read every old-world shard, re-partition via ``reshard_host``
        per the manifest layout, return THIS process's new shard."""
        from ..parallel.reshard import reshard_host

        man = self._read_manifest(iteration, old_n) or {}
        shards = []
        for p in range(old_n):
            shard = os.path.join(
                self.path,
                f"{self.name}.iter{iteration:012d}.proc{p}of{old_n}")
            with open(shard, "rb") as f:
                shards.append(pickle.load(f))
        layout = man.get("layout") or {}
        spec_tree = _layout_spec_tree(shards[0], layout)
        new_shards = reshard_host(shards, spec_tree, spec_tree, self._nproc)
        print(f"[chainermn_tpu checkpoint] elastic resume: generation "
              f"{iteration} resharded {old_n} -> {self._nproc} process(es)",
              file=sys.stderr, flush=True)
        return new_shards[self._process]

    def maybe_load(self, state: Any = None, elastic: bool = True
                   ) -> Tuple[Any, Optional[int]]:
        """Resume from the newest consistent generation, if any.

        Returns ``(state, iteration)``; ``(state, None)`` untouched when no
        consistent checkpoint exists (fresh start) — mirroring the
        reference's ``maybe_load`` no-op contract [uv].

        **Elastic** (format v2, default on): when the newest restorable
        generation was saved under a DIFFERENT world size, its shards are
        re-partitioned host-side per the manifest layout
        (:func:`~chainermn_tpu.parallel.reshard.reshard_host`) and every
        process receives its new-world shard — a preempted n=8 job
        resumes on the n=4 that survives.  Candidate agreement is
        collective (intersection of what every process can fully verify
        over the DCN object lane), so the gang can never split between a
        resumed and a fresh-started half.  Same-world generations win
        ties; a strictly NEWER other-world generation wins outright.

        If shards exist but nothing is restorable (an interrupted v1 save
        with nothing older, or manifest-less shards from another world
        size), every process raises the same error on gang-agreed
        information — loud and collective, exactly like the reference's
        same-rank-count requirement [uv], minus the cases v2 makes
        resumable.
        """
        self._join_writer()  # our newest shard must be on disk and visible
        gens = self._consistent_generations()
        newest_same = gens[-1] if gens else None
        newest_elastic: Optional[Tuple[int, int]] = None
        if elastic:
            cand_lists = self.comm.allgather_obj(self._elastic_candidates())
            agreed = set(map(tuple, cand_lists[0]))
            for other in cand_lists[1:]:
                agreed &= set(map(tuple, other))
            if agreed:
                newest_elastic = max(agreed)
        if newest_elastic is not None and (
                newest_same is None or newest_elastic[0] > newest_same):
            it, old_n = newest_elastic
            return self._elastic_load(it, old_n), it
        if newest_same is None:
            any_stale = any(self.comm.allgather_obj(
                bool(self._local_generations(any_world_size=True))))
            if any_stale:
                raise RuntimeError(
                    f"checkpoint shards for '{self.name}' exist in "
                    f"{self.path} but no generation is restorable across "
                    f"all {self._nproc} process(es) — an interrupted save "
                    "left only partial/torn shards, or the world size "
                    "changed and the shards carry no v2 manifest to "
                    "reshard from; resume with the original world size or "
                    "delete the stale shards (docs/ROBUSTNESS.md)")
            return state, None
        it = newest_same
        with open(self._filename(it), "rb") as f:
            loaded = pickle.load(f)
        return loaded, it

    def get_generations(self) -> List[int]:
        """Consistent generations currently resumable (newest last)."""
        self._join_writer()
        return self._consistent_generations()

    def finalize(self) -> None:
        """Delete every local shard (reference: cleanup on job teardown [uv]),
        including shards saved under a different world size.  Cleanup runs
        even when the last in-flight write failed — its error re-raises
        AFTER the contract is honored."""
        try:
            self._join_writer()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
            for _, path in self._local_files(any_world_size=True):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
            if self.comm.owns_rank(0):
                for fn in os.listdir(self.path):
                    m = self._MANIFEST_PAT.match(fn)
                    if m and m.group("name") == self.name:
                        try:
                            os.unlink(os.path.join(self.path, fn))
                        except FileNotFoundError:
                            pass

    # ---- trainer-extension face (chainermn_tpu.training) ----
    # When registering directly (``trainer.extend(checkpointer)``) the save
    # cadence comes from the TRAINER's trigger alone; ``cp_interval`` is only
    # this extension's default trigger period, never a second gate.
    trigger = property(lambda self: (self.cp_interval, "iteration"))

    def __call__(self, trainer) -> None:
        self.save(trainer.checkpoint_state(), trainer.iteration)


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    cp_interval: int = 5,
    gc_interval: int = 5,
    path: Optional[str] = None,
    keep: int = 5,
    async_write: bool = True,
    layout: Optional[Dict[str, Any]] = None,
    manifest: bool = True,
) -> MultiNodeCheckpointer:
    """Factory with the reference's signature (``create_multi_node_checkpointer``
    [uv]); ``path`` defaults to ``./{name}-checkpoints`` like the reference's
    cwd-relative default.  ``layout``/``manifest`` are the format-v2 knobs
    (elastic resume + torn-shard tolerance — see class docstring)."""
    if path is None:
        path = os.path.join(os.getcwd(), f"{name}-checkpoints")
    return MultiNodeCheckpointer(name, comm, path, cp_interval, gc_interval,
                                 keep, async_write, layout=layout,
                                 manifest=manifest)


def reshard_checkpoint(path: str, name: str, new_nproc: int,
                       iteration: Optional[int] = None,
                       source_process: int = 0) -> int:
    """Rewrite a checkpoint saved under one world size for another.

    Beyond-reference (the reference — and :meth:`maybe_load` — REQUIRE the
    original rank count): an offline tool for the common elastic case where
    per-process state is REPLICATED (params, optimizer state, trainer
    counters — everything the step builders keep replicated).  It takes
    ``source_process``'s shard of the newest old-world generation (or
    ``iteration``) and writes it as every one of the ``new_nproc`` shards.

    Contract: rank-SPECIFIC state inside the shard (iterator cursors, RNG
    per rank) is duplicated, not resharded — the multi-node iterator
    tolerates this (non-master ranks install the master's broadcast state),
    but anything else per-rank must be re-derived by the caller after
    resume.  Run this offline (no gang needed), then restart the job at the
    new world size.

    Returns the iteration rewritten.  Raises if no complete old-world
    generation exists.
    """
    pat = MultiNodeCheckpointer._PAT
    by_gen: dict = {}
    for fn in os.listdir(path):
        m = pat.match(fn)
        if m and m.group("name") == name:
            key = (int(m.group("it")), int(m.group("nproc")))
            by_gen.setdefault(key, set()).add(int(m.group("proc")))
    if new_nproc < 1:
        raise ValueError(f"new_nproc must be >= 1, got {new_nproc}")
    # superset, not equality: a stray shard with proc >= nproc must not
    # disqualify a generation whose required shards all exist
    complete = [(it, nproc) for (it, nproc), procs in by_gen.items()
                if procs >= set(range(nproc))
                and (iteration is None or it == iteration)]
    if not complete:
        raise RuntimeError(
            f"no complete generation for '{name}' in {path}"
            + (f" at iteration {iteration}" if iteration is not None else ""))
    it = max(i for i, _ in complete)
    worlds = sorted(n for i, n in complete if i == it)
    if len(worlds) > 1 and iteration is None:
        # Two complete generations at the SAME iteration under different
        # world sizes: picking one silently decides which payload wins.
        # Make the caller choose via iteration= + cleaning the stale set.
        raise RuntimeError(
            f"iteration {it} of '{name}' has complete checkpoints for "
            f"multiple world sizes {worlds}; remove the stale generation "
            f"or pass iteration= explicitly to confirm the newest one")
    old_nproc = worlds[-1]
    if not 0 <= source_process < old_nproc:
        raise ValueError(f"source_process {source_process} outside the old "
                         f"world size {old_nproc}")
    src = os.path.join(
        path, f"{name}.iter{it:012d}.proc{source_process}of{old_nproc}")
    with open(src, "rb") as f:
        payload = f.read()
    for p in range(new_nproc):
        _atomic_write(path, os.path.join(
            path, f"{name}.iter{it:012d}.proc{p}of{new_nproc}"), payload)
    return it
