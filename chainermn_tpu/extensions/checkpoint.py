"""Fault-tolerant distributed checkpointing.

Reference parity: ``chainermn/extensions/checkpoint.py ::
create_multi_node_checkpointer(name, comm, cp_interval, gc_interval, path)``
[uv] (SURVEY.md §2.6, §5 "failure detection / recovery") — each rank
snapshots its own shard of state, old generations are garbage-collected, and
``maybe_load`` auto-resumes from the newest generation that is *consistent
across all ranks* after a restart with the same world size.

TPU adaptation: sharding is per *controller process* (multi-controller JAX
has one process per host, vs one per GPU under MPI), and cross-process
consistency agreement rides the DCN object lane (``allgather_obj``) instead
of MPI.  State is any picklable pytree — train state, optimizer state, and
iterator ``state_dict`` all qualify; device arrays are pulled to host first
so a checkpoint never pins HBM.

Async writes (orbax-style, SURVEY.md §5 build note): ``save`` detaches the
state to host (the only device sync) and hands serialize+write to a
single background thread; the train loop continues immediately.  Depth is
bounded at one in-flight write (a new save waits out the previous one),
every read/consistency operation joins the writer first, and writer errors
re-raise at the next checkpoint call instead of vanishing.
"""

from __future__ import annotations

import os
import pickle
import re
import tempfile
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from ..communicators.base import CommunicatorBase


def _atomic_write(directory: str, target: str, payload: bytes) -> None:
    """Write-then-rename so a crash mid-write never corrupts ``target``."""
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp_ckpt_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, target)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _to_host(tree):
    """Detach a pytree from devices: jax.Array → numpy on host."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
        tree)


class MultiNodeCheckpointer:
    """Sharded generation-based checkpointer with consistent auto-resume.

    Knobs (reference signature + one TPU addition):

    * ``cp_interval`` — trainer-extension save frequency, in iterations.
    * ``gc_interval`` — run GC once every this many ``save`` calls.
    * ``keep`` — how many newest generations GC retains (the reference
      conflated this with ``cp_interval`` [uv]; a separate knob avoids
      "checkpoint every 1000 iters" implying "keep 1000 generations").
    """

    def __init__(self, name: str, comm: CommunicatorBase, path: str,
                 cp_interval: int = 5, gc_interval: int = 5, keep: int = 5,
                 async_write: bool = True):
        self.name = name
        self.comm = comm
        self.path = path
        self.cp_interval = int(cp_interval)
        self.gc_interval = int(gc_interval)
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError("keep must be >= 1 (GC may never delete the "
                             "newest generation)")
        self._saves_since_gc = 0
        self._async = bool(async_write)
        self._executor = None
        self._pending = None  # Future of the one in-flight write
        os.makedirs(path, exist_ok=True)

    # ---- naming ----
    @property
    def _process(self) -> int:
        return jax.process_index()

    @property
    def _nproc(self) -> int:
        return jax.process_count()

    def _filename(self, iteration: int, process: Optional[int] = None) -> str:
        p = self._process if process is None else process
        return os.path.join(
            self.path,
            f"{self.name}.iter{iteration:012d}.proc{p}of{self._nproc}")

    _PAT = re.compile(
        r"^(?P<name>.+)\.iter(?P<it>\d{12})\.proc(?P<proc>\d+)of(?P<nproc>\d+)$")

    def _local_files(self, any_world_size: bool = False) -> List[Tuple[int, str]]:
        """(iteration, filename) shards THIS process has on disk (matching
        the current world size unless ``any_world_size``)."""
        out = []
        for fn in os.listdir(self.path):
            m = self._PAT.match(fn)
            if (m and m.group("name") == self.name
                    and int(m.group("proc")) == self._process
                    and (any_world_size or int(m.group("nproc")) == self._nproc)):
                out.append((int(m.group("it")), os.path.join(self.path, fn)))
        return sorted(out)

    def _local_generations(self, any_world_size: bool = False) -> List[int]:
        return [it for it, _ in self._local_files(any_world_size)]

    # ---- async writer plumbing ----
    def _join_writer(self) -> None:
        """Wait out the in-flight write; re-raise its error if it failed."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def _submit(self, fn, *args):
        from concurrent.futures import ThreadPoolExecutor

        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"ckpt-{self.name}")
        self._pending = self._executor.submit(fn, *args)

    def flush(self) -> None:
        """Block until the in-flight async write (if any) is on disk."""
        self._join_writer()

    # ---- save / load ----
    def save(self, state: Any, iteration: int) -> None:
        """Snapshot this process's shard of ``state`` at ``iteration``.

        Atomic per shard (tmp file + rename) so a crash mid-save never
        corrupts an older generation — the reference relied on the same
        write-then-rename discipline [uv].  With ``async_write`` (default)
        the device→host detach AND the pickle happen here, synchronously —
        serializing on the writer thread would capture live references to
        mutable state (iterator orders, log accumulators) that the train
        loop keeps mutating; only the disk IO is deferred.
        """
        payload = pickle.dumps(_to_host(state),
                               protocol=pickle.HIGHEST_PROTOCOL)
        if not self._async:
            self._write(payload, iteration)
            return
        self._join_writer()  # bounded depth: one write in flight
        self._submit(self._write, payload, iteration)

    def _write(self, payload: bytes, iteration: int) -> None:
        _atomic_write(self.path, self._filename(iteration), payload)
        self._saves_since_gc += 1
        if self._saves_since_gc >= self.gc_interval:
            self._gc()
            self._saves_since_gc = 0

    def _gc(self) -> None:
        """Drop all but the newest ``keep`` local generations."""
        gens = self._local_generations()
        for it in gens[:-self.keep]:
            try:
                os.unlink(self._filename(it))
            except FileNotFoundError:
                pass

    def _consistent_generations(self) -> List[int]:
        """Generations every process has (set intersection over DCN)."""
        local = set(self._local_generations())
        all_lists = self.comm.allgather_obj(sorted(local))
        consistent = local
        for other in all_lists:
            consistent &= set(other)
        return sorted(consistent)

    def maybe_load(self, state: Any = None) -> Tuple[Any, Optional[int]]:
        """Resume from the newest consistent generation, if any.

        Returns ``(state, iteration)``; ``(state, None)`` untouched when no
        consistent checkpoint exists (fresh start) — mirroring the
        reference's ``maybe_load`` no-op contract [uv].  If shards exist but
        NO generation is consistent across every process (world-size change,
        or a save that crashed partway through the gang with nothing older
        to fall back to), every process raises the same error — the decision
        is taken on gang-agreed information so the job can never split into
        crashed and fresh-started halves (the reference required same rank
        count [uv]; here it is enforced, loudly and collectively).
        """
        self._join_writer()  # our newest shard must be on disk and visible
        gens = self._consistent_generations()
        if not gens:
            any_stale = any(self.comm.allgather_obj(
                bool(self._local_generations(any_world_size=True))))
            if any_stale:
                raise RuntimeError(
                    f"checkpoint shards for '{self.name}' exist in "
                    f"{self.path} but no generation is consistent across "
                    f"all {self._nproc} process(es) — the world size "
                    "changed, or an interrupted save left only partial "
                    "shards; resume with the original world size or delete "
                    "the stale shards")
            return state, None
        it = gens[-1]
        with open(self._filename(it), "rb") as f:
            loaded = pickle.load(f)
        return loaded, it

    def get_generations(self) -> List[int]:
        """Consistent generations currently resumable (newest last)."""
        self._join_writer()
        return self._consistent_generations()

    def finalize(self) -> None:
        """Delete every local shard (reference: cleanup on job teardown [uv]),
        including shards saved under a different world size.  Cleanup runs
        even when the last in-flight write failed — its error re-raises
        AFTER the contract is honored."""
        try:
            self._join_writer()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
            for _, path in self._local_files(any_world_size=True):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass

    # ---- trainer-extension face (chainermn_tpu.training) ----
    # When registering directly (``trainer.extend(checkpointer)``) the save
    # cadence comes from the TRAINER's trigger alone; ``cp_interval`` is only
    # this extension's default trigger period, never a second gate.
    trigger = property(lambda self: (self.cp_interval, "iteration"))

    def __call__(self, trainer) -> None:
        self.save(trainer.checkpoint_state(), trainer.iteration)


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    cp_interval: int = 5,
    gc_interval: int = 5,
    path: Optional[str] = None,
    keep: int = 5,
    async_write: bool = True,
) -> MultiNodeCheckpointer:
    """Factory with the reference's signature (``create_multi_node_checkpointer``
    [uv]); ``path`` defaults to ``./{name}-checkpoints`` like the reference's
    cwd-relative default."""
    if path is None:
        path = os.path.join(os.getcwd(), f"{name}-checkpoints")
    return MultiNodeCheckpointer(name, comm, path, cp_interval, gc_interval,
                                 keep, async_write)


def reshard_checkpoint(path: str, name: str, new_nproc: int,
                       iteration: Optional[int] = None,
                       source_process: int = 0) -> int:
    """Rewrite a checkpoint saved under one world size for another.

    Beyond-reference (the reference — and :meth:`maybe_load` — REQUIRE the
    original rank count): an offline tool for the common elastic case where
    per-process state is REPLICATED (params, optimizer state, trainer
    counters — everything the step builders keep replicated).  It takes
    ``source_process``'s shard of the newest old-world generation (or
    ``iteration``) and writes it as every one of the ``new_nproc`` shards.

    Contract: rank-SPECIFIC state inside the shard (iterator cursors, RNG
    per rank) is duplicated, not resharded — the multi-node iterator
    tolerates this (non-master ranks install the master's broadcast state),
    but anything else per-rank must be re-derived by the caller after
    resume.  Run this offline (no gang needed), then restart the job at the
    new world size.

    Returns the iteration rewritten.  Raises if no complete old-world
    generation exists.
    """
    pat = MultiNodeCheckpointer._PAT
    by_gen: dict = {}
    for fn in os.listdir(path):
        m = pat.match(fn)
        if m and m.group("name") == name:
            key = (int(m.group("it")), int(m.group("nproc")))
            by_gen.setdefault(key, set()).add(int(m.group("proc")))
    if new_nproc < 1:
        raise ValueError(f"new_nproc must be >= 1, got {new_nproc}")
    # superset, not equality: a stray shard with proc >= nproc must not
    # disqualify a generation whose required shards all exist
    complete = [(it, nproc) for (it, nproc), procs in by_gen.items()
                if procs >= set(range(nproc))
                and (iteration is None or it == iteration)]
    if not complete:
        raise RuntimeError(
            f"no complete generation for '{name}' in {path}"
            + (f" at iteration {iteration}" if iteration is not None else ""))
    it = max(i for i, _ in complete)
    worlds = sorted(n for i, n in complete if i == it)
    if len(worlds) > 1 and iteration is None:
        # Two complete generations at the SAME iteration under different
        # world sizes: picking one silently decides which payload wins.
        # Make the caller choose via iteration= + cleaning the stale set.
        raise RuntimeError(
            f"iteration {it} of '{name}' has complete checkpoints for "
            f"multiple world sizes {worlds}; remove the stale generation "
            f"or pass iteration= explicitly to confirm the newest one")
    old_nproc = worlds[-1]
    if not 0 <= source_process < old_nproc:
        raise ValueError(f"source_process {source_process} outside the old "
                         f"world size {old_nproc}")
    src = os.path.join(
        path, f"{name}.iter{it:012d}.proc{source_process}of{old_nproc}")
    with open(src, "rb") as f:
        payload = f.read()
    for p in range(new_nproc):
        _atomic_write(path, os.path.join(
            path, f"{name}.iter{it:012d}.proc{p}of{new_nproc}"), payload)
    return it
