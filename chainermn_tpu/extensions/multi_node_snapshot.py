"""Replica-set-aware snapshots: write once per GROUP, not once per rank.

Reference parity: ``chainermn/extensions/multi_node_snapshot.py ::
multi_node_snapshot(comm, snapshot, replica_sets)`` [uv] (SURVEY.md §2.6,
merged-era) — when training is data-parallel, every rank in a replica set
holds IDENTICAL state, so writing one snapshot per rank multiplies the
checkpoint IO and storage by the set size for nothing.  The wrapper makes
only the first rank of each replica set write, and on resume the loaded
state fans out to the rest of the set.

TPU adaptation: comm ranks are devices and a controller PROCESS may own
many of them (all of them, single-controller).  Shards are therefore
written at replica-SET granularity (``.set{i}of{n}`` files) by the process
owning the set's lead rank, and the restore fan-out inside a set rides
``split(...)`` sub-communicators' DCN object lane (``bcast_obj``) instead
of MPI — shared filesystems are NOT assumed.  Ranks absent from
``replica_sets`` form singleton sets, exactly the reference's default.

Composition, not reimplementation: the wrapper borrows the
:class:`~..extensions.checkpoint.MultiNodeCheckpointer` it is given for
its name, path, trigger cadence and write discipline (atomic
write-then-rename), and overrides only WHO writes and HOW a shard is
located on resume.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..communicators.base import CommunicatorBase
from .checkpoint import (MANIFEST_SCHEMA, MultiNodeCheckpointer,
                         _atomic_write, _crc, _leaf_paths_and_shapes,
                         _to_host)


def _normalize_sets(replica_sets: Sequence[Sequence[int]],
                    size: int) -> List[List[int]]:
    """Validate + complete the partition: listed sets must be disjoint and
    in range; unlisted ranks become singleton sets (reference default)."""
    seen: set = set()
    sets: List[List[int]] = []
    for s in replica_sets:
        s = sorted(int(r) for r in s)
        if not s:
            raise ValueError("empty replica set")
        for r in s:
            if not 0 <= r < size:
                raise ValueError(f"rank {r} outside world size {size}")
            if r in seen:
                raise ValueError(f"rank {r} appears in two replica sets")
            seen.add(r)
        sets.append(s)
    for r in range(size):
        if r not in seen:
            sets.append([r])
    return sorted(sets)


class MultiNodeSnapshot:
    """The wrapped extension.  ``save``/``maybe_load``/trainer-``__call__``
    mirror :class:`MultiNodeCheckpointer`'s faces."""

    def __init__(self, comm: CommunicatorBase,
                 snapshot: MultiNodeCheckpointer,
                 replica_sets: Sequence[Sequence[int]]):
        self.comm = comm
        self.ckpt = snapshot
        self.sets = _normalize_sets(replica_sets, comm.size)
        self._set_of_rank = {r: i for i, s in enumerate(self.sets) for r in s}
        # the process's OWN set: the one holding its lead rank (the state a
        # process snapshots is process-wide, so its ranks must not straddle
        # sets in multi-controller — the one-process case owns everything
        # and is exempt by construction)
        owned = [r for r in range(comm.size)
                 if getattr(comm, "owns_rank", lambda _r: True)(r)]
        my_sets = {self._set_of_rank[r] for r in owned}
        if len(my_sets) > 1 and len(owned) != comm.size:
            raise ValueError(
                f"process owns ranks {owned} spanning replica sets "
                f"{sorted(my_sets)}; replica sets must align with process "
                "boundaries (each process's ranks inside ONE set)")
        self.set_id = self._set_of_rank[comm.rank]
        # sets this process WRITES: those whose lead rank it owns
        self._writer_sets = [i for i, s in enumerate(self.sets)
                             if getattr(comm, "owns_rank",
                                        lambda _r: True)(min(s))]

    # ---- naming ----
    @property
    def _nsets(self) -> int:
        return len(self.sets)

    def _filename(self, iteration: int, set_id: int) -> str:
        return os.path.join(
            self.ckpt.path,
            f"{self.ckpt.name}.iter{iteration:012d}"
            f".set{set_id}of{self._nsets}")

    _PAT = re.compile(
        r"^(?P<name>.+)\.iter(?P<it>\d{12})\.set(?P<sid>\d+)of(?P<n>\d+)$")

    # ---- manifest (same format-v2 sidecar as MultiNodeCheckpointer,
    # kind="set": one checksum per replica SET, not per process) ----
    def _manifest_path(self, iteration: int) -> str:
        return os.path.join(
            self.ckpt.path,
            f"{self.ckpt.name}.iter{iteration:012d}"
            f".sets{self._nsets}.manifest.json")

    def _read_manifest(self, iteration: int) -> Optional[Dict[str, Any]]:
        try:
            with open(self._manifest_path(iteration)) as f:
                man = json.load(f)
        except (FileNotFoundError, ValueError, OSError):
            return None
        return man if man.get("schema") == MANIFEST_SCHEMA else None

    def _verify(self, iteration: int, set_id: int) -> bool:
        """Shard vs manifest CRC; manifest-less generations pass (v1)."""
        man = self._read_manifest(iteration)
        if man is None:
            return True
        want = (man.get("checksums") or {}).get(str(set_id))
        if want is None:
            return True
        try:
            with open(self._filename(iteration, set_id), "rb") as f:
                return _crc(f.read()) == int(want)
        except OSError:
            return False

    def _visible_generations(self, set_id: int,
                             any_layout: bool = False) -> List[int]:
        out = []
        for fn in os.listdir(self.ckpt.path):
            m = self._PAT.match(fn)
            if (m and m.group("name") == self.ckpt.name
                    and (any_layout or (int(m.group("sid")) == set_id
                                        and int(m.group("n")) == self._nsets))):
                it = int(m.group("it"))
                if not any_layout and not self._verify(it, set_id):
                    print(f"[chainermn_tpu snapshot] set shard "
                          f"{self._filename(it, set_id)} fails its "
                          f"manifest checksum — skipping generation {it}",
                          file=sys.stderr, flush=True)
                    continue
                out.append(it)
        return sorted(out)

    # ---- save / load ----
    def save(self, state: Any, iteration: int) -> None:
        """One atomic shard per replica set this process leads — a pure-DP
        job with replica sets of size G does 1/G of the per-rank IO.

        Write discipline is the wrapped checkpointer's, really borrowed:
        the detach+pickle happens here synchronously (mutable state must
        not race the train loop), the disk IO rides the checkpointer's
        one-deep async writer thread when it was built with
        ``async_write``, and its ``keep``/``gc_interval`` knobs govern
        the wrapper's own ``.setXofY`` generations."""
        host_state = _to_host(state) if self._writer_sets else None
        payload = (pickle.dumps(host_state,
                                protocol=pickle.HIGHEST_PROTOCOL)
                   if self._writer_sets else None)
        manifest_task = None
        if self.ckpt._manifest:
            # NOT a gang collective (same discipline as the per-process
            # checkpointer): every process publishes its set-id → shard
            # checksum map (non-writers publish an empty one) on the
            # bounded best-effort side channel, and only the rank-0
            # owner — always a writer, rank 0 leads its own set — waits
            # to collect before writing the kind="set" manifest.  A dead
            # or skipping peer's sets go unverified, never wedge a save.
            mine = ({sid: _crc(payload) for sid in self._writer_sets}
                    if payload is not None else {})
            owner = self.comm.owns_rank(0)
            tag = f"{self.ckpt.name}.sets{self._nsets}.it{iteration}"
            per_proc = self.comm.allgather_obj_eventual(
                tag, mine,
                timeout_s=self.ckpt.manifest_timeout_s if owner else 0.0,
                discard_tag=self.ckpt._sum_prev_tag)
            self.ckpt._sum_prev_tag = tag
            checksums: Dict[int, int] = {}
            for entry in per_proc.values():
                checksums.update({int(k): int(v)
                                  for k, v in (entry or {}).items()})
            if owner:
                manifest_task = {
                    "schema": MANIFEST_SCHEMA,
                    "name": self.ckpt.name,
                    "iteration": iteration,
                    "world_size": self._nsets,
                    "kind": "set",
                    "layout": self.ckpt.layout,
                    "leaves": _leaf_paths_and_shapes(
                        host_state, self.ckpt.layout, self._nsets),
                    "checksums": {str(k): v for k, v in checksums.items()},
                }
        if not self._writer_sets:
            return
        if not self.ckpt._async:
            self._write(payload, iteration, manifest_task)
            return
        self.ckpt._join_writer()  # bounded depth: one write in flight
        self.ckpt._submit(self._write, payload, iteration, manifest_task)

    def _write(self, payload: bytes, iteration: int,
               manifest_task=None) -> None:
        for sid in self._writer_sets:
            _atomic_write(self.ckpt.path, self._filename(iteration, sid),
                          payload)
        if manifest_task is not None:
            _atomic_write(
                self.ckpt.path, self._manifest_path(iteration),
                json.dumps(manifest_task, sort_keys=True, indent=1).encode())
        self.ckpt.last_saved_iteration = iteration
        self.ckpt._saves_since_gc += 1
        if self.ckpt._saves_since_gc >= self.ckpt.gc_interval:
            self._gc()
            self.ckpt._saves_since_gc = 0

    def _gc(self) -> None:
        """Drop all but the newest ``keep`` generations of OWNED sets."""
        for sid in self._writer_sets:
            for it in self._visible_generations(sid)[:-self.ckpt.keep]:
                try:
                    os.unlink(self._filename(it, sid))
                except FileNotFoundError:
                    pass
                if self.comm.owns_rank(0):
                    try:
                        os.unlink(self._manifest_path(it))
                    except FileNotFoundError:
                        pass

    def flush(self) -> None:
        """Block until the in-flight async write (if any) is on disk."""
        self.ckpt._join_writer()

    def maybe_load(self, state: Any = None) -> Tuple[Any, Optional[int]]:
        """Newest generation every process's set can produce, fanned out
        within each set: the lead process reads the shard, the rest of the
        set receive it over the split sub-communicator's object lane.

        Shards-exist-but-nothing-consistent fails loudly and collectively,
        exactly like :meth:`MultiNodeCheckpointer.maybe_load` — a silent
        fresh start after a partial gang save would split the job into
        crashed and restarted halves."""
        self.ckpt._join_writer()  # our newest shards must be visible
        local = set(self._visible_generations(self.set_id))
        gens = set.intersection(
            *map(set, self.comm.allgather_obj(sorted(local))))
        if not gens:
            # stale = ANY snapshot shard of this name, including ones from
            # a different replica-set layout (mirrors checkpoint.py's
            # any_world_size probe)
            any_stale = any(self.comm.allgather_obj(bool(
                self._visible_generations(self.set_id, any_layout=True))))
            if any_stale:
                raise RuntimeError(
                    f"replica-set snapshot shards for '{self.ckpt.name}' "
                    f"exist in {self.ckpt.path} but no generation is "
                    f"consistent across all {self._nsets} replica set(s) — "
                    "an interrupted save left partial shards, or the "
                    "replica-set layout changed; restore the original "
                    "layout or delete the stale shards")
            return state, None
        it = max(gens)
        subs = self.comm.split([self._set_of_rank[r]
                                for r in range(self.comm.size)])
        sub = subs[self.set_id] if isinstance(subs, dict) else subs
        payload = None
        if self.set_id in self._writer_sets:
            with open(self._filename(it, self.set_id), "rb") as f:
                payload = f.read()
        payload = sub.bcast_obj(payload, root=0)
        return pickle.loads(payload), it

    # ---- trainer-extension face ----
    trigger = property(lambda self: self.ckpt.trigger)

    def __call__(self, trainer) -> None:
        self.save(trainer.checkpoint_state(), trainer.iteration)


def multi_node_snapshot(comm: CommunicatorBase,
                        snapshot: MultiNodeCheckpointer,
                        replica_sets: Sequence[Sequence[int]]
                        ) -> MultiNodeSnapshot:
    """Factory with the reference's signature
    (``multi_node_snapshot(comm, snapshot, replica_sets)`` [uv])."""
    return MultiNodeSnapshot(comm, snapshot, replica_sets)
