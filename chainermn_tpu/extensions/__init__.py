"""Training-loop extensions (reference: ``chainermn/extensions/`` — SURVEY.md §2.6)."""

from .allreduce_persistent import AllreducePersistent, allreduce_persistent  # noqa: F401
from .checkpoint import (  # noqa: F401
    MANIFEST_SCHEMA,
    MultiNodeCheckpointer,
    create_multi_node_checkpointer,
    reshard_checkpoint,
)
from .multi_node_snapshot import (  # noqa: F401
    MultiNodeSnapshot,
    multi_node_snapshot,
)
from .gang import GangReconfig, SelfHealingGang  # noqa: F401
from .observation_aggregator import (  # noqa: F401
    ObservationAggregator,
    aggregate_observations,
)
from .preemption import PreemptionExit, PreemptionHandler  # noqa: F401
from .watchdog import Watchdog  # noqa: F401

__all__ = [
    "GangReconfig",
    "SelfHealingGang",
    "AllreducePersistent",
    "allreduce_persistent",
    "MANIFEST_SCHEMA",
    "MultiNodeCheckpointer",
    "create_multi_node_checkpointer",
    "reshard_checkpoint",
    "MultiNodeSnapshot",
    "multi_node_snapshot",
    "ObservationAggregator",
    "aggregate_observations",
    "PreemptionExit",
    "PreemptionHandler",
    "Watchdog",
]
