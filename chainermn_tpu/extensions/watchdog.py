"""Hang/deadlock detection for distributed training.

SURVEY.md §5: the reference had deadlock *mitigation* only — the global
except hook turns a raised exception into ``MPI_Abort``, but a rank stuck
inside a collective raises nothing and the gang hangs silently forever
(the classic NCCL failure mode; same story for a wedged DCN transfer).

This extension closes that gap: a daemon thread watches the wall-clock gap
since the last completed training step and, when it exceeds ``timeout``,
dumps every Python thread's stack (so the hang site is in the log) and
aborts the process loudly — by default through the same
coordinator-shutdown path as :mod:`chainermn_tpu.global_except_hook`, so
one hung rank kills the whole gang instead of wedging it.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional


def _default_abort(gap: float, timeout: float) -> None:
    print(f"[chainermn_tpu watchdog] no step completed for {gap:.0f}s "
          f"(timeout {timeout:.0f}s) — dumping stacks and aborting the gang",
          file=sys.stderr, flush=True)
    faulthandler.dump_traceback(file=sys.stderr)
    try:
        import jax
        jax.distributed.shutdown()
    except Exception:
        pass
    os._exit(43)


class Watchdog:
    """Abort the job if no training step completes within ``timeout``.

    Register like any trainer extension; ``observe`` (called every
    iteration) feeds the heartbeat, and the watcher ALSO reads the
    trainer's ``last_progress`` stamp, which the loop updates after the
    step and after every individual extension — so a slow-but-progressing
    extension pass (a long eval, a checkpoint flush) never false-triggers;
    only ONE unit of work stuck for longer than ``timeout`` fires.

    ``action(gap, timeout)`` overrides the abort for testing or custom
    escalation; the default kills the process (and with it the coordinator
    session, so the rest of the gang dies loudly rather than waiting in a
    collective).  The timer arms at the FIRST completed unit of work and
    disarms at ``finalize`` (and on the trainer's exception path) — setup
    and the first step's arbitrarily-long XLA compile cannot false-trigger.

    Evidence flush (ISSUE 2): before ``action`` runs, the watchdog
    best-effort dumps the stall evidence to ``dump_dir`` (default: the
    trainer's ``out`` directory) — a final trace export
    (``watchdog_trace.json``, rank-sharded when ``rank`` is given) and a
    ``watchdog_health.json`` :func:`observability.export.health_snapshot`
    carrying the comm ledger, span summary, and any :class:`HealthMonitor`
    findings.  The dump runs in a side thread bounded by
    ``flush_timeout`` seconds, so a wedged filesystem cannot turn the
    abort path into a second hang; whatever was written survives the
    ``os._exit``.
    """

    trigger = (1, "iteration")
    priority = 10_000  # heartbeat first, before any slow extension runs
    finalize_on_error = True  # the trainer disarms us when run() unwinds —
    # an armed watchdog would os._exit a process saving crash diagnostics

    def __init__(self, timeout: float = 600.0,
                 action: Optional[Callable[[float, float], None]] = None,
                 poll_interval: Optional[float] = None,
                 dump_dir: Optional[str] = None,
                 monitor=None, rank: Optional[int] = None,
                 flush_timeout: float = 10.0):
        if timeout <= 0:
            raise ValueError(f"timeout must be positive, got {timeout}")
        self.timeout = float(timeout)
        self.action = action or _default_abort
        self.poll_interval = poll_interval or max(self.timeout / 4, 0.05)
        self.dump_dir = dump_dir
        self.monitor = monitor
        self.rank = rank
        self.flush_timeout = float(flush_timeout)
        self._last = None
        self._trainer = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- extension surface --
    def initialize(self, trainer) -> None:
        # Armed only from the FIRST completed unit of work: the first
        # step's XLA compile can legitimately exceed any hang timeout
        # (big SPMD programs take many minutes), so the clock must not
        # start at initialize time.
        self._trainer = trainer
        self._last = None
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="chainermn-tpu-watchdog", daemon=True)
        self._thread.start()

    def observe(self, trainer) -> None:
        self._trainer = trainer
        self._last = time.monotonic()

    def __call__(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- the watcher --
    def _heartbeat(self) -> Optional[float]:
        """Most recent sign of life: our own observe stamp or the trainer's
        per-unit progress stamp, whichever is newer."""
        beats = [self._last]
        progress = getattr(self._trainer, "last_progress", None)
        if progress is not None:
            beats.append(progress)
        beats = [b for b in beats if b is not None]
        return max(beats) if beats else None

    def _dump_evidence(self, gap: float) -> None:
        """Write the stall evidence (trace flush + health snapshot) to
        disk — runs on a side thread, bounded by ``flush_timeout``."""
        import json

        from ..observability import export as _export
        from ..observability import trace as _trace

        out = self.dump_dir or getattr(self._trainer, "out", None)
        if out is None:
            print("[chainermn_tpu watchdog] no dump_dir/trainer.out — "
                  "skipping evidence files", file=sys.stderr, flush=True)
            return
        os.makedirs(out, exist_ok=True)
        snap = _export.health_snapshot(self._trainer, monitor=self.monitor)
        snap["watchdog"] = {"gap_s": round(gap, 1),
                            "timeout_s": self.timeout,
                            "last_phase": getattr(self._trainer,
                                                  "last_phase", None)}
        health_path = os.path.join(out, "watchdog_health.json")
        if self.rank is not None:
            # rank-sharded like the trace: a gang stall fires every
            # rank's watchdog near-simultaneously into the SAME dump_dir,
            # and last-writer-wins would erase exactly the per-rank
            # attribution this dump exists for
            from ..observability.aggregate import shard_path
            health_path = shard_path(health_path, self.rank)
        tmp = f"{health_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=2, default=str)
        os.replace(tmp, health_path)
        wrote = [health_path]
        tr = _trace.get_tracer()
        if tr.enabled:
            trace_path = os.path.join(out, "watchdog_trace.json")
            tr.export_chrome_trace(trace_path, rank=self.rank)
            wrote.append(trace_path if self.rank is None else
                         "rank-sharded " + trace_path)
        # Full debug bundle (flight ring + providers + env — ISSUE 5):
        # the postmortem artifact scripts/explain_bundle.py renders.
        from ..observability import flight as _flight
        _flight.note("watchdog_abort", gap_s=round(gap, 1),
                     timeout_s=self.timeout,
                     last_phase=getattr(self._trainer, "last_phase", None))
        bundle = _flight.dump_bundle(
            out, "watchdog_abort", trainer=self._trainer,
            monitor=self.monitor, rank=self.rank,
            extra={"gap_s": round(gap, 1), "timeout_s": self.timeout})
        if bundle is not None:
            wrote.append(bundle)
        print(f"[chainermn_tpu watchdog] stall evidence written: "
              f"{', '.join(wrote)}", file=sys.stderr, flush=True)

    def _flush_before_abort(self, gap: float) -> None:
        """Best-effort, time-bounded evidence dump; never raises — the
        abort must proceed even if the dump wedges or explodes."""
        def run():
            try:
                self._dump_evidence(gap)
            except Exception as e:
                print(f"[chainermn_tpu watchdog] evidence dump failed: "
                      f"{e!r}", file=sys.stderr, flush=True)

        t = threading.Thread(target=run, name="chainermn-tpu-watchdog-dump",
                             daemon=True)
        t.start()
        t.join(timeout=self.flush_timeout)
        if t.is_alive():
            print(f"[chainermn_tpu watchdog] evidence dump still running "
                  f"after {self.flush_timeout:.0f}s — aborting anyway",
                  file=sys.stderr, flush=True)

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            last = self._heartbeat()
            if last is None:
                continue
            gap = time.monotonic() - last
            if gap > self.timeout:
                # Name the last COMPLETED unit of work so the stall
                # report says WHERE the job wedged (the stuck unit is
                # whatever comes after it) — fed by the trainer's phase
                # stamps (observability step-breakdown layer).
                phase = getattr(self._trainer, "last_phase", None)
                if phase is not None:
                    print(f"[chainermn_tpu watchdog] last completed "
                          f"phase: {phase} at iteration "
                          f"{getattr(self._trainer, 'iteration', '?')}",
                          file=sys.stderr, flush=True)
                # Evidence first (bounded): the default action os._exits,
                # and the trace buffer/comm ledger live only in memory.
                self._flush_before_abort(gap)
                self.action(gap, self.timeout)
                return

    # resume contract: a watchdog carries no durable state
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        self._last = time.monotonic() if self._thread is not None else None
