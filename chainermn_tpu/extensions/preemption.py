"""Bounded-grace preemption handling: SIGTERM → checkpoint, bundle, exit 0.

Cloud schedulers (GKE node drains, TPU maintenance events, spot
reclamation) deliver SIGTERM with a grace window and then SIGKILL.  The
flight recorder's stock SIGTERM path (``observability.flight
.install_signal_handlers``) dumps a debug bundle and dies with the
default disposition — correct for a crash investigation, wrong for a
preemption: the job loses every step since the last periodic checkpoint
and the exit code reads as a failure.

:class:`PreemptionHandler` upgrades that path into the elastic story
(ISSUE 8, docs/ROBUSTNESS.md):

1. The signal handler only sets a flag and stamps a deadline — all real
   work happens at the next step boundary, on the main thread, where
   trainer state is consistent.
2. The train loop (via the extension ``observe`` hook, or an explicit
   :meth:`check` in hand-rolled loops) notices the flag, saves one final
   checkpoint generation through the v2 manifest path (so a restart on a
   DIFFERENT world size reshards and resumes exactly), books the save
   overhead into the :class:`~..observability.slo.GoodputLedger`'s
   ``checkpoint`` bucket (overhead is attributed, not vanished), dumps a
   ``preempt`` flight bundle recording the grace budget used and the
   generation saved, and exits 0 — a preempted job is a SUCCESS to the
   scheduler, which is what makes it reschedule instead of backoff.
3. A grace watchdog thread guarantees BOUNDED death: if the step never
   reaches a boundary (wedged collective, giant compile), the deadline
   fires a bundle explaining why nothing was saved and still exits 0.

``scripts/explain_bundle.py`` renders the resulting bundle into the
operator view: reason ``preempt``, grace used, generation saved (or why
not), and the elastic resume hint.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Optional

from ..observability import flight as _flight


class PreemptionExit(SystemExit):
    """Graceful preemption exit (code 0).  A ``SystemExit`` subclass so
    the Trainer's exception path disarms liveness monitors
    (``finalize_on_error``) WITHOUT running full finalize — the
    checkpointer must keep the shards a resume needs."""

    def __init__(self, generation: Optional[int] = None):
        self.generation = generation
        super().__init__(0)


class PreemptionHandler:
    """Trainer extension + signal handler for bounded-grace preemption.

    Parameters
    ----------
    checkpointer:
        A :class:`~.checkpoint.MultiNodeCheckpointer` (or the replica-set
        wrapper) used for the final save.  ``None`` still gives bounded
        exit-0 + bundle, just without a saved generation.
    grace_s:
        The scheduler's grace window.  The final save must complete (and
        the loop must reach a step boundary) inside it; past the
        deadline the watchdog thread dumps and exits regardless.
    dump_dir:
        Where the ``preempt`` bundle lands (default: the flight
        recorder's configured crash dump dir).
    ledger:
        Optional :class:`~..observability.slo.GoodputLedger`; the final
        save's wall time books into its ``checkpoint`` bucket.
    signals:
        Which signals mean "preempt" (default SIGTERM only; SIGUSR1
        stays the flight recorder's dump-and-continue probe).
    exit_fn:
        Test seam for the hard deadline exit (default ``os._exit``).
    """

    trigger = (1, "iteration")
    priority = 9_500  # right after the Watchdog heartbeat, before any
    #                   slow extension delays the final save
    finalize_on_error = True

    def __init__(self, checkpointer=None, grace_s: float = 30.0,
                 dump_dir: Optional[str] = None,
                 ledger=None, rank: Optional[int] = None,
                 signals=(signal.SIGTERM,),
                 exit_fn: Callable[[int], None] = os._exit):
        if grace_s <= 0:
            raise ValueError(f"grace_s must be positive, got {grace_s}")
        self.checkpointer = checkpointer
        self.grace_s = float(grace_s)
        self.dump_dir = dump_dir
        self.ledger = ledger
        self.rank = rank
        self.signals = tuple(signals)
        self._exit = exit_fn
        self.requested = False
        self.completed = False
        self._signal_name: Optional[str] = None
        self._t_signal: Optional[float] = None
        self._deadline_thread: Optional[threading.Thread] = None
        self._prev_handlers = {}
        self._trainer = None
        self._installed = False

    # ---- installation ----
    def install(self) -> None:
        """Register the signal handlers (idempotent; main thread only —
        CPython restriction).  Installed AFTER the flight recorder's
        handlers, this takes over SIGTERM while leaving SIGUSR1 to the
        dump-and-continue probe."""
        if self._installed:
            return
        for sig in self.signals:
            self._prev_handlers[sig] = signal.getsignal(sig)
            signal.signal(sig, self._on_signal)
        self._installed = True

    def uninstall(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers = {}
        self._installed = False

    # ---- the signal path: flag + deadline, nothing else ----
    def _on_signal(self, signum, frame) -> None:
        if self.requested:
            return  # a second SIGTERM changes nothing; the deadline holds
        self.requested = True
        self._t_signal = time.monotonic()
        self._signal_name = signal.Signals(signum).name
        _flight.note("preempt_requested", signal=self._signal_name,
                     grace_s=self.grace_s)
        print(f"[chainermn_tpu preempt] {self._signal_name} received — "
              f"final checkpoint at the next step boundary "
              f"(grace {self.grace_s:.1f}s)", file=sys.stderr, flush=True)
        t = threading.Thread(target=self._deadline_watch, daemon=True,
                             name="chainermn-tpu-preempt-deadline")
        t.start()
        self._deadline_thread = t

    def _deadline_watch(self) -> None:
        """The bound: past the grace window, dump-and-exit 0 even if the
        loop never reached a step boundary (a wedged collective must not
        turn a preemption into a SIGKILL with no evidence)."""
        deadline = (self._t_signal or time.monotonic()) + self.grace_s
        while not self.completed:
            now = time.monotonic()
            if now >= deadline:
                self._dump(saved_generation=None,
                           why_not_saved="grace budget exhausted before "
                                         "a step boundary was reached",
                           grace_used_s=now - (self._t_signal or now))
                print("[chainermn_tpu preempt] grace exhausted — exiting 0 "
                      "without a final save (older generations remain)",
                      file=sys.stderr, flush=True)
                self._exit(0)
                return  # test exit_fn seams return instead of dying
            time.sleep(min(0.05, deadline - now))

    # ---- the step-boundary path ----
    def observe(self, trainer) -> None:
        self._trainer = trainer
        if self.requested and not self.completed:
            self.finish(trainer.checkpoint_state(), trainer.iteration,
                        trainer=trainer)

    def check(self, state: Any, iteration: int) -> None:
        """Hand-rolled-loop face: call once per iteration with the state
        a resume needs; no-op until a preemption signal arrived."""
        if self.requested and not self.completed:
            self.finish(state, iteration)

    def finish(self, state: Any, iteration: int, trainer=None) -> None:
        """Save, book, dump, exit 0.  Raises :class:`PreemptionExit`."""
        t0 = time.monotonic()
        saved: Optional[int] = None
        why: Optional[str] = None
        try:
            if self.checkpointer is not None:
                self.checkpointer.save(state, iteration)
                self.checkpointer.flush()
                saved = iteration
            else:
                why = "no checkpointer configured"
        except Exception as e:  # noqa: BLE001 — the exit must stay 0
            why = f"final checkpoint save failed: {e!r}"
            print(f"[chainermn_tpu preempt] {why}", file=sys.stderr,
                  flush=True)
        save_s = time.monotonic() - t0
        if self.ledger is not None:
            self.ledger.add("checkpoint", save_s)
        grace_used = time.monotonic() - (self._t_signal or t0)
        _flight.note("preempt", signal=self._signal_name,
                     generation=saved, saved=saved is not None,
                     save_s=round(save_s, 4),
                     grace_used_s=round(grace_used, 4),
                     grace_budget_s=self.grace_s)
        self._dump(saved_generation=saved, why_not_saved=why,
                   grace_used_s=grace_used, save_s=save_s,
                   trainer=trainer)
        self.completed = True
        print(f"[chainermn_tpu preempt] exiting 0 "
              f"(generation={'none' if saved is None else saved}, "
              f"grace used {grace_used:.2f}s of {self.grace_s:.1f}s)",
              file=sys.stderr, flush=True)
        raise PreemptionExit(saved)

    def _dump(self, saved_generation, why_not_saved, grace_used_s,
              save_s: Optional[float] = None, trainer=None) -> None:
        out = self.dump_dir or _flight.crash_dump_dir()
        if not out:
            return
        world = 1
        ckpt_dir = None
        if self.checkpointer is not None:
            ckpt_dir = getattr(self.checkpointer, "path", None)
            try:
                world = self.checkpointer._nproc
            except Exception:
                pass
        extra = {"preempt": {
            "signal": self._signal_name,
            "grace_budget_s": self.grace_s,
            "grace_used_s": round(float(grace_used_s), 4),
            "save_s": None if save_s is None else round(save_s, 4),
            "generation_saved": saved_generation,
            "why_not_saved": why_not_saved,
            "world_size": world,
            "checkpoint_dir": ckpt_dir,
            # the elastic contract: any world size whose shards divide
            # evenly can resume via the v2 manifest (reshard_host)
            "resume_hint": (
                "restart with ANY process count; maybe_load reshards "
                f"the manifest generation (saved at world={world}) "
                "host-side — docs/ROBUSTNESS.md 'Elastic resume'"),
        }}
        _flight.dump_bundle(out, "preempt", trainer=trainer,
                            rank=self.rank, extra=extra)

    # ---- extension plumbing ----
    def initialize(self, trainer) -> None:
        self._trainer = trainer
        self.install()

    def __call__(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        self.uninstall()

    def state_dict(self) -> dict:
        return {}  # preemption state never survives a restart

    def load_state_dict(self, state: dict) -> None:
        pass
