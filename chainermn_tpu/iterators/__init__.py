"""Dataset iterators (multi-node aware).

Reference parity: ``chainermn/iterators/`` — ``create_multi_node_iterator``
(``iterators/_multi_node_iterator.py`` [uv]) and
``create_synchronized_iterator`` (``iterators/_synchronized_iterator.py``
[uv]); SURVEY.md §2.5.  The reference wraps *Chainer's* ``SerialIterator``;
this framework is standalone so it ships its own :class:`SerialIterator`
with the same epoch/position/serialization contract, and the multi-node
wrappers compose with any iterator exposing that contract.

TPU adaptation: the reference's multi-node iterator is a master/slave
process pair exchanging batches over MPI.  Under a single-controller JAX
process that owns every rank the *semantics* (all ranks observe the master
rank's batch stream) are delivered by iterating on the process that owns the
master rank and broadcasting the batch over DCN (``bcast_obj``); on one
process this is a passthrough with a defensive copy, exactly how the
reference behaves under ``mpiexec -n 1``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..communicators.base import CommunicatorBase


class SerialIterator:
    """Sequential/shuffled minibatch iterator with epoch accounting.

    Standalone analog of Chainer's ``SerialIterator`` (the reference's
    iterator substrate — external dep, see SURVEY.md §1 note on Chainer
    sitting below everything).  Supports ``state_dict``/``load_state_dict``
    so the multi-node checkpointer can resume it mid-epoch.
    """

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.repeat = repeat
        self.shuffle = shuffle
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self.epoch = 0
        self.current_position = 0
        self.is_new_epoch = False
        self._order = self._new_order()

    def _new_order(self) -> np.ndarray:
        n = len(self.dataset)
        return self._rng.permutation(n) if self.shuffle else np.arange(n)

    @property
    def epoch_detail(self) -> float:
        return self.epoch + self.current_position / max(len(self.dataset), 1)

    def __iter__(self):
        return self

    def __next__(self):
        n = len(self.dataset)
        if not self.repeat and self.epoch > 0 and self.current_position == 0:
            raise StopIteration
        i, stop = self.current_position, min(self.current_position + self.batch_size, n)
        batch = [self.dataset[int(j)] for j in self._order[i:stop]]
        if stop >= n:
            self.epoch += 1
            self.is_new_epoch = True
            self.current_position = 0
            self._order = self._new_order()
            if self.repeat:
                # Pad from subsequent epoch(s) — looping so batch_size > n
                # still yields full, fixed-shape batches (no recompiles).
                while len(batch) < self.batch_size:
                    take = min(self.batch_size - len(batch), n)
                    batch.extend(self.dataset[int(j)] for j in self._order[:take])
                    self.current_position = take % n
                    if take == n:
                        self.epoch += 1
                        self._order = self._new_order()
        else:
            self.is_new_epoch = False
            self.current_position = stop
        return batch

    next = __next__

    def reset(self) -> None:
        self._rng = np.random.RandomState(self._seed)
        self.epoch = 0
        self.current_position = 0
        self.is_new_epoch = False
        self._order = self._new_order()

    # ---- resume contract (consumed by extensions/checkpoint.py) ----
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "current_position": self.current_position,
            "is_new_epoch": self.is_new_epoch,
            "order": np.asarray(self._order),
            "rng_state": self._rng.get_state(),
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.current_position = int(state["current_position"])
        self.is_new_epoch = bool(state["is_new_epoch"])
        self._order = np.asarray(state["order"])
        self._rng.set_state(state["rng_state"])


class _MultiNodeIterator:
    """All ranks observe the master rank's batch stream (bcast per batch)."""

    def __init__(self, actual_iterator, communicator: CommunicatorBase,
                 rank_master: int):
        self.actual_iterator = actual_iterator
        self.communicator = communicator
        self.rank_master = rank_master
        self.epoch = 0
        self.is_new_epoch = False
        self._epoch_detail = 0.0

    @property
    def _is_master(self) -> bool:
        return self.communicator.owns_rank(self.rank_master)

    def __iter__(self):
        return self

    def __next__(self):
        # Only the process owning the master rank drives the underlying
        # iterator (non-master processes skip their local input pipeline
        # entirely); bcast_obj carries (batch, epoch bookkeeping) to
        # everyone — DCN under multi-controller, a copy under one process.
        # Reference analog: _MultiNodeIterator master sends
        # (batch, is_new_epoch) via MPI [uv].
        stop = False
        payload = None
        if self._is_master:
            try:
                batch = self.actual_iterator.next()
                payload = (
                    batch,
                    getattr(self.actual_iterator, "epoch", 0),
                    getattr(self.actual_iterator, "is_new_epoch", False),
                    getattr(self.actual_iterator, "epoch_detail", 0.0),
                )
            except StopIteration:
                stop = True
        stop, payload = self.communicator.bcast_obj(
            (stop, payload), root=self.rank_master)
        if stop:
            raise StopIteration
        batch, self.epoch, self.is_new_epoch, self._epoch_detail = payload
        return batch

    next = __next__

    @property
    def epoch_detail(self) -> float:
        # Reflects the MASTER stream (synced each batch), so epoch triggers
        # fire identically on every process regardless of local shard sizes.
        return self._epoch_detail

    def reset(self) -> None:
        if self._is_master and hasattr(self.actual_iterator, "reset"):
            self.actual_iterator.reset()
        self.epoch = 0
        self.is_new_epoch = False
        self._epoch_detail = 0.0

    def state_dict(self) -> dict:
        # The master's state is authoritative; broadcast it so every process
        # checkpoints an identical, resumable copy.
        local = (self.actual_iterator.state_dict()
                 if self._is_master else None)
        return self.communicator.bcast_obj(local, root=self.rank_master)

    def load_state_dict(self, state: dict) -> None:
        if self._is_master:
            self.actual_iterator.load_state_dict(state)


def create_multi_node_iterator(actual_iterator, communicator: CommunicatorBase,
                               rank_master: int = 0):
    """Replicate one rank's batch stream to all ranks (reference:
    ``create_multi_node_iterator`` [uv] — model-parallel input replication,
    exercised by ``examples/model_parallel``)."""
    return _MultiNodeIterator(actual_iterator, communicator, rank_master)


def create_synchronized_iterator(actual_iterator, communicator: CommunicatorBase):
    """Synchronize the iterator's RNG across ranks so every rank draws the
    same shuffle order (reference: ``create_synchronized_iterator`` [uv]).

    The master rank's full iterator state (RNG, shuffle order, position) is
    broadcast and installed into every rank's iterator before use; thereafter
    all ranks step identical streams.  On a single controller this is an
    identity (the master's own stream is left untouched).
    """
    if not hasattr(actual_iterator, "state_dict"):
        raise ValueError(
            "synchronized iterator needs an iterator with state_dict/"
            "load_state_dict (e.g. chainermn_tpu.iterators.SerialIterator)")
    state = communicator.bcast_obj(actual_iterator.state_dict(), root=0)
    actual_iterator.load_state_dict(state)
    return actual_iterator


__all__ = [
    "SerialIterator",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
]
