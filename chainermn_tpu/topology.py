"""TPU slice topology discovery and runtime bootstrap.

Reference parity: ``chainermn/communicators/_communication_utility.py ::
init_ranks / init_intra_mpi_comm / init_inter_mpi_comm / init_nccl_comm`` [uv]
(see SURVEY.md §2.1).  The reference discovers cluster topology by
all-gathering hostnames over MPI and derives ``intra_rank`` (GPU index within
the node) and ``inter_rank`` (node index).  On TPU none of that is needed:
the slice topology is a property of the runtime — ``jax.devices()`` already
knows which process (host) owns which chip and how the chips are wired over
ICI.  This module maps that information onto ChainerMN's rank vocabulary:

=================  ============================================
ChainerMN concept  TPU-native meaning
=================  ============================================
``rank``           index of a chip along the communicator mesh axis
``size``           number of chips in the communicator mesh
``intra_rank``     chip index within its host (``device.local_hardware_id``)
``intra_size``     chips per host (``jax.local_device_count()``)
``inter_rank``     host index (``jax.process_index()``)
``inter_size``     host count (``jax.process_count()``)
=================  ============================================

The reference's ``mpiexec`` bootstrap (one process per GPU) becomes
``jax.distributed.initialize`` (one process per host, multi-controller SPMD);
``init_distributed`` below wraps it and is a no-op for single-process runs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical mesh-axis name for the data-parallel "multi-node" axis.  The
# reference has no axis names (ranks are implicit in MPI_COMM_WORLD); we pick
# one so in-jit collectives (lax.psum etc.) can refer to it.
DEFAULT_AXIS_NAME = "mn"

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bootstrap the multi-controller runtime (reference: ``mpiexec`` + MPI_Init).

    Safe to call unconditionally: a no-op when running single-process (the
    common case for tests and single-host jobs).  Multi-host TPU pods launched
    through a cluster scheduler auto-detect all three arguments.
    """
    global _initialized
    if _initialized:
        return
    explicit = coordinator_address is not None
    # Auto-detect only on unambiguous signals.  TPU_WORKER_HOSTNAMES is set
    # even on single-host TPU VMs, so it only counts with >1 worker listed.
    auto = any(v in os.environ for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    auto = auto or len([w for w in workers.split(",") if w.strip()]) > 1
    if explicit or auto:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        # Crash hygiene (reference: auto-installed MPI_Abort hook): once a
        # gang exists, an uncaught exception on one process must abort the
        # whole job instead of wedging the others inside a collective.
        from .global_except_hook import add_hook

        add_hook()
    # No-op branch leaves the flag unset so a later *explicit* call (e.g. a
    # pod launcher passing coordinator_address) still initializes.


@dataclasses.dataclass(frozen=True)
class Topology:
    """Rank bookkeeping derived from the device list (not hostname gossip)."""

    size: int
    intra_size: int
    inter_size: int
    inter_rank: int  # this process's host index

    @classmethod
    def detect(cls, devices: Optional[Sequence[jax.Device]] = None) -> "Topology":
        devices = list(devices) if devices is not None else jax.devices()
        n_local = len([d for d in devices if d.process_index == jax.process_index()])
        n_proc = len({d.process_index for d in devices})
        return cls(
            size=len(devices),
            intra_size=max(n_local, 1),
            inter_size=max(n_proc, 1),
            inter_rank=jax.process_index(),
        )

    def intra_rank_of(self, rank: int) -> int:
        return rank % self.intra_size

    def inter_rank_of(self, rank: int) -> int:
        return rank // self.intra_size


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = DEFAULT_AXIS_NAME,
) -> Mesh:
    """A 1-D mesh over all chips — the communicator's world.

    Reference analog: ``MPI_COMM_WORLD`` ordering in ``init_ranks`` [uv].
    Devices are kept in ``jax.devices()`` order, which the runtime guarantees
    to be consistent across processes (so every host agrees on rank→chip).
    """
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices, dtype=object), (axis_name,))


def make_nd_mesh(axis_names: Sequence[str], axis_sizes: Sequence[int],
                 devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """An N-D mesh (e.g. ``('data','model')``) for hybrid DP×MP layouts.

    Reference analog: manual ``CommunicatorBase.split(color, key)`` 2-D
    decompositions (SURVEY.md §2.8 "Hybrid DP×MP").
    """
    devices = list(devices) if devices is not None else jax.devices()
    arr = np.asarray(devices, dtype=object).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def slice_index_of(device: jax.Device) -> int:
    """Which slice (ICI island) a device belongs to.

    Real multislice TPU devices carry ``slice_index``; single-slice and CPU
    devices fall back to ``process_index`` (each host = one "slice", the
    closest analog: intra-host is fast, cross-host is DCN).
    """
    idx = getattr(device, "slice_index", None)
    if idx is not None:
        return int(idx)
    return int(device.process_index)


def make_multislice_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = ("slice", "chip"),
    num_slices: Optional[int] = None,
) -> Mesh:
    """A 2-D ``('slice', 'chip')`` mesh exposing the two-tier fabric.

    Reference analog: ``HierarchicalCommunicator`` [uv] — intra-node NCCL
    reduce → inter-node MPI allreduce → intra-node bcast, i.e. "use the
    fast fabric first, cross the slow one once".  On TPU the two tiers are
    ICI (within a slice) and DCN (across slices); collectives over the
    ``chip`` axis ride ICI, collectives over ``slice`` cross DCN.  See
    :func:`chainermn_tpu.ops.collective.hierarchical_pmean` for the
    gradient-mean recipe built on this mesh.

    Slice membership comes from each device's ``slice_index`` (multislice
    runtime) with a ``process_index`` fallback; ``num_slices`` overrides
    detection (e.g. to carve a virtual CPU mesh into fake slices for tests).
    """
    devices = list(devices) if devices is not None else jax.devices()
    if num_slices is None:
        groups: dict = {}
        for d in devices:
            groups.setdefault(slice_index_of(d), []).append(d)
        sizes = {len(v) for v in groups.values()}
        if len(sizes) != 1:
            raise ValueError(
                f"uneven slices: {{idx: len}} = "
                f"{ {k: len(v) for k, v in groups.items()} }")
        ordered = [d for _, grp in sorted(groups.items()) for d in grp]
        num_slices = len(groups)
    else:
        if len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices not divisible into {num_slices} slices")
        ordered = devices
    arr = np.asarray(ordered, dtype=object).reshape(
        (num_slices, len(ordered) // num_slices))
    return Mesh(arr, tuple(axis_names))
