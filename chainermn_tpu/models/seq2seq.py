"""Encoder–decoder seq2seq (the reference's translation workload).

Reference parity: ``examples/seq2seq/seq2seq.py`` [uv] (SURVEY.md §2.9,
BASELINE config #3) — an embed → stacked-LSTM encoder → stacked-LSTM
decoder → projection network trained with teacher forcing on padded
variable-length pairs.

TPU-first design: the reference used Chainer's ``NStepLSTM`` over *lists*
of variable-length CuPy arrays (cuDNN packed sequences).  Dynamic shapes
would defeat XLA, so here sequences are right-padded to a static bucket
length and time recurrence is ``flax.linen.scan`` over the time axis —
one compiled program per bucket shape, MXU-friendly batched matmuls at
every step, and a mask carries the ragged lengths.  PAD=0 never
contributes to loss and never advances encoder state.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

PAD, BOS, EOS = 0, 1, 2
N_SPECIAL = 3


class _EncoderStep(nn.Module):
    """One masked time-step through stacked LSTM cells: pad positions
    (mask 0) freeze both c and h so the final carry is the state at each
    sequence's last real token."""

    hidden: int
    n_layers: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, xs):
        x, m = xs  # (B, units), (B, 1)
        new_carry = []
        inp = x
        for i in range(self.n_layers):
            # dtype must be EXPLICIT: the default (None) promotes bf16
            # inputs with fp32 params to an fp32 carry, which breaks the
            # scan carry-type contract against the bf16 initial carry.
            cell = nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype,
                                        name=f"lstm{i}")
            (c_new, h_new), inp = cell(carry[i], inp)
            c_old, h_old = carry[i]
            new_carry.append((m * c_new + (1 - m) * c_old,
                              m * h_new + (1 - m) * h_old))
        return tuple(new_carry), inp


class _DecoderStep(nn.Module):
    """One time-step through stacked LSTM cells (no mask: teacher forcing
    loss masks pad positions instead)."""

    hidden: int
    n_layers: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry, x):
        new_carry = []
        inp = x
        for i in range(self.n_layers):
            cell = nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype,
                                        name=f"lstm{i}")
            c, inp = cell(carry[i], inp)
            new_carry.append(c)
        return tuple(new_carry), inp


def _scan_over_time(step_cls, *args, name):
    return nn.scan(
        step_cls,
        variable_broadcast="params",
        split_rngs={"params": False},
        in_axes=1, out_axes=1)(*args, name=name)


class Seq2seq(nn.Module):
    """Embed → LSTM encode → LSTM decode (teacher forcing) → logits.

    ``__call__(src, tgt_in)`` returns per-position target logits; ``src``
    and ``tgt_in`` are int32 ``(batch, time)`` right-padded with PAD.
    """

    n_source_vocab: int
    n_target_vocab: int
    n_units: int = 512
    n_layers: int = 3
    dtype: Any = jnp.bfloat16  # MXU-native compute; params stay fp32

    def setup(self):
        self.embed_x = nn.Embed(self.n_source_vocab, self.n_units,
                                dtype=self.dtype)
        self.embed_y = nn.Embed(self.n_target_vocab, self.n_units,
                                dtype=self.dtype)
        self.encoder = _scan_over_time(
            _EncoderStep, self.n_units, self.n_layers, self.dtype,
            name="encoder")
        self.decoder = _scan_over_time(
            _DecoderStep, self.n_units, self.n_layers, self.dtype,
            name="decoder")
        self.proj = nn.Dense(self.n_target_vocab, dtype=self.dtype)

    def _init_carry_like(self, emb: jnp.ndarray):
        # Derive zeros from the embeddings rather than jnp.zeros so the
        # carry inherits their sharding/varying-axis type — required for
        # lax.scan type agreement inside shard_map'ped training steps.
        zeros = emb[:, 0, :] * 0
        return tuple((zeros, zeros) for _ in range(self.n_layers))

    def encode(self, src: jnp.ndarray):
        """Final stacked-LSTM carry at each sequence's last real token."""
        mask = (src != PAD)[..., None].astype(self.dtype)  # (B, T, 1)
        emb = self.embed_x(src) * mask
        carry, _ = self.encoder(self._init_carry_like(emb), (emb, mask))
        return carry

    def __call__(self, src: jnp.ndarray, tgt_in: jnp.ndarray) -> jnp.ndarray:
        carry = self.encode(src)
        emb = self.embed_y(tgt_in)
        _, hs = self.decoder(carry, emb)
        return self.proj(hs).astype(jnp.float32)

    def translate(self, src: jnp.ndarray, max_len: int = 32) -> jnp.ndarray:
        """Greedy decode under jit: fixed ``max_len`` steps of ``lax.scan``
        (static shapes — a data-dependent while_loop would defeat batching),
        with EOS-frozen emission (reference: ``Seq2seq.translate`` eager
        per-sentence loop [uv])."""
        batch = src.shape[0]
        carry = self.encode(src)
        bos = jnp.full((batch,), BOS, jnp.int32)

        # LIFTED scan (nn.scan), not raw lax.scan: the step closes over
        # bound submodules (embed_y/decoder/proj), and flax forbids raw
        # jax transforms over bound state (JaxTransformError on 0.10.x);
        # nn.scan broadcasts the params collection through the loop.
        def step(mdl, state, _):
            carry, tok, done = state
            emb = mdl.embed_y(tok[:, None])
            carry, h = mdl.decoder(carry, emb)
            nxt = mdl.proj(h[:, 0]).astype(jnp.float32).argmax(-1).astype(jnp.int32)
            nxt = jnp.where(done, PAD, nxt)
            done = done | (nxt == EOS)
            return (carry, nxt, done), nxt

        scan = nn.scan(step, variable_broadcast="params",
                       split_rngs={"params": False}, length=max_len)
        _, toks = scan(self, (carry, bos, jnp.zeros((batch,), bool)), None)
        return jnp.swapaxes(toks, 0, 1)  # (B, max_len)


def masked_cross_entropy(logits: jnp.ndarray, tgt_out: jnp.ndarray) -> jnp.ndarray:
    """Mean NLL over non-PAD target positions (per-token, so loss scale is
    independent of padding/bucketing)."""
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, tgt_out[..., None], axis=-1)[..., 0]
    mask = (tgt_out != PAD).astype(nll.dtype)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)


def token_accuracy(logits: jnp.ndarray, tgt_out: jnp.ndarray) -> jnp.ndarray:
    mask = tgt_out != PAD
    hit = (logits.argmax(-1) == tgt_out) & mask
    return hit.sum() / jnp.maximum(mask.sum(), 1)


# ---- host-side data plumbing (padding / bucketing; reference fed lists) ----

def encode_pairs(pairs: Sequence[Tuple[Sequence[int], Sequence[int]]],
                 src_len: int, tgt_len: int):
    """Pad (src_ids, tgt_ids) token pairs into fixed-shape int32 arrays:
    ``src (N, src_len)``, ``tgt_in (N, tgt_len)`` (BOS-prefixed), ``tgt_out
    (N, tgt_len)`` (EOS-suffixed) — the static-shape stand-in for the
    reference's variable-length list feed."""
    import numpy as np

    n = len(pairs)
    src = np.full((n, src_len), PAD, np.int32)
    tgt_in = np.full((n, tgt_len), PAD, np.int32)
    tgt_out = np.full((n, tgt_len), PAD, np.int32)
    for i, (s, t) in enumerate(pairs):
        s = list(s)[:src_len]
        t = list(t)[: tgt_len - 1]
        src[i, : len(s)] = s
        tgt_in[i, 0] = BOS
        tgt_in[i, 1 : len(t) + 1] = t
        tgt_out[i, : len(t)] = t
        tgt_out[i, len(t)] = EOS
    return src, tgt_in, tgt_out
