from .convnets import AlexNet, GoogLeNet, VGG16  # noqa: F401
from .mlp import MLP, accuracy, cross_entropy_loss  # noqa: F401
from .vit import ViT, ViT_B16, ViT_S16, ViT_Ti16  # noqa: F401
