from .mlp import MLP, accuracy, cross_entropy_loss  # noqa: F401
