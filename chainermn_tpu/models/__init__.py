from .convnets import AlexNet, GoogLeNet, VGG16  # noqa: F401
from .mlp import MLP, accuracy, cross_entropy_loss  # noqa: F401
