"""MNIST-scale MLP.

Reference parity: the 3-layer MLP of ``examples/mnist/train_mnist.py`` [uv]
(units-hidden → units-hidden → 10, ReLU), the model behind BASELINE
config #1.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)


def cross_entropy_loss(logits, labels):
    logp = jnp.take_along_axis(
        nn.log_softmax(logits), labels[:, None], axis=-1)
    return -logp.mean()


def accuracy(logits, labels):
    return (logits.argmax(-1) == labels).mean()
