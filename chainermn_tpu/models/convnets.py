"""Classic ImageNet convnets: AlexNet, VGG-16, GoogLeNet (Inception v1).

Reference parity: ``examples/imagenet/models/{alex,googlenet,...}.py`` [uv]
(SURVEY.md §2.9 — the reference's ImageNet example shipped a model zoo, not
just ResNet).  Same TPU-first conventions as ``resnet.py``: NHWC, bf16
convs/matmuls on the MXU, fp32 params and loss; all three expose the
``(x, train=...) -> logits`` interface the DP example and train-step
builders expect, and register in ``resnet.ARCHS`` for the imagenet CLI.

``stem_strides`` mirrors the ResNet knob: the ImageNet stem at small test
resolutions (32 px CI runs) collapses spatial dims too fast, so strides
soften when ``stem_strides == 1``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class AlexNet(nn.Module):
    """AlexNet (one-tower variant), BN instead of LRN — the modern form."""

    num_classes: int = 1000
    stem_strides: int = 2  # >=2: ImageNet stem; 1: small-input test mode
    dtype: Any = jnp.bfloat16
    # 0.0 (default) = no dropout: the step builders don't thread a dropout
    # rng; classic-recipe users can set 0.5 and pass rngs= to apply()
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        big = self.stem_strides > 1

        def pool(y):
            # Small-input mode still MUST downsample: without it the
            # flatten below feeds Dense(4096) a 32·32·256 vector — a
            # ~1B-parameter layer in the mode meant to be cheap.
            if big:
                return nn.max_pool(y, (3, 3), strides=(2, 2))
            if min(y.shape[1:3]) > 4:
                return nn.max_pool(y, (2, 2), strides=(2, 2))
            return y

        x = x.astype(self.dtype)
        x = conv(64, (11, 11) if big else (3, 3),
                 strides=(4, 4) if big else (1, 1))(x)
        x = pool(nn.relu(norm()(x)))
        x = pool(nn.relu(norm()(conv(192, (5, 5))(x))))
        x = nn.relu(norm()(conv(384, (3, 3))(x)))
        x = nn.relu(norm()(conv(256, (3, 3))(x)))
        x = pool(nn.relu(norm()(conv(256, (3, 3))(x))))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = self._drop(x, train)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = self._drop(x, train)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)

    def _drop(self, x, train):
        if self.dropout_rate > 0:
            return nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


class VGG16(nn.Module):
    """VGG-16 with BatchNorm (configuration D)."""

    num_classes: int = 1000
    stem_strides: int = 2  # 1 skips the final pools for small inputs
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.0
    cfg: Sequence = ((64, 64), (128, 128), (256, 256, 256),
                     (512, 512, 512), (512, 512, 512))

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        for i, widths in enumerate(self.cfg):
            for w in widths:
                x = nn.relu(norm()(conv(w, (3, 3))(x)))
            # small-input mode: stop pooling once spatial dims are tiny
            if self.stem_strides > 1 or min(x.shape[1:3]) > 4:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = self._drop(x, train)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = self._drop(x, train)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)

    def _drop(self, x, train):
        if self.dropout_rate > 0:
            return nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


class _Inception(nn.Module):
    """Inception v1 block: 1x1 / 1x1→3x3 / 1x1→5x5 / pool→1x1 branches."""

    b1: int
    b3r: int
    b3: int
    b5r: int
    b5: int
    bp: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)

        def unit(y, width, kernel):
            return nn.relu(norm()(conv(width, kernel)(y)))

        p1 = unit(x, self.b1, (1, 1))
        p3 = unit(unit(x, self.b3r, (1, 1)), self.b3, (3, 3))
        p5 = unit(unit(x, self.b5r, (1, 1)), self.b5, (5, 5))
        pp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        pp = unit(pp, self.bp, (1, 1))
        return jnp.concatenate([p1, p3, p5, pp], axis=-1)


class GoogLeNet(nn.Module):
    """GoogLeNet / Inception v1 (BN form, no aux heads — eval-equivalent)."""

    num_classes: int = 1000
    stem_strides: int = 2
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        big = self.stem_strides > 1
        inc = partial(_Inception, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.relu(norm()(conv(
            64, (7, 7), strides=(2, 2) if big else (1, 1))(x)))
        if big:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(norm()(conv(64, (1, 1))(x)))
        x = nn.relu(norm()(conv(192, (3, 3))(x)))
        if big:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = inc(64, 96, 128, 16, 32, 32)(x, train)     # 3a
        x = inc(128, 128, 192, 32, 96, 64)(x, train)   # 3b
        if big:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = inc(192, 96, 208, 16, 48, 64)(x, train)    # 4a
        x = inc(160, 112, 224, 24, 64, 64)(x, train)   # 4b
        x = inc(128, 128, 256, 24, 64, 64)(x, train)   # 4c
        x = inc(112, 144, 288, 32, 64, 64)(x, train)   # 4d
        x = inc(256, 160, 320, 32, 128, 128)(x, train)  # 4e
        if big:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = inc(256, 160, 320, 32, 128, 128)(x, train)  # 5a
        x = inc(384, 192, 384, 48, 128, 128)(x, train)  # 5b
        x = jnp.mean(x, axis=(1, 2))
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
