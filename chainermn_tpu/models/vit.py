"""Vision Transformer (ViT-S/B) — the modern imagenet family.

Beyond-reference (the reference's zoo — SURVEY.md §2.9 — is 2017-era
convnets): a patch-embedding transformer classifier built TPU-first:

* NHWC patchify as ONE conv (stride = patch) → big MXU matmuls throughout;
* bf16 compute / fp32 params, matching the convnet conventions in this
  package;
* attention can run through the in-tree Pallas flash kernel
  (``attn_impl='flash'``) — online-softmax VMEM scratch instead of the
  O(S²) score matrix — or plain XLA einsum (``'xla'``, the default, which
  XLA fuses fine at classification sequence lengths).

Interface matches the zoo: ``(x, train=...) -> logits``, ``stem_strides``
accepted (ignored — patch size already scales with input), registered in
``resnet.ARCHS`` for the imagenet CLI.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class _MHSA(nn.Module):
    """Multi-head self-attention over (B, S, D), optional flash kernel."""

    num_heads: int
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        h = self.num_heads
        qkv = nn.DenseGeneral((3, h, d // h), dtype=self.dtype, name="qkv")(x)
        q, k, v = (qkv[:, :, i] for i in range(3))  # each (B, S, H, Dh)
        from ..ops.flash_attention import resolve_attn_impl

        if resolve_attn_impl(self.attn_impl, s) == "flash":
            from ..ops import flash_attention

            o = flash_attention(q, k, v)
        else:
            scale = (d // h) ** -0.5
            att = jnp.einsum("bqhc,bkhc->bhqk", q, k) * scale
            att = nn.softmax(att.astype(jnp.float32)).astype(self.dtype)
            o = jnp.einsum("bhqk,bkhc->bqhc", att, v)
        return nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype,
                               name="proj")(o)


class _Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + _MHSA(self.num_heads, self.dtype, self.attn_impl)(y)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.Dense(d * self.mlp_ratio, dtype=self.dtype)(y)
        y = nn.gelu(y)
        return x + nn.Dense(d, dtype=self.dtype)(y)


class ViT(nn.Module):
    """ViT classifier; defaults are ViT-S/16 shaped."""

    num_classes: int = 1000
    patch: int = 16
    d_model: int = 384
    depth: int = 12
    num_heads: int = 6
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    stem_strides: int = 2  # accepted for zoo-interface parity; unused

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no dropout in the baseline recipe; BN-free by design
        b, hgt, wid, _ = x.shape
        p = self.patch
        if hgt < p or wid < p:
            raise ValueError(
                f"input {hgt}x{wid} smaller than patch {p}; construct the "
                f"model with a smaller patch= (silently reconfiguring would "
                f"change the pos_embed shape and break checkpoints)")
        x = nn.Conv(self.d_model, (p, p), strides=(p, p),
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(b, -1, self.d_model)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.d_model))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(x.dtype), x],
            axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.d_model))
        x = x + pos.astype(x.dtype)
        for _ in range(self.depth):
            x = _Block(self.num_heads, dtype=self.dtype,
                       attn_impl=self.attn_impl)(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # classify on the CLS token; head in fp32 like the convnets
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x[:, 0])


ViT_S16 = partial(ViT, patch=16, d_model=384, depth=12, num_heads=6)
ViT_B16 = partial(ViT, patch=16, d_model=768, depth=12, num_heads=12)
ViT_Ti16 = partial(ViT, patch=16, d_model=192, depth=12, num_heads=3)
