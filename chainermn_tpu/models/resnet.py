"""ResNet family (v1.5) for the ImageNet DP benchmark.

Reference parity: ``examples/imagenet/models/resnet50.py`` [uv]
(SURVEY.md §2.9) — the headline data-parallel workload (BASELINE configs
#2/#4 use ResNet-50/152).

TPU-first design: convs and matmuls run in bfloat16 (MXU-native), while
parameters, BatchNorm statistics and the softmax/loss stay float32 for
numerical stability — the TPU analog of the reference's
``allreduce_grad_dtype=float16`` compute/compress split.  Shapes are NHWC
(XLA:TPU's preferred conv layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from chainermn_tpu.ops import conv_backward

ModuleDef = Any


class Affine(nn.Module):
    """Per-channel scale+shift — the zero-extra-pass floor for norm traffic.

    A pure elementwise epilogue XLA fuses into the producing conv, so a
    network built on it pays NO activation passes for normalization.  Used
    (a) as the probe that bounds how much of ResNet's HBM traffic BatchNorm
    costs (docs/PERF.md roofline) and (b) as the apply-side of the
    stale-stats BN below."""

    dtype: Any = jnp.bfloat16
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return (x.astype(jnp.float32) * scale + bias).astype(self.dtype)


class StaleBatchNorm(nn.Module):
    """BatchNorm normalizing with the PREVIOUS step's batch statistics.

    Standard training BN cannot normalize until the CURRENT batch's
    mean/var exist, which forces the conv output through HBM extra times
    (a stats read plus a normalize read+write) — 8.4 GB of ResNet-50's
    44 GB/step on v5e (docs/PERF.md roofline, measured by
    scripts/probe_bn_traffic.py).  Normalizing with statistics that are
    CONSTANTS at this step makes the apply side a per-channel affine — a
    pure elementwise epilogue XLA fuses into the producing conv — and
    the current batch's stats reduction fuses too (measured: within 2%
    of the zero-norm floor).  The statistics used are exactly one step
    stale: the previous step's batch mean/var.  Same 1-step-stale trade
    as the double-buffered allreduce (SURVEY.md §6 v1.2): semantics
    documented, opt-in.

    Eval uses the slow EMA (``mean``/``var``) exactly like
    ``nn.BatchNorm``; ``last_mean``/``last_var`` carry the one-step
    pipeline.  Flax auto-names the module path by class (``BatchNorm_0``
    vs ``StaleBatchNorm_0``), so converting a checkpoint between norms
    needs a module-name rename map — it is not drop-in.
    """

    train: bool = True
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        # Two stat pairs.  mean/var: the slow EMA, used in EVAL exactly like
        # nn.BatchNorm's running stats.  last_mean/last_var: the PREVIOUS
        # step's batch statistics, used to normalize in TRAIN — exactly one
        # step stale, no EMA lag.  An early variant normalized with the EMA
        # itself and destabilized (loss re-inflated after step ~50): the EMA
        # lags the drifting activations by ~momentum/(1-momentum) steps and
        # the feedback loop compounds.  The 1-step variant diverges even
        # faster at lr 0.05 (docs/evidence_stalebn_divergence.json) — this
        # module is a PERF PROBE, not a training path; nf_resnet50 is the
        # shipped BN-free alternative (docs/PERF.md "Round 4").
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        last_mean = self.variable("batch_stats", "last_mean",
                                  lambda: jnp.zeros((c,), jnp.float32))
        last_var = self.variable("batch_stats", "last_var",
                                 lambda: jnp.ones((c,), jnp.float32))
        if self.train and not self.is_initializing():
            m, v = last_mean.value, last_var.value  # STALE: read before update
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            bmean = jnp.mean(xf, axes)
            bvar = jnp.mean(jnp.square(xf), axes) - jnp.square(bmean)
            ra_mean.value = (self.momentum * ra_mean.value
                             + (1 - self.momentum) * bmean)
            ra_var.value = (self.momentum * ra_var.value
                            + (1 - self.momentum) * bvar)
            last_mean.value, last_var.value = bmean, bvar
        else:
            m, v = ra_mean.value, ra_var.value  # eval: EMA, like BatchNorm
        inv = scale / jnp.sqrt(v + self.epsilon)
        y = (x.astype(jnp.float32) - m) * inv + bias
        return y.astype(self.dtype)


def make_norm(norm: str, train: bool, dtype):
    """Factory for the block norm layer: 'bn' (reference-parity BatchNorm),
    'affine' (per-channel scale+shift, the fusion floor), 'stalebn'
    (BN with one-step-stale statistics — see StaleBatchNorm)."""
    if norm == "bn":
        return partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=dtype)
    if norm == "affine":
        return partial(Affine, dtype=dtype)
    if norm == "stalebn":
        return partial(StaleBatchNorm, train=train, momentum=0.9,
                       epsilon=1e-5, dtype=dtype)
    raise ValueError(f"unknown norm {norm!r}")


class PallasConv(nn.Module):
    """nn.Conv(use_bias=False) stand-in whose VJP runs the Pallas 3x3
    backward kernels (ops/conv_backward.py).  Same param name ("kernel"),
    shape (kh, kw, cin, features) and default init as nn.Conv, so
    checkpoints are interchangeable with the XLA path when call sites pin
    the module name."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Any = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel
        s = self.strides[0] if isinstance(self.strides, tuple) else self.strides
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (kh, kw, x.shape[-1], self.features), jnp.float32)
        return conv_backward.conv2d(x.astype(self.dtype),
                                    w.astype(self.dtype), s)


def _conv3x3_factory(conv_impl: str, dtype):
    """The 3x3 conv used inside blocks: XLA end to end, or XLA forward with
    the Pallas traffic-floor backward (conv_impl='pallas')."""
    if conv_impl == "pallas":
        return partial(PallasConv, dtype=dtype)
    return partial(nn.Conv, use_bias=False, dtype=dtype)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm: str = "bn"
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = make_norm(self.norm, train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        conv3 = _conv3x3_factory(self.conv_impl, self.dtype)
        residual = x
        y = conv3(self.filters, (3, 3), strides=(self.strides, self.strides),
                  name="Conv_0")(x)
        y = nn.relu(norm()(y))
        y = conv3(self.filters, (3, 3), name="Conv_1")(y)
        # zero-init the last BN scale so each block starts as identity —
        # standard large-batch ResNet trick (Goyal et al.), matters at the
        # batch sizes DP scaling targets
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides),
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm: str = "bn"
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = make_norm(self.norm, train, self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        conv3 = _conv3x3_factory(self.conv_impl, self.dtype)
        residual = x
        y = nn.relu(norm()(conv(self.filters, (1, 1), name="Conv_0")(x)))
        # v1.5: stride lives on the 3x3, not the 1x1
        y = nn.relu(norm()(conv3(self.filters, (3, 3),
                                 strides=(self.strides, self.strides),
                                 name="Conv_1")(y)))
        y = norm(scale_init=nn.initializers.zeros)(
            conv(self.filters * 4, (1, 1), name="Conv_2")(y))
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem_strides: int = 2  # small-image variants (CIFAR-style) can use 1
    norm: str = "bn"  # 'bn' | 'stalebn' (fused-epilogue stats) | 'affine'
    conv_impl: str = "xla"  # 'xla' | 'pallas' (traffic-floor 3x3 backward)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_filters, (7, 7),
                    strides=(self.stem_strides, self.stem_strides),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = make_norm(self.norm, train, self.dtype)(name="bn_init")(x)
        x = nn.relu(x)
        if self.stem_strides == 2:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, dtype=self.dtype,
                                   norm=self.norm,
                                   conv_impl=self.conv_impl)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        # head in float32: the tiny matmul costs nothing, the logits gain
        # a lot of precision
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


# --- Normalizer-free ResNets (Brock et al. 2021, NF-ResNet) ---------------
# The measured BN-free variant (VERDICT r3 directive #2): BatchNorm's extra
# activation passes cost 8.4 GB of ResNet-50's 44 GB/step on v5e
# (scripts/probe_bn_traffic.py), and the zero-norm "affine floor" measures
# +19% step throughput.  NF-ResNets reach that floor with PUBLISHED
# convergence parity on ImageNet: scaled weight standardization (statistics
# over the WEIGHTS — 25 M params, negligible traffic — not the activations),
# analytic variance tracking (alpha/beta), and SkipInit.  Adaptive gradient
# clipping (AGC), which the paper needs only at batch 4096+, is wired via
# optax: compose ``optax.adaptive_grad_clip(0.01)`` ahead of the optimizer
# (imagenet CLI: ``--agc 0.01``; composition with the multi-node optimizer
# is clip-engagement-tested in tests/test_resnet.py).

GAMMA_RELU = 1.7139588594436646  # sqrt(2/(1-1/pi)): restores unit variance


class ScaledWSConv(nn.Module):
    """Conv with scaled weight standardization + learnable per-channel gain.

    W_hat = gain * (W - mean) / sqrt(var * fan_in + eps), statistics taken
    per output channel over (kh, kw, cin).  All the normalization work is
    on the 25 M-param weight tensor — O(params) traffic instead of BN's
    O(activations) — so the activation path is a bare conv the TPU can
    stream at the HBM floor."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: int = 1
    dtype: Any = jnp.bfloat16
    padding: Any = "SAME"
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel
        cin = x.shape[-1]
        w = self.param("kernel", nn.initializers.he_normal(),
                       (kh, kw, cin, self.features), jnp.float32)
        gain = self.param("gain", nn.initializers.ones,
                          (self.features,), jnp.float32)
        mu = w.mean((0, 1, 2), keepdims=True)
        var = w.var((0, 1, 2), keepdims=True)
        fan_in = kh * kw * cin
        w_hat = (w - mu) * jax.lax.rsqrt(var * fan_in + 1e-4) * gain
        if self.conv_impl == "pallas" and self.padding == "SAME":
            # Pallas backward for every eligible conv (stride-1 3x3 AND
            # 1x1 on planes >= 14x14 — see _eligible); conv2d falls back
            # to the XLA transpose only for stride-2 / tiny planes, so
            # routing every SAME conv through it is behavior-safe.
            return conv_backward.conv2d(x.astype(self.dtype),
                                        w_hat.astype(self.dtype),
                                        self.strides)
        return jax.lax.conv_general_dilated(
            x.astype(self.dtype), w_hat.astype(self.dtype),
            (self.strides, self.strides), self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class NFBottleneckBlock(nn.Module):
    """Pre-activation normalizer-free bottleneck:
    ``x + alpha * skip_gain * f(relu(x / beta) * gamma)`` with SkipInit
    (skip_gain zero-init) so every block starts as identity."""

    filters: int
    beta: float  # sqrt of the analytically tracked input variance
    strides: int = 1
    alpha: float = 0.2
    dtype: Any = jnp.bfloat16
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        conv = partial(ScaledWSConv, dtype=self.dtype,
                       conv_impl=self.conv_impl)
        act = lambda v: nn.relu(v) * GAMMA_RELU  # noqa: E731
        out = act(x / self.beta)
        if self.strides > 1 or x.shape[-1] != self.filters * 4:
            # transition: the shortcut consumes the NORMALIZED activated
            # input, resetting its variance to ~1
            shortcut = conv(self.filters * 4, (1, 1), strides=self.strides,
                            name="conv_shortcut")(out)
        else:
            shortcut = x
        y = act(conv(self.filters, (1, 1))(out))
        y = act(conv(self.filters, (3, 3), strides=self.strides)(y))
        y = conv(self.filters * 4, (1, 1))(y)
        skip_gain = self.param("skip_gain", nn.initializers.zeros,
                               (), jnp.float32)
        # trunk stays in bf16: an fp32 residual path re-inflates HBM traffic
        # past BN's (measured 45 GB vs 36 GB floor); the scalar gain is
        # folded in fp32, the add runs at compute dtype
        return shortcut + ((self.alpha * skip_gain).astype(self.dtype)
                           * y.astype(self.dtype))


class NFResNet(nn.Module):
    """Normalizer-free ResNet-v1.5-shaped network (NF-ResNet-50/101/152).

    Variance bookkeeping follows the NF-ResNet recipe: expected_var starts
    at 1 after the stem, grows by alpha^2 per block, and resets to
    1 + alpha^2 at transitions (their shortcut reads the normalized
    activated input)."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    alpha: float = 0.2
    dtype: Any = jnp.bfloat16
    stem_strides: int = 2
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no normalization layers; kept for ARCHS signature parity
        x = x.astype(self.dtype)
        x = ScaledWSConv(self.num_filters, (7, 7),
                         strides=self.stem_strides,
                         padding=[(3, 3), (3, 3)], dtype=self.dtype,
                         name="conv_init")(x)
        x = nn.relu(x) * GAMMA_RELU
        if self.stem_strides == 2:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        expected_var = 1.0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                transition = j == 0  # stage entry: width and/or stride jump
                x = NFBottleneckBlock(
                    self.num_filters * 2 ** i,
                    beta=float(expected_var) ** 0.5, strides=strides,
                    alpha=self.alpha, dtype=self.dtype,
                    conv_impl=self.conv_impl)(x)
                expected_var = (1.0 if transition else expected_var) \
                    + self.alpha ** 2
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


NFResNet50 = partial(NFResNet, stage_sizes=[3, 4, 6, 3])
NFResNet101 = partial(NFResNet, stage_sizes=[3, 4, 23, 3])
NFResNet152 = partial(NFResNet, stage_sizes=[3, 8, 36, 3])


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)

ARCHS: dict = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "nf_resnet50": NFResNet50,
    "nf_resnet101": NFResNet101,
    "nf_resnet152": NFResNet152,
}

# The reference's imagenet example shipped a zoo beyond ResNet
# (models/{alex,googlenet,...}.py [uv], SURVEY.md §2.9) — registered here so
# the CLI accepts them; defined in models/convnets.py (import at the bottom
# to avoid a cycle: convnets is standalone, ARCHS is the registry).
from .convnets import AlexNet, GoogLeNet, VGG16  # noqa: E402

ARCHS.update({
    "alex": AlexNet,
    "alexnet": AlexNet,
    "googlenet": GoogLeNet,
    "vgg16": VGG16,
})

from .vit import ViT_B16, ViT_S16, ViT_Ti16  # noqa: E402

ARCHS.update({
    "vit_ti16": ViT_Ti16,
    "vit_s16": ViT_S16,
    "vit_b16": ViT_B16,
})
