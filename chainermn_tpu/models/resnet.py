"""ResNet family (v1.5) for the ImageNet DP benchmark.

Reference parity: ``examples/imagenet/models/resnet50.py`` [uv]
(SURVEY.md §2.9) — the headline data-parallel workload (BASELINE configs
#2/#4 use ResNet-50/152).

TPU-first design: convs and matmuls run in bfloat16 (MXU-native), while
parameters, BatchNorm statistics and the softmax/loss stay float32 for
numerical stability — the TPU analog of the reference's
``allreduce_grad_dtype=float16`` compute/compress split.  Shapes are NHWC
(XLA:TPU's preferred conv layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        # zero-init the last BN scale so each block starts as identity —
        # standard large-batch ResNet trick (Goyal et al.), matters at the
        # batch sizes DP scaling targets
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides),
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = nn.relu(norm()(conv(self.filters, (1, 1))(x)))
        # v1.5: stride lives on the 3x3, not the 1x1
        y = nn.relu(norm()(conv(self.filters, (3, 3),
                                strides=(self.strides, self.strides))(y)))
        y = norm(scale_init=nn.initializers.zeros)(
            conv(self.filters * 4, (1, 1))(y))
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem_strides: int = 2  # small-image variants (CIFAR-style) can use 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_filters, (7, 7),
                    strides=(self.stem_strides, self.stem_strides),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="bn_init")(x)
        x = nn.relu(x)
        if self.stem_strides == 2:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        # head in float32: the tiny matmul costs nothing, the logits gain
        # a lot of precision
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)

ARCHS: dict = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}

# The reference's imagenet example shipped a zoo beyond ResNet
# (models/{alex,googlenet,...}.py [uv], SURVEY.md §2.9) — registered here so
# the CLI accepts them; defined in models/convnets.py (import at the bottom
# to avoid a cycle: convnets is standalone, ARCHS is the registry).
from .convnets import AlexNet, GoogLeNet, VGG16  # noqa: E402

ARCHS.update({
    "alex": AlexNet,
    "alexnet": AlexNet,
    "googlenet": GoogLeNet,
    "vgg16": VGG16,
})

from .vit import ViT_B16, ViT_S16, ViT_Ti16  # noqa: E402

ARCHS.update({
    "vit_ti16": ViT_Ti16,
    "vit_s16": ViT_S16,
    "vit_b16": ViT_B16,
})
