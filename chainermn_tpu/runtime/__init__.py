"""Native runtime components: the C++ prefetching data loader.

Reference relationship: the reference's input pipeline used Chainer's
``MultiprocessIterator`` (worker processes, because the GIL forbids
parallel batch assembly in threads) feeding ``scatter_dataset`` shards
(SURVEY.md §2.9).  The TPU runtime is one controller process per host, so
the native equivalent is a C++ thread pool (``_prefetch.cpp``) that
assembles batches from a record buffer into a ring of slots without ever
taking the GIL; Python-side cost per batch is two ctypes calls and a
numpy view.

The extension compiles on first use with the system ``g++`` (toolchain is
part of the runtime image; no pybind11 — plain ``extern "C"`` + ctypes).
When compilation is impossible the loader degrades to a pure-Python
fallback with identical semantics, so tests and CPU-only environments
never hard-fail.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_prefetch.cpp")
_LIB_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: Optional[str] = None


def _user_cache_dir() -> str:
    """Per-user, owner-only cache dir for the fallback build.

    A fixed world-visible path (e.g. /tmp/_prefetch.so) would let another
    local user pre-plant a .so that we then CDLL-load in-process (CWE-379);
    the dir here is keyed on uid, created 0700, and verified to be owned by
    us and not group/other-writable before anything is loaded from it.
    """
    d = os.path.join(tempfile.gettempdir(), f"chainermn-tpu-{os.getuid()}")
    os.makedirs(d, mode=0o700, exist_ok=True)
    st = os.stat(d)
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        raise OSError(f"refusing unsafe native-build cache dir {d}")
    return d


def _build_library() -> Optional[ctypes.CDLL]:
    """Compile _prefetch.cpp once per interpreter.

    The .so is keyed on a hash of the source (stale binaries are never
    trusted) and built beside the source, falling back to a per-user 0700
    cache dir when the package dir is read-only.
    """
    global _LIB, _LIB_ERR
    with _LIB_LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        with open(_SRC, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        errs = []
        for where in ("pkg", "cache"):
            try:
                out_dir = _HERE if where == "pkg" else _user_cache_dir()
                so_path = os.path.join(out_dir, f"_prefetch-{tag}.so")
                if not os.path.exists(so_path):
                    tmp = f"{so_path}.tmp{os.getpid()}"
                    try:
                        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                               "-pthread", _SRC, "-o", tmp]
                        subprocess.run(cmd, check=True, capture_output=True,
                                       timeout=120)
                        os.replace(tmp, so_path)  # atomic vs other builders
                    finally:
                        if os.path.exists(tmp):
                            os.unlink(tmp)
                    # GC binaries of older source revisions (and the
                    # untagged name from pre-hash builds).
                    for old in os.listdir(out_dir):
                        if (old.startswith("_prefetch") and old.endswith(".so")
                                and old != os.path.basename(so_path)):
                            try:
                                os.unlink(os.path.join(out_dir, old))
                            except OSError:
                                pass
                _LIB = ctypes.CDLL(so_path)
                break
            except (OSError, subprocess.SubprocessError) as e:
                errs.append(f"{where}: {e}")
        if _LIB is None:
            _LIB_ERR = "; ".join(errs) or "unknown"
            return None
        _LIB.pfl_create.restype = ctypes.c_void_p
        _LIB.pfl_create.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int]
        _LIB.pfl_create_file.restype = ctypes.c_void_p
        _LIB.pfl_create_file.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        _LIB.pfl_set_order.restype = ctypes.c_int
        _LIB.pfl_set_order.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        _LIB.pfl_cancel.restype = ctypes.c_int
        _LIB.pfl_cancel.argtypes = [ctypes.c_void_p]
        _LIB.pfl_acquire.restype = ctypes.c_int64
        _LIB.pfl_acquire.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        _LIB.pfl_release.restype = None
        _LIB.pfl_release.argtypes = [ctypes.c_void_p]
        _LIB.pfl_destroy.restype = None
        _LIB.pfl_destroy.argtypes = [ctypes.c_void_p]
        return _LIB


def native_available() -> bool:
    """True when the C++ prefetcher compiled (or was already cached)."""
    return _build_library() is not None


class _Fields:
    """Field packing: (N, …) arrays ⇄ one contiguous (N, record_bytes)
    uint8 buffer the C++ side can memcpy rows from."""

    def __init__(self, arrays: Sequence[np.ndarray]):
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError("all field arrays must share the leading dim")
        self.shapes = [a.shape[1:] for a in arrays]
        self.dtypes = [a.dtype for a in arrays]
        flat = [np.ascontiguousarray(a).reshape(n, -1).view(np.uint8)
                for a in arrays]
        self.packed = (flat[0] if len(flat) == 1
                       else np.concatenate(flat, axis=1))
        self.packed = np.ascontiguousarray(self.packed)
        self.record_bytes = self.packed.shape[1]
        self.n_records = n

    def unpack(self, raw: np.ndarray):
        """(B, record_bytes) uint8 → tuple of (B, …) field arrays."""
        out, off = [], 0
        for shape, dtype in zip(self.shapes, self.dtypes):
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            fld = raw[:, off:off + nbytes].view(dtype).reshape(
                (len(raw),) + tuple(shape))
            out.append(fld)
            off += nbytes
        return tuple(out) if len(out) > 1 else out[0]


_META_NAME = "meta.json"
_DATA_NAME = "data.bin"


def write_file_dataset(path: str, arrays: Sequence[np.ndarray],
                       chunk_records: int = 256) -> None:
    """Materialize a dataset to disk in the prefetcher's record format.

    Layout: ``path/data.bin`` holds N contiguous packed records (each
    record = the concatenated raw bytes of every field's row — exactly
    what the C++ workers pread into batch slots), ``path/meta.json``
    holds shapes/dtypes.  Written in ``chunk_records`` blocks so an
    ImageNet-scale dataset never needs 2× memory.

    Reference frame: the on-disk stage the reference's
    ``examples/imagenet/train_imagenet.py`` [uv] read via Chainer dataset
    files + MultiprocessIterator; here the format is flat records because
    the consumer is ``pread``-ing C++ threads, not worker processes.
    """
    import json

    arrays = [np.ascontiguousarray(a) for a in arrays]
    n = len(arrays[0])
    if any(len(a) != n for a in arrays):
        raise ValueError("all field arrays must share the leading dim")
    os.makedirs(path, exist_ok=True)
    meta = {
        "version": 1,
        "n_records": int(n),
        "fields": [{"shape": list(a.shape[1:]), "dtype": str(a.dtype)}
                   for a in arrays],
    }
    meta["record_bytes"] = int(sum(
        int(np.prod(f["shape"], dtype=np.int64))
        * np.dtype(f["dtype"]).itemsize for f in meta["fields"]))
    with open(os.path.join(path, _DATA_NAME), "wb") as f:
        for start in range(0, n, chunk_records):
            stop = min(start + chunk_records, n)
            rows = [a[start:stop].reshape(stop - start, -1).view(np.uint8)
                    for a in arrays]
            block = rows[0] if len(rows) == 1 else np.concatenate(rows, axis=1)
            f.write(np.ascontiguousarray(block).tobytes())
    tmp = os.path.join(path, f".{_META_NAME}.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(path, _META_NAME))  # meta last = commit


class FileDataset:
    """A dataset materialized by :func:`write_file_dataset`.

    Random access (``len`` / ``[i]`` → tuple of field rows) goes through a
    lazy ``np.memmap``; the fast path is handing the WHOLE object to
    :class:`PrefetchIterator`, whose C++ workers then ``pread`` batches
    straight from the file without Python or the memmap in the loop.
    """

    def __init__(self, path: str):
        import json

        self.path = path
        self.data_path = os.path.join(path, _DATA_NAME)
        with open(os.path.join(path, _META_NAME)) as f:
            meta = json.load(f)
        if meta.get("version") != 1:
            raise ValueError(f"unsupported dataset version {meta.get('version')}")
        self.n_records = int(meta["n_records"])
        self.record_bytes = int(meta["record_bytes"])
        self.shapes = [tuple(f["shape"]) for f in meta["fields"]]
        self.dtypes = [np.dtype(f["dtype"]) for f in meta["fields"]]
        expect = self.n_records * self.record_bytes
        actual = os.path.getsize(self.data_path)
        if actual != expect:
            raise ValueError(
                f"{self.data_path}: size {actual} != n_records×record_bytes "
                f"{expect} — truncated or foreign file")
        self._mm = None

    @property
    def packed(self) -> np.ndarray:
        if self._mm is None:
            self._mm = np.memmap(self.data_path, dtype=np.uint8, mode="r",
                                 shape=(self.n_records, self.record_bytes))
        return self._mm

    def unpack(self, raw: np.ndarray):
        out, off = [], 0
        for shape, dtype in zip(self.shapes, self.dtypes):
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            fld = raw[:, off:off + nbytes].view(dtype).reshape(
                (len(raw),) + tuple(shape))
            out.append(fld)
            off += nbytes
        return tuple(out) if len(out) > 1 else out[0]

    def __len__(self) -> int:
        return self.n_records

    def __getitem__(self, i: int):
        row = self.unpack(np.asarray(self.packed[i:i + 1]))
        return (tuple(f[0] for f in row) if isinstance(row, tuple)
                else row[0])


class PrefetchIterator:
    """Drop-in :class:`~chainermn_tpu.iterators.SerialIterator` analog with
    native prefetch: batches are (tuples of) stacked numpy arrays.

    ``dataset``: one array ``(N, …)`` or a tuple of arrays (e.g. images,
    labels).  The batch contract matches SerialIterator exactly: with
    ``repeat=True`` every batch has ``batch_size`` rows (epoch-boundary
    batches pad from the next epoch's order, so jitted steps never see a
    shape change); with ``repeat=False`` the final batch may be short.
    Epoch-interior batches are assembled by the C++ workers; boundary
    batches are gathered in Python.  Exposes the same epoch/position/
    reset/state_dict surface so the Trainer, multi-node iterator wrappers
    and checkpointer compose unchanged.
    """

    def __init__(self, dataset, batch_size: int, repeat: bool = True,
                 shuffle: bool = True, seed: Optional[int] = None,
                 n_threads: int = 8, n_slots: int = 16,
                 copy: bool = False, use_native: Optional[bool] = None):
        file_backed = isinstance(dataset, FileDataset)
        if file_backed:
            # FileDataset quacks like _Fields (n_records/record_bytes/
            # packed/unpack); the native handle preads from its data file.
            self._fields = dataset
        else:
            arrays = (dataset if isinstance(dataset, (tuple, list))
                      else (dataset,))
            self._fields = _Fields([np.asarray(a) for a in arrays])
        self._copy = copy
        self._held = False  # consumer currently holds a slot (deferred release)
        self.batch_size = int(batch_size)
        self.repeat = repeat
        self.shuffle = shuffle
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self.epoch = 0
        self.current_position = 0
        self.is_new_epoch = False
        self._order = self._new_order()

        lib = _build_library() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError(f"native prefetcher unavailable: {_LIB_ERR}")
        self._lib = lib
        self._handle = None
        if lib is not None:
            if file_backed:
                self._handle = lib.pfl_create_file(
                    dataset.data_path.encode(), 0,
                    self._fields.record_bytes, self._fields.n_records,
                    self.batch_size, int(n_slots), int(n_threads))
            else:
                self._handle = lib.pfl_create(
                    self._fields.packed.ctypes.data,
                    self._fields.record_bytes, self._fields.n_records,
                    self.batch_size, int(n_slots), int(n_threads))
            if self._handle:
                self._push_stream()

    # -- ordering ---------------------------------------------------------
    def _new_order(self) -> np.ndarray:
        n = self._fields.n_records
        return (self._rng.permutation(n) if self.shuffle
                else np.arange(n)).astype(np.int64)

    def _push_stream(self):
        """Hand the C++ side the full batches from the current position."""
        self._release_held()
        rest = np.ascontiguousarray(self._order[self.current_position:])
        rc = self._lib.pfl_set_order(
            self._handle,
            rest.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(rest))
        if rc != 0:
            raise RuntimeError("pfl_set_order called with batches in flight")
        self._stream = rest  # keep alive: C++ copies, but be defensive

    # -- iteration --------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        n = self._fields.n_records
        if not self.repeat and self.epoch > 0 and self.current_position == 0:
            self._release_held()
            raise StopIteration
        i = self.current_position
        stop = min(i + self.batch_size, n)
        native_ok = self._handle is not None and stop - i == self.batch_size

        if native_ok:
            batch = self._next_native()
            idx = None
        else:
            idx = list(self._order[i:stop])

        # Position/epoch accounting — identical to SerialIterator,
        # including cross-epoch padding of the boundary batch.
        if stop >= n:
            self.epoch += 1
            self.is_new_epoch = True
            self.current_position = 0
            self._order = self._new_order()
            if self.repeat and idx is not None:
                while len(idx) < self.batch_size:
                    take = min(self.batch_size - len(idx), n)
                    idx.extend(self._order[:take])
                    self.current_position = take % n
                    if take == n:
                        self.epoch += 1
                        self._order = self._new_order()
        else:
            self.is_new_epoch = False
            self.current_position = stop

        if idx is not None:
            sel = np.asarray(idx, np.int64)
            batch = self._fields.unpack(
                np.ascontiguousarray(self._fields.packed[sel]))

        if self.is_new_epoch and self._handle and self.repeat:
            # The new stream may recycle the slot backing `batch` —
            # detach it before handing the ring back to the workers.
            if self._held and not self._copy:
                batch = (tuple(np.array(f) for f in batch)
                         if isinstance(batch, tuple) else np.array(batch))
            self._push_stream()
        return batch

    next = __next__

    def reset(self) -> None:
        self._rng = np.random.RandomState(self._seed)
        self.epoch = 0
        self.current_position = 0
        self.is_new_epoch = False
        self._order = self._new_order()
        if self._handle:
            self._drain()
            self._push_stream()

    def _release_held(self):
        if self._held:
            self._lib.pfl_release(self._handle)
            self._held = False

    def _next_native(self):
        # Deferred release: the PREVIOUS batch's slot goes back to the
        # workers now, so by default the yielded arrays are views valid
        # until the next ``next()`` (the training loop device_puts them
        # immediately; pass copy=True to detach instead).  This keeps the
        # visible per-batch cost at ~zero — assembly happened in C++
        # threads while the previous step computed.
        self._release_held()
        out = ctypes.c_void_p()
        b = self._lib.pfl_acquire(self._handle, ctypes.byref(out))
        if b == -3:
            raise RuntimeError(
                "prefetcher disk read failed (file truncated/removed or "
                "I/O error mid-stream); the stream is poisoned — recreate "
                "the iterator after fixing the data file")
        if b < 0:
            raise RuntimeError(f"prefetcher stream desync (code {b})")
        self._held = True
        raw = np.ctypeslib.as_array(
            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
            shape=(self.batch_size, self._fields.record_bytes))
        batch = self._fields.unpack(raw)
        if self._copy:
            batch = (tuple(np.array(f) for f in batch)
                     if isinstance(batch, tuple) else np.array(batch))
        return batch

    @property
    def epoch_detail(self) -> float:
        return self.epoch + self.current_position / max(
            self._fields.n_records, 1)

    # -- resume (same contract as SerialIterator) -------------------------
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "current_position": self.current_position,
            "is_new_epoch": self.is_new_epoch,
            "order": np.asarray(self._order),
            "rng_state": self._rng.get_state(),
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self.current_position = int(state["current_position"])
        self.is_new_epoch = bool(state["is_new_epoch"])
        self._order = np.asarray(state["order"], np.int64)
        self._rng.set_state(state["rng_state"])
        if self._handle:
            # Drain whatever the workers had queued, then restart the
            # stream from the restored position.
            self._drain()
            self._push_stream()

    def _drain(self):
        """Abandon the in-flight stream in O(1) (pfl_cancel), not O(stream)."""
        self._release_held()
        rc = self._lib.pfl_cancel(self._handle)
        if rc != 0:
            raise RuntimeError("pfl_cancel with a slot still held")

    def close(self):
        if getattr(self, "_handle", None):
            self._release_held()
            self._lib.pfl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


__all__ = ["FileDataset", "PrefetchIterator", "native_available",
           "write_file_dataset"]
