// Native batch-assembly prefetcher for the data-loading hot path.
//
// Reference relationship: the reference's input pipeline leaned on
// Chainer's MultiprocessIterator (worker *processes* assembling batches,
// SURVEY.md §2.9 "ImageNet ... MultiprocessIterator + scatter") because
// CPython threads can't copy batches in parallel under the GIL.  The
// TPU-native rebuild keeps the runtime in-process (one controller process
// per host talking to its chips) so the equivalent is worker *threads* in
// C++ that never touch the GIL: they gather records from a caller-owned
// buffer (in-memory or np.memmap'd) into a ring of pre-assembled batch
// slots, while Python only flips pointers.
//
// Contract (single consumer, in-order delivery):
//   h = pfl_create(data, record_bytes, n_records, batch_size, slots, thr)
//   pfl_set_order(h, indices, n)   // defines floor(n/batch) batches
//   while ((b = pfl_acquire(h, &p)) >= 0) { consume p; pfl_release(h); }
//   pfl_destroy(h)
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread (see runtime/__init__.py
// :: _build_library).

#include <fcntl.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<uint8_t> buf;
  int64_t batch = -1;  // which batch currently occupies this slot (-1 free)
  bool consumed = true;
};

struct Loader {
  const uint8_t* data;     // in-memory mode (null in file mode)
  int fd = -1;             // file mode: records pread() from data_offset
  int64_t data_offset = 0;
  bool io_error = false;   // sticky; surfaced via pfl_acquire() == -3
  int64_t record_bytes, n_records, batch_size;
  int n_slots;

  std::vector<Slot> slots;
  std::vector<int64_t> order;
  // All stream/claim state lives under `mu` — a claimed-but-unconsumed
  // batch blocks set_order, so no stale claims can poison a slot.
  int64_t n_batches = 0;
  int64_t next_build = 0;
  int64_t next_consume = 0;
  int64_t acquired = -1;  // slot index currently held by the consumer
  int64_t gen = 0;        // stream generation; pfl_cancel bumps it so
                          // workers parked on a cancelled stream's claims
                          // drop them instead of filling from a stale order

  std::mutex mu;
  std::condition_variable cv_slot_free, cv_batch_ready;
  bool stop = false;
  int filling = 0;  // workers currently copying outside the lock
  std::vector<std::thread> workers;

  // Returns false on I/O failure (file mode only); the caller marks the
  // loader poisoned rather than publishing a half-filled batch.
  bool fill(int64_t b, Slot& slot) {
    const int64_t* idx = order.data() + b * batch_size;
    for (int64_t r = 0; r < batch_size; ++r) {
      uint8_t* dst = slot.buf.data() + r * record_bytes;
      if (fd >= 0) {
        int64_t off = data_offset + idx[r] * record_bytes;
        int64_t done = 0;
        while (done < record_bytes) {
          ssize_t got = pread(fd, dst + done,
                              static_cast<size_t>(record_bytes - done),
                              static_cast<off_t>(off + done));
          if (got <= 0) return false;  // EOF mid-record or read error
          done += got;
        }
      } else {
        std::memcpy(dst, data + idx[r] * record_bytes,
                    static_cast<size_t>(record_bytes));
      }
    }
    return true;
  }

  void work() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      // Claim the next batch of the current stream (park when exhausted).
      while (!stop && next_build >= n_batches) cv_slot_free.wait(lk);
      if (stop) return;
      int64_t g = gen;
      int64_t b = next_build++;
      // Turn gate: fill only once the slot's previous occupant (batch
      // b - n_slots) has been CONSUMED.  A bare slot.consumed check is
      // racy — the worker holding batch b+n_slots could steal the slot
      // the moment the consumer frees it, deadlocking batch b.
      Slot& slot = slots[b % n_slots];
      while (!stop && gen == g && next_consume + n_slots <= b)
        cv_slot_free.wait(lk);
      if (stop) return;
      if (gen != g) continue;  // stream cancelled while parked: drop claim
      slot.consumed = false;
      slot.batch = -1;  // mark "filling"
      ++filling;
      lk.unlock();
      bool ok = fill(b, slot);  // the GIL-free hot copy, outside the lock
      lk.lock();
      --filling;
      if (!ok) io_error = true;          // poison: consumer sees -3
      else if (gen == g) slot.batch = b; // publish only into the same stream
      cv_batch_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* pfl_create(const void* data, int64_t record_bytes, int64_t n_records,
                 int64_t batch_size, int n_slots, int n_threads) {
  if (record_bytes <= 0 || batch_size <= 0 || n_slots < 2 || n_threads < 1)
    return nullptr;
  auto* L = new Loader();
  L->data = static_cast<const uint8_t*>(data);
  L->record_bytes = record_bytes;
  L->n_records = n_records;
  L->batch_size = batch_size;
  L->n_slots = n_slots;
  L->slots.resize(n_slots);
  for (auto& s : L->slots)
    s.buf.resize(static_cast<size_t>(batch_size * record_bytes));
  for (int i = 0; i < n_threads; ++i)
    L->workers.emplace_back([L] { L->work(); });
  return L;
}

// File-backed variant: records live in `path` starting at `data_offset`
// (raw packed rows, the layout write_file_dataset emits); worker threads
// pread() them straight into batch slots — the disk analog of the
// reference's MultiprocessIterator feeding ImageNet from local storage.
void* pfl_create_file(const char* path, int64_t data_offset,
                      int64_t record_bytes, int64_t n_records,
                      int64_t batch_size, int n_slots, int n_threads) {
  if (record_bytes <= 0 || batch_size <= 0 || n_slots < 2 || n_threads < 1)
    return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  auto* L = new Loader();
  L->data = nullptr;
  L->fd = fd;
  L->data_offset = data_offset;
  L->record_bytes = record_bytes;
  L->n_records = n_records;
  L->batch_size = batch_size;
  L->n_slots = n_slots;
  L->slots.resize(n_slots);
  for (auto& s : L->slots)
    s.buf.resize(static_cast<size_t>(batch_size * record_bytes));
  for (int i = 0; i < n_threads; ++i)
    L->workers.emplace_back([L] { L->work(); });
  return L;
}

// Abandon the current stream in O(1): no new claims, wait out in-flight
// fills, reset the ring.  Caller must have released any held slot.
int pfl_cancel(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->acquired >= 0) return -1;
  ++L->gen;           // invalidates every outstanding claim
  L->n_batches = 0;   // parks claim loops immediately
  L->next_build = 0;
  L->next_consume = 0;
  L->cv_slot_free.notify_all();  // wake gate-parked workers to drop claims
  while (L->filling > 0) {
    // Workers mid-copy finish into their slot but skip the publish (gen
    // mismatch); cv_batch_ready fires exactly on that finish.
    L->cv_batch_ready.wait(lk);
  }
  for (auto& s : L->slots) { s.batch = -1; s.consumed = true; }
  return 0;
}

// Define a new stream. Caller must have consumed the previous stream fully
// (next_consume == n_batches) — enforced by returning -1 on violation.
int pfl_set_order(void* h, const int64_t* idx, int64_t n_idx) {
  auto* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->next_consume < L->n_batches || L->acquired >= 0) return -1;
  int64_t nb = n_idx / L->batch_size;
  L->order.assign(idx, idx + nb * L->batch_size);
  for (auto& s : L->slots) { s.batch = -1; s.consumed = true; }
  L->n_batches = nb;
  L->next_consume = 0;
  L->next_build = 0;
  L->cv_slot_free.notify_all();
  return 0;
}

// Blocks until the next in-order batch is assembled; returns its index and
// sets *out to the slot buffer, or returns -1 when the stream is done.
int64_t pfl_acquire(void* h, void** out) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->acquired >= 0) return -2;  // release first
  if (L->next_consume >= L->n_batches) return -1;
  int64_t b = L->next_consume;
  Slot& slot = L->slots[b % L->n_slots];
  while (!L->stop && !L->io_error && slot.batch != b)
    L->cv_batch_ready.wait(lk);
  if (L->io_error) return -3;  // disk read failed; stream is poisoned
  if (L->stop) return -1;
  L->acquired = b % L->n_slots;
  *out = slot.buf.data();
  return b;
}

void pfl_release(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->acquired < 0) return;
  Slot& slot = L->slots[L->acquired];
  slot.consumed = true;
  slot.batch = -1;
  L->acquired = -1;
  ++L->next_consume;
  L->cv_slot_free.notify_all();
}

void pfl_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
  }
  L->cv_slot_free.notify_all();
  L->cv_batch_ready.notify_all();
  for (auto& t : L->workers) t.join();
  if (L->fd >= 0) close(L->fd);
  delete L;
}

}  // extern "C"
