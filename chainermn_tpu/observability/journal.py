"""Causal fleet journal: one HLC-ordered happens-before timeline.

Every observability plane before this PR is per-process (tracer, comm
ledger, flight ring, /statusz), so the fleet's actually-distributed
behavior — lease beats, epoch fences, failovers, remote KV pulls —
could only be reconstructed post-hoc from per-worker bundles with
unrelated wall clocks.  This module closes the gap with a hybrid
logical clock (HLC):

* **HLC stamp** — ``(l, c)`` where ``l`` is the max physical clock
  (microseconds) this process has SEEN (its own, or any peer's via a
  received message) and ``c`` is a logical counter breaking ties.  A
  local event ticks; a receive merges the sender's stamp, so every
  send→receive pair is ordered ``stamp(send) < stamp(recv)`` no matter
  how skewed the wall clocks are.  The stamp rides as ONE extra field
  (``hlc``) in the existing ``worker_lane.v1`` mailbox dicts and lease
  payloads — no new wire, no new schema rev.

* **Per-process journal** (:class:`Journal`) — a bounded, line-buffered
  ``journal.<proc>.jsonl`` next to the flight ring: every distributed
  state transition already noted somewhere (fleet dispatch/failover/
  shed, beats, fences, cache pulls, autoscale, gang heal — via the
  :func:`~.flight.note` tee) plus the wire-level events (mailbox
  send/receive, beat/lease-judged) gets one ``journal.v1`` line with
  its HLC stamp.  Line-buffered append means a SIGKILL'd process keeps
  every line it wrote — the journal is chaos evidence, like the ring.

* **merge** (:func:`merge_journals`) — fold N per-process journals into
  ONE total order by ``(l, c, proc, seq)``.  Per-process stamps are
  strictly increasing, so the merged order is consistent with every
  per-process program order; the receive-merge rule makes it consistent
  with every send→receive edge (the happens-before property the fuzz
  in tests/test_journal.py checks).  :func:`happens_before_edges`
  extracts the explicit cross-process edges (mailbox seq pairs, lease
  seq pairs) for causal-chain rendering, and
  :func:`export_perfetto` renders the merged timeline as one Perfetto
  lane per process through the existing
  :func:`~.aggregate.merge_trace_shards` machinery.

Everything is a no-op until :func:`configure` runs (``wire_stamp``
returns None, so senders only add the ``hlc`` field when journaling is
on — zero overhead off).  Stdlib only; safe without a JAX backend.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: Schema stamp carried by every journal line.
JOURNAL_SCHEMA = "chainermn_tpu.journal.v1"

#: Schema of the merged fleet timeline document.
MERGE_SCHEMA = "chainermn_tpu.journal_merge.v1"

#: Env var pair that configures the journal in spawned workers (the
#: ``--journal-dir`` CLI flag sets them for its own process instead).
ENV_DIR = "CHAINERMN_JOURNAL_DIR"
ENV_PROC = "CHAINERMN_JOURNAL_PROC"

#: Flight-note kinds NOT teed into the journal: tracer span/instant
#: tees are per-process latency detail, not distributed state.
_TEE_EXCLUDE = ("span", "instant")


class HLC:
    """Hybrid logical clock: ``(l, c)`` with physical microseconds in
    ``l``.  Thread-safe; both faces strictly increase the local stamp,
    so one process's journal is totally ordered by its own stamps."""

    def __init__(self, now_us: Optional[Callable[[], int]] = None):
        self._now_us = now_us or (lambda: int(time.time() * 1e6))
        self._l = 0
        self._c = 0
        self._lock = threading.Lock()

    def tick(self) -> Tuple[int, int]:
        """Stamp a local event (send included)."""
        pt = self._now_us()
        with self._lock:
            if pt > self._l:
                self._l, self._c = pt, 0
            else:
                self._c += 1
            return self._l, self._c

    def merge(self, remote: Optional[Sequence[int]]) -> Tuple[int, int]:
        """Stamp a receive event, folding in the sender's stamp so the
        receive orders strictly after the send."""
        if not remote:
            return self.tick()
        rl, rc = int(remote[0]), int(remote[1])
        pt = self._now_us()
        with self._lock:
            if pt > self._l and pt > rl:
                self._l, self._c = pt, 0
            elif rl > self._l:
                self._l, self._c = rl, rc + 1
            elif self._l > rl:
                self._c += 1
            else:
                self._c = max(self._c, rc) + 1
            return self._l, self._c

    def read(self) -> Tuple[int, int]:
        with self._lock:
            return self._l, self._c


class Journal:
    """Bounded per-process HLC journal file (``journal.<proc>.jsonl``).

    ``capacity`` bounds the RETAINED line count: the file grows to
    ``2*capacity`` lines, then compacts (atomically, tmp + replace) to
    the newest ``capacity`` — amortized O(1) per event, and a reader
    always sees a complete file.  Writes are line-buffered so a killed
    process keeps everything it journaled (the chaos-evidence
    contract the flight ring already honors).
    """

    DEFAULT_CAPACITY = 20000

    def __init__(self, path: str, proc: str,
                 capacity: int = DEFAULT_CAPACITY):
        self.path = str(path)
        self.proc = str(proc)
        self.capacity = int(capacity)
        self.hlc = HLC()
        self._lock = threading.Lock()
        self._seq = 0
        self._lines = 0
        self.dropped = 0
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._f = open(self.path, "a", buffering=1)

    # ---- emit faces ----
    def emit(self, kind: str,
             _stamp: Optional[Tuple[int, int]] = None,
             **fields) -> Tuple[int, int]:
        """Journal one local event; returns its HLC stamp."""
        stamp = _stamp if _stamp is not None else self.hlc.tick()
        self._write(kind, stamp, fields)
        return stamp

    def wire_emit(self, kind: str, **fields) -> List[int]:
        """Journal a SEND event and return the stamp for the wire (the
        message's ``hlc`` field must be the send event's own stamp)."""
        stamp = self.hlc.tick()
        self._write(kind, stamp, fields)
        return [stamp[0], stamp[1]]

    def recv_emit(self, remote: Optional[Sequence[int]], kind: str,
                  **fields) -> Tuple[int, int]:
        """Journal a RECEIVE event, merging the sender's wire stamp."""
        stamp = self.hlc.merge(remote)
        self._write(kind, stamp, fields)
        return stamp

    def _write(self, kind: str, stamp: Tuple[int, int],
               fields: Dict[str, Any]) -> None:
        ev = {"schema": JOURNAL_SCHEMA, "proc": self.proc,
              "kind": str(kind), "hlc": [stamp[0], stamp[1]],
              "t": round(time.time(), 6)}
        for k, v in fields.items():
            if k not in ev:
                ev[k] = v
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            try:
                self._f.write(json.dumps(ev, default=str,
                                         sort_keys=True) + "\n")
            except ValueError:
                return   # closed mid-teardown: never raise on emit
            self._lines += 1
            if self._lines > 2 * self.capacity:
                n = self._compact()
                if n is not None:
                    self._lines = n

    def _compact(self) -> Optional[int]:
        """Rewrite the file to its newest ``capacity`` lines and return
        the new line count, or None if compaction failed (caller holds
        the lock and owns ``_lines``)."""
        try:
            self._f.flush()
            with open(self.path) as f:
                lines = f.readlines()
            keep = lines[-self.capacity:]
            self.dropped += max(len(lines) - len(keep), 0)
            tmp = f"{self.path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                f.writelines(keep)
            os.replace(tmp, self.path)
            self._f.close()
            self._f = open(self.path, "a", buffering=1)
            return len(keep)
        except OSError:
            return None   # compaction is best-effort; emission survives

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# module-global journal (mirrors the flight module's global-ring shape)
# ---------------------------------------------------------------------------

_JOURNAL: Optional[Journal] = None


def journal_path(journal_dir: str, proc: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in str(proc)) or "proc"
    return os.path.join(journal_dir, f"journal.{safe}.jsonl")


def configure(journal_dir: str, proc: str,
              capacity: int = Journal.DEFAULT_CAPACITY) -> Journal:
    """Open this process's journal and tee flight notes into it.
    Idempotent per (dir, proc); reconfiguring closes the old file."""
    global _JOURNAL
    if (_JOURNAL is not None and _JOURNAL.proc == str(proc)
            and os.path.dirname(_JOURNAL.path)
            == os.path.abspath(journal_dir)):
        return _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.close()
    _JOURNAL = Journal(journal_path(os.path.abspath(journal_dir), proc),
                       proc, capacity)
    from . import flight as _flight
    _flight.set_journal_tee(_tee)
    return _JOURNAL


def configure_from_env() -> Optional[Journal]:
    """Configure from ``CHAINERMN_JOURNAL_DIR``/``_PROC`` when set (the
    spawned-worker path: the fleet passes them via the environment)."""
    d = os.environ.get(ENV_DIR)
    if not d:
        return None
    proc = os.environ.get(ENV_PROC) or f"pid{os.getpid()}"
    return configure(d, proc)


def reset() -> None:
    """Close and detach the global journal (tests)."""
    global _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.close()
        _JOURNAL = None
    from . import flight as _flight
    _flight.set_journal_tee(None)


def get_journal() -> Optional[Journal]:
    return _JOURNAL


def enabled() -> bool:
    return _JOURNAL is not None


def emit(kind: str, **fields) -> None:
    j = _JOURNAL
    if j is not None:
        j.emit(kind, **fields)


def wire_emit(kind: str, **fields) -> Optional[List[int]]:
    """Journal a send event; returns the wire stamp, or None when the
    journal is off (senders add the ``hlc`` field only when not None —
    the zero-overhead-off contract)."""
    j = _JOURNAL
    if j is None:
        return None
    return j.wire_emit(kind, **fields)


def recv_emit(remote: Optional[Sequence[int]], kind: str,
              **fields) -> None:
    j = _JOURNAL
    if j is not None:
        j.recv_emit(remote, kind, **fields)


def _tee(kind: str, fields: Dict[str, Any]) -> None:
    """The flight-note tee: every distributed state transition already
    noted into the ring lands in the journal too (minus tracer noise)."""
    j = _JOURNAL
    if j is None or kind in _TEE_EXCLUDE:
        return
    try:
        j.emit(kind, **fields)
    except Exception:   # noqa: BLE001 — a journal fault must never
        pass            # break the emitter's hot path


# ---------------------------------------------------------------------------
# merge: N per-process journals -> one happens-before timeline
# ---------------------------------------------------------------------------

def sort_key(ev: Dict[str, Any]) -> Tuple[int, int, str, int]:
    """The merged total order: HLC first (captures happens-before),
    then (proc, seq) as a deterministic tie-break for concurrency."""
    hlc = ev.get("hlc") or [0, 0]
    return (int(hlc[0]), int(hlc[1]), str(ev.get("proc")),
            int(ev.get("seq", 0)))


def read_journal(path: str) -> List[Dict[str, Any]]:
    """One journal file's events (schema-checked; torn tail lines from
    a mid-write kill are skipped, foreign schemas are refused)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue   # torn tail: the writer died mid-line
            if ev.get("schema") != JOURNAL_SCHEMA:
                raise ValueError(
                    f"refusing journal line with schema "
                    f"{ev.get('schema')!r} in {path!r} (this reader "
                    f"speaks {JOURNAL_SCHEMA})")
            out.append(ev)
    return out


def find_journals(journal_dir: str) -> List[str]:
    return sorted(_glob.glob(os.path.join(str(journal_dir),
                                          "journal.*.jsonl")))


def merge_journals(journal_dir_or_paths,
                   out_path: Optional[str] = None) -> Dict[str, Any]:
    """Fold per-process journals into ONE totally-ordered fleet
    timeline.

    Returns ``{"schema", "procs", "events", "edges"}`` where ``events``
    is every journal line sorted by :func:`sort_key` (happens-before
    consistent: per-process stamps strictly increase, and a receive's
    merged stamp exceeds its send's) and ``edges`` the explicit
    cross-process send→receive pairs from
    :func:`happens_before_edges`.  Also written to ``out_path``
    (atomically) when given.
    """
    if isinstance(journal_dir_or_paths, (str, os.PathLike)):
        paths = find_journals(str(journal_dir_or_paths))
    else:
        paths = [str(p) for p in journal_dir_or_paths]
    events: List[Dict[str, Any]] = []
    procs: List[str] = []
    for p in paths:
        try:
            evs = read_journal(p)
        except OSError:
            continue
        events.extend(evs)
        for ev in evs:
            if ev.get("proc") not in procs:
                procs.append(ev["proc"])
    events.sort(key=sort_key)
    for i, ev in enumerate(events):
        ev["idx"] = i
    doc = {"schema": MERGE_SCHEMA, "procs": sorted(procs),
           "events": events,
           "edges": happens_before_edges(events)}
    if out_path:
        tmp = f"{out_path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, out_path)
    return doc


def happens_before_edges(events: Sequence[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """Explicit cross-process happens-before edges in a merged event
    list: mailbox ``mbx_send → mbx_recv`` pairs (matched on
    ``(mailbox, mseq)``) and lease ``beat → lease_judged`` pairs
    (matched on ``(worker, lseq)``).  Each edge is ``{"kind", "src",
    "dst"}`` with ``src``/``dst`` the event indices."""
    edges: List[Dict[str, Any]] = []
    sends: Dict[Tuple[str, int], int] = {}
    beats: Dict[Tuple[str, int], int] = {}
    for i, ev in enumerate(events):
        kind = ev.get("kind")
        if kind == "mbx_send":
            sends[(str(ev.get("mailbox")), int(ev.get("mseq", -1)))] = i
        elif kind == "mbx_recv":
            src = sends.get((str(ev.get("mailbox")),
                             int(ev.get("mseq", -1))))
            if src is not None:
                edges.append({"kind": "mailbox", "src": src, "dst": i})
        elif kind == "beat":
            beats[(str(ev.get("worker")), int(ev.get("lseq", -1)))] = i
        elif kind == "lease_judged":
            src = beats.get((str(ev.get("worker")),
                             int(ev.get("lseq", -1))))
            if src is not None:
                edges.append({"kind": "lease", "src": src, "dst": i})
    return edges


def format_event(ev: Dict[str, Any]) -> str:
    """One human line of a journal event (causal chains, --request)."""
    hlc = ev.get("hlc") or [0, 0]
    skip = {"schema", "proc", "kind", "hlc", "t", "seq", "idx"}
    detail = " ".join(
        f"{k}={ev[k]}" for k in sorted(ev) if k not in skip)
    return (f"hlc=({hlc[0]},{hlc[1]}) proc={ev.get('proc')} "
            f"{ev.get('kind')}" + (f" {detail}" if detail else ""))


# ---------------------------------------------------------------------------
# one request's cross-process causal story (explain_bundle --request)
# ---------------------------------------------------------------------------

def request_story(merged: Dict[str, Any],
                  trace_id: str) -> Dict[str, Any]:
    """Every journaled event of ONE request, in happens-before order,
    with the cross-process edges that connect them: submit → dispatch
    → [pull] → prefill → ticks → done/shed, failover hops included.
    The ``--request`` face of ``scripts/explain_bundle.py``."""
    evs = [e for e in merged.get("events", [])
           if e.get("trace_id") == trace_id]
    idxs = {e.get("idx") for e in evs}
    edges = [ed for ed in merged.get("edges", [])
             if ed.get("src") in idxs and ed.get("dst") in idxs]
    procs: List[str] = []
    for e in evs:
        if e.get("proc") not in procs:
            procs.append(e["proc"])
    outcome = None
    failovers = 0
    pulls = 0
    workers: List[str] = []
    for e in evs:
        if e.get("kind") != "fleet":
            continue
        event = e.get("event")
        if event in ("submitted", "dispatched", "redispatched"):
            w = e.get("to") if event == "redispatched" else e.get("worker")
            if w and w not in workers:
                workers.append(w)
        if event == "redispatched":
            failovers += 1
        elif str(event or "").startswith("remote_pull"):
            pulls += 1
        elif event == "finished":
            outcome = {"kind": "done", "worker": e.get("worker"),
                       "reason": e.get("reason")}
        elif event == "shed":
            outcome = {"kind": "shed"}
    return {"trace_id": trace_id, "events": evs, "edges": edges,
            "procs": procs, "workers": workers, "outcome": outcome,
            "failovers": failovers, "remote_pull_events": pulls}


def _event_label(e: Dict[str, Any]) -> str:
    kind = str(e.get("kind"))
    sub = e.get("event")
    return f"{kind}:{sub}" if sub else kind


def request_critical_path(merged: Dict[str, Any],
                          trace_id: str) -> Dict[str, Any]:
    """The longest chain of one served request (ISSUE 20): walk the
    request's journaled events in happens-before order and attribute
    the wall between each consecutive pair to a named SEGMENT
    (``fleet:submitted -> fleet:dispatched`` is queueing,
    ``fleet:dispatched -> fleet:prefill_done`` is prefill, and so on).
    A request's events form one causal chain (failover hops included),
    so the HLC-ordered walk IS the critical path; the dominant segment
    names where this request's latency actually went."""
    story = request_story(merged, trace_id)
    evs = story["events"]
    segments: List[Dict[str, Any]] = []
    for a, b in zip(evs, evs[1:]):
        ha = (a.get("hlc") or [0, 0])[0]
        hb = (b.get("hlc") or [0, 0])[0]
        segments.append({
            "from": _event_label(a), "to": _event_label(b),
            "src_proc": a.get("proc"), "dst_proc": b.get("proc"),
            "us": max(0, int(hb) - int(ha)),
        })
    total = sum(s["us"] for s in segments)
    dominant = max(segments, key=lambda s: s["us"]) if segments \
        else None
    return {
        "trace_id": trace_id,
        "n_events": len(evs),
        "total_us": total,
        "segments": segments,
        "dominant": dominant,
        "dominant_frac": (dominant["us"] / total)
        if dominant and total else 0.0,
        "outcome": story.get("outcome"),
    }


def render_critical_path(cp: Dict[str, Any]) -> str:
    if not cp.get("segments"):
        return (f"request {cp.get('trace_id')}: no critical path "
                f"(fewer than two journaled events)")
    lines = [f"request {cp['trace_id']}: critical path "
             f"{cp['total_us']}us over {cp['n_events']} events"]
    for s in cp["segments"]:
        mark = " <-- dominant" if s is cp.get("dominant") else ""
        hop = "" if s["src_proc"] == s["dst_proc"] \
            else f" [{s['src_proc']} -> {s['dst_proc']}]"
        lines.append(f"  {s['from']} -> {s['to']}: {s['us']}us"
                     f"{hop}{mark}")
    d = cp.get("dominant")
    if d is not None:
        lines.append(f"  dominant: {d['from']} -> {d['to']} "
                     f"({d['us']}us, {cp['dominant_frac']:.0%} of "
                     f"the path)")
    return "\n".join(lines)


def render_request_story(story: Dict[str, Any]) -> str:
    """Human rendering of :func:`request_story`: one HLC-ordered line
    per event, cross-process edges called out, verdict at the end."""
    tid = story["trace_id"]
    evs = story["events"]
    if not evs:
        return f"request {tid}: no journaled events"
    by_idx = {e.get("idx"): e for e in evs}
    # annotate each receive with where its cause came from
    cause: Dict[int, Dict[str, Any]] = {}
    for ed in story.get("edges", []):
        cause[ed["dst"]] = ed
    lines = [
        f"request {tid}: {len(evs)} events across "
        f"{len(story['procs'])} process(es) {story['procs']}"
        + (f", {story['failovers']} failover hop(s)"
           if story["failovers"] else "")
        + (f", {story['remote_pull_events']} remote-pull event(s)"
           if story["remote_pull_events"] else "")]
    for e in evs:
        line = f"  {format_event(e)}"
        ed = cause.get(e.get("idx"))
        if ed is not None:
            src = by_idx.get(ed["src"])
            if src is not None:
                hlc = src.get("hlc") or [0, 0]
                line += (f"   <- happens-after {src.get('kind')}"
                         f"@{src.get('proc')} hlc=({hlc[0]},{hlc[1]})")
        lines.append(line)
    out = story.get("outcome")
    if out is None:
        lines.append("  outcome: NONE journaled (in flight, or the "
                     "journal window ended first)")
    elif out["kind"] == "done":
        lines.append(f"  outcome: done on {out.get('worker')} "
                     f"(reason {out.get('reason')})"
                     + (f" after {story['failovers']} failover(s)"
                        if story["failovers"] else ""))
    else:
        lines.append("  outcome: shed"
                     + (f" after {story['failovers']} failover(s)"
                        if story["failovers"] else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Perfetto export: one lane per process via merge_trace_shards
# ---------------------------------------------------------------------------

def export_perfetto(merged: Dict[str, Any], out_path: str
                    ) -> Dict[str, Any]:
    """Render a merged journal as a Perfetto/Chrome document with one
    process lane per journaled process, through the SAME
    :func:`~.aggregate.merge_trace_shards` machinery the trainer's
    trace shards use (pid = lane, metadata names the proc).  Timestamps
    are the HLC physical component (µs), so cross-process causality
    reads left-to-right on one shared timeline.

    Schedule-execution records (``kind="schedule_exec"``, ISSUE 20)
    get their own THREAD lane per process (tid 1) as complete events
    with their measured wall as the duration — an executed collective
    schedule is visible in the same doc as the request flow that
    triggered it."""
    from .aggregate import merge_trace_shards, shard_path

    procs = list(merged.get("procs") or [])
    base = os.path.splitext(out_path)[0] + ".shard.json"
    paths = []
    for rank, proc in enumerate(procs):
        evs = [e for e in merged["events"] if e.get("proc") == proc]
        trace_events: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": rank, "tid": 0,
             "args": {"name": f"journal:{proc}"}}]
        if any(e.get("kind") == "schedule_exec" for e in evs):
            trace_events.append(
                {"ph": "M", "name": "thread_name", "pid": rank,
                 "tid": 1, "args": {"name": "schedule_exec"}})
        for e in evs:
            hlc = e.get("hlc") or [0, 0]
            args = {k: v for k, v in e.items()
                    if k not in ("schema", "proc", "hlc", "t", "idx")}
            if e.get("kind") == "schedule_exec":
                trace_events.append(
                    {"ph": "X", "pid": rank, "tid": 1,
                     "name": f"{e.get('op')}({e.get('arg')})",
                     "ts": int(hlc[0]) + int(hlc[1]),
                     "dur": max(1, int(float(e.get("wall_us", 1)))),
                     "cat": "schedule_exec", "args": args})
                continue
            trace_events.append(
                {"ph": "i", "name": str(e.get("kind")), "pid": rank,
                 "tid": 0, "s": "t", "ts": int(hlc[0]) + int(hlc[1]),
                 "cat": "journal", "args": args})
        p = shard_path(base, rank)
        with open(p, "w") as f:
            json.dump({"traceEvents": trace_events,
                       "metadata": {"rank": rank, "proc": proc}}, f)
        paths.append(p)
    return merge_trace_shards(paths, out_path=out_path)
