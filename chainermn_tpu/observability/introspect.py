"""Live introspection: a stdlib HTTP thread serving /statusz et al.

The third leg of the production triad: the flight recorder explains a
*death*, the metrics stream explains a *trend*, and this module answers
"what is it doing RIGHT NOW" while the process is alive — without a
debugger, without restarting, from ``curl``:

* ``/statusz``  — JSON: uptime, pid/rank, current phase (the innermost
  open span), tracing state, goodput split, every registered flight
  provider's snapshot (serving queue/slot state, trainer position, SLO
  status).
* ``/metricsz`` — Prometheus text exposition (``export.prometheus_text``
  + any extra-gauge callback), scrape-ready.
* ``/requestz`` — JSON: live + recently finished serving requests with
  their trace ids and phase timestamps (the per-request tracing view).
* ``/debugz``   — GET shows the last bundle; ``/debugz?dump=1`` dumps a
  fresh debug bundle (``flight.dump_bundle``) and returns its path —
  the live postmortem trigger.
* ``/healthz``  — 200 "ok" (load-balancer liveness).

Wired behind ``--statusz-port`` in ``chainermn_tpu.train``,
``chainermn_tpu.serve``, and ``bench.py``; binds 127.0.0.1 by default
(introspection is an operator tool, not a public API).  Port 0 picks a
free port (tests); the chosen port is on ``StatusServer.port``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from . import flight as _flight
from . import trace


class StatusServer:
    """Background HTTP introspection endpoint (daemon thread).

    ``extra_gauges``: callable returning a flat dict merged into
    ``/metricsz`` (the serving engine passes its ``metrics()``).
    ``requests_fn``: callable returning the ``/requestz`` payload (the
    serving frontend registers its live+recent request table).
    ``dump_dir``: where ``/debugz?dump=1`` writes bundles (defaults to
    the flight module's crash dump dir at request time).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 extra_gauges: Optional[Callable[[], Dict[str, float]]] = None,
                 requests_fn: Optional[Callable[[], Any]] = None,
                 dump_dir: Optional[str] = None,
                 rank: Optional[int] = None):
        self.extra_gauges = extra_gauges
        self.requests_fn = requests_fn
        self.dump_dir = dump_dir
        self.rank = rank
        self._t0 = time.time()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._requested_port = int(port)

    # ---- payload builders (also unit-testable without a socket) ----
    def statusz(self) -> Dict[str, Any]:
        tr = trace.get_tracer()
        rec = _flight.get_flight_recorder()
        last_phase = rec.last("phase")
        payload: Dict[str, Any] = {
            "schema": "chainermn_tpu.statusz.v1",
            "t": round(time.time(), 3),
            "uptime_s": round(time.time() - self._t0, 3),
            "pid": os.getpid(),
            "rank": self.rank,
            "tracing_enabled": tr.enabled,
            "current_span": tr.current_span(),
            "last_phase": (last_phase or {}).get("name"),
            "flight_ring": {"events": len(rec.events()),
                            "capacity": rec.capacity,
                            "total_seen": rec.total_seen},
            "providers": _flight.provider_snapshots(),
        }
        return payload

    def metricsz(self) -> str:
        from .export import prometheus_text
        extra = None
        if self.extra_gauges is not None:
            try:
                extra = self.extra_gauges()
            except Exception:
                extra = None
        # flight-ring loss accounting rides every exposition: a dropped
        # event is missing evidence, and /metricsz is where a scrape
        # learns the ring overflowed (ISSUE 17 satellite)
        dropped = _flight.get_flight_recorder().dropped_counts()
        if dropped:
            extra = dict(extra or {})
            for kind, n in sorted(dropped.items()):
                extra[f"flight/dropped/{kind}"] = float(n)
        # schedule-execution truth counters (ISSUE 20): per-link
        # ops/bytes/wall measured by the reshard profiler
        from .comm import schedule_exec_gauges
        sched = schedule_exec_gauges()
        if sched:
            extra = dict(extra or {})
            extra.update(sched)
        return prometheus_text(extra)

    def requestz(self) -> Any:
        if self.requests_fn is None:
            return {"requests": [], "note": "no request source registered"}
        return self.requests_fn()

    def debugz(self, dump: bool = False) -> Dict[str, Any]:
        out: Dict[str, Any] = {"last_bundle": _flight.last_bundle()}
        if dump:
            d = self.dump_dir or _flight.crash_dump_dir()
            if d is None:
                out["error"] = ("no dump dir configured (pass dump_dir "
                                "or flight.set_crash_dump_dir)")
            else:
                bundle = _flight.dump_bundle(d, "debugz", rank=self.rank)
                if bundle is None:
                    out["error"] = "bundle dump failed (see stderr)"
                else:
                    out["bundle"] = bundle
                    out["last_bundle"] = bundle
        return out

    # ---- lifecycle ----
    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "StatusServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code: int = 200) -> None:
                body = json.dumps(obj, indent=2, default=str,
                                  sort_keys=True).encode()
                self._send(code, body, "application/json")

            def do_GET(self) -> None:  # noqa: N802 (stdlib contract)
                url = urlparse(self.path)
                try:
                    if url.path in ("/statusz", "/", "/statusz/"):
                        self._json(server.statusz())
                    elif url.path == "/metricsz":
                        self._send(200, server.metricsz().encode(),
                                   "text/plain; version=0.0.4")
                    elif url.path == "/requestz":
                        self._json(server.requestz())
                    elif url.path == "/debugz":
                        q = parse_qs(url.query)
                        dump = q.get("dump", ["0"])[0] in ("1", "true")
                        self._json(server.debugz(dump=dump))
                    elif url.path == "/healthz":
                        self._send(200, b"ok\n", "text/plain")
                    else:
                        self._json({"error": "not found", "endpoints": [
                            "/statusz", "/metricsz", "/requestz",
                            "/debugz", "/healthz"]}, code=404)
                except Exception as e:  # a broken provider ≠ a dead server
                    self._json({"error": repr(e)}, code=500)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="chainermn-tpu-statusz",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def start_status_server(port: int, **kwargs) -> StatusServer:
    """One-call CLI face: build + start, log the bound port."""
    import sys
    srv = StatusServer(port, **kwargs).start()
    print(f"[chainermn_tpu statusz] serving on "
          f"http://127.0.0.1:{srv.port}/statusz", file=sys.stderr,
          flush=True)
    return srv
