"""Machine-readable metrics export: Prometheus textfile + JSONL stream.

The ROADMAP north star is a production service, and production gates on
what machines can scrape — not on a Perfetto file a human eyeballs.  Two
export faces, one source of truth (the tracer + comm accountant + the
trainer's observation path):

* **Prometheus textfile** (:func:`write_prometheus_textfile`) — the
  node-exporter textfile-collector contract: counters as ``_total``,
  gauges as-is, all under the ``chainermn_tpu_`` namespace, written
  atomically so a scrape never sees a torn file.
* **JSONL metrics stream** (:class:`MetricsWriter` /
  :class:`MetricsReport`) — one JSON object per line, append-only, each
  record stamped with the versioned schema id (``SCHEMA``), a kind, a
  wall-clock timestamp, and (under multi-controller) the writing rank.
  Append-only + per-line flush means a killed run keeps every record up
  to the kill, and ``scripts/check_perf_regression.py`` can diff two
  streams without any end-of-run finalization having happened.

:func:`health_snapshot` assembles the "what was this process doing"
dict — counters, gauges, span summary, comm ledger, last step report,
anomaly findings — that the Watchdog dumps before aborting a stalled
gang and that the train CLI writes at clean exit.

Schema evolution rule: bump :data:`SCHEMA` whenever a consumer-visible
field changes meaning; readers (``read_metrics_jsonl``) reject streams
whose major schema id they do not know, loudly, instead of mis-parsing.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, IO, List, Optional

from . import trace
from .comm import get_accountant

#: Versioned schema id stamped on every JSONL record and snapshot.
SCHEMA = "chainermn_tpu.metrics.v1"

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _atomic_write_text(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _prom_name(name: str) -> str:
    return "chainermn_tpu_" + _PROM_BAD.sub("_", name).strip("_")


def _esc_label(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline — the
    full exposition-format rule set, applied to EVERY label value."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _esc_help(v: str) -> str:
    """HELP-text escaping: backslash and newline (quotes are legal)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(extra_gauges: Optional[Dict[str, float]] = None) -> str:
    """Render the tracer's counters/gauges + the comm ledger in the
    Prometheus text exposition format (version 0.0.4).

    Per-family contract (the node-exporter parser's, verified by the
    round-trip test): ONE ``# HELP`` and ONE ``# TYPE`` line per metric
    family, immediately followed by all of that family's samples; label
    values escaped per the exposition spec (backslash, quote, newline).
    """
    tr = trace.get_tracer()
    # family name -> (kind, help, [(labels-or-None, value), ...]);
    # insertion-ordered so related families stay adjacent
    families: Dict[str, list] = {}

    def add(name: str, kind: str, help_text: str, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        fam = families.setdefault(name, [kind, help_text, []])
        fam[2].append((labels, float(value)))

    for name, total in sorted(tr.counters().items()):
        add(_prom_name(name) + "_total", "counter",
            f"cumulative total of tracer counter '{name}'", total)
    # extra gauges OVERRIDE tracer gauges of the same name (the serving
    # engine publishes e.g. serving/queue_depth both ways; duplicate
    # unlabeled samples of one series are invalid exposition text)
    gauges = dict(tr.gauges())
    gauges.update(extra_gauges or {})
    for name, value in sorted(gauges.items()):
        add(_prom_name(name), "gauge",
            f"instantaneous value of gauge '{name}'", value)
    spans = tr.summary()["spans"]
    for family, field, scale, help_text in (
            ("chainermn_tpu_span_seconds_total", "total_ms", 1e-3,
             "cumulative wall seconds inside each tracer span"),
            ("chainermn_tpu_span_count_total", "count", 1.0,
             "number of closes of each tracer span")):
        for name, row in sorted(spans.items()):
            add(family, "counter", help_text, float(row[field]) * scale,
                {"name": name})
    rep = get_accountant().report()
    for family, field, help_text in (
            ("chainermn_tpu_comm_bytes_total", "bytes",
             "payload bytes moved per collective op and axis"),
            ("chainermn_tpu_comm_calls_total", "calls",
             "collective call count per op and axis"),
            ("chainermn_tpu_comm_host_seconds_total", "host_time_s",
             "host-observed seconds per collective op and axis")):
        for key, row in sorted(rep["per_op"].items()):
            op, _, axis = key.partition("@")
            add(family, "counter", help_text,
                float(row.get(field, 0.0)), {"axis": axis, "op": op})

    lines: List[str] = []
    for name, (kind, help_text, samples) in families.items():
        lines.append(f"# HELP {name} {_esc_help(help_text)}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in samples:
            lab = ""
            if labels:
                inner = ",".join(f'{k}="{_esc_label(v)}"'
                                 for k, v in sorted(labels.items()))
                lab = "{" + inner + "}"
            lines.append(f"{name}{lab} {value}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)(?:\s+\d+)?$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"\s*(,|$)')


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """Strict parser for the exposition subset this repo emits.

    Validates the per-family contract — every sample's family has a
    ``# TYPE`` (and ``# HELP``) line ABOVE it, label syntax is legal,
    values parse as floats — raising ``ValueError`` with the offending
    line otherwise.  Returns ``{"families": {name: {"type", "help"}},
    "samples": [(name, labels, value), ...]}`` with label values
    UN-escaped (the round-trip test's oracle).
    """
    families: Dict[str, Dict[str, str]] = {}
    samples: List[tuple] = []
    seen_series: set = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                raise ValueError(f"line {i}: malformed HELP: {line!r}")
            name = parts[2]
            families.setdefault(name, {})["help"] = (
                parts[3] if len(parts) > 3 else "")
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {i}: malformed TYPE: {line!r}")
            families.setdefault(parts[2], {})["type"] = parts[3]
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: unparseable sample: {line!r}")
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in families:
                base = name[: -len(suffix)]
        if base not in families or "type" not in families[base]:
            raise ValueError(
                f"line {i}: sample {name!r} has no preceding # TYPE line")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_RE.match(raw, pos)
                if not lm:
                    raise ValueError(
                        f"line {i}: malformed labels {raw!r}")
                labels[lm.group("k")] = re.sub(
                    r"\\(.)",
                    lambda e: {"n": "\n"}.get(e.group(1), e.group(1)),
                    lm.group("v"))
                pos = lm.end()
        try:
            value = float(m.group("value"))
        except ValueError:
            raise ValueError(
                f"line {i}: non-numeric sample value: {line!r}")
        series = (name, tuple(sorted(labels.items())))
        if series in seen_series:
            raise ValueError(
                f"line {i}: duplicate series {name}{labels!r} — "
                "Prometheus rejects scrapes with repeated samples")
        seen_series.add(series)
        samples.append((name, labels, value))
    return {"families": families, "samples": samples}


def write_prometheus_textfile(path: str,
                              extra_gauges: Optional[Dict[str, float]]
                              = None) -> str:
    """Atomically write :func:`prometheus_text` to ``path``; returns the
    rendered text."""
    text = prometheus_text(extra_gauges)
    _atomic_write_text(path, text)
    return text


def _numeric(v) -> Optional[float]:
    """Host-side numeric or None — deliberately does NOT call float() on
    device arrays: an exporter must never force a device sync."""
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    # 0-d numpy scalars (np.float32(…)) are host-side and cheap
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "shape", None) == () \
            and type(v).__module__.startswith("numpy"):
        try:
            return float(item())
        except (TypeError, ValueError):
            return None
    return None


class MetricsWriter:
    """Append-only JSONL stream with a versioned schema stamp per record.

    One writer per process; under multi-controller each rank writes its
    own file (``shard_path``-style suffix chosen by the caller) or passes
    ``rank`` so records are attributable after a cat-merge.  Lines are
    flushed as written: a SIGKILL loses at most the current line, never
    the stream.
    """

    def __init__(self, path: str, rank: Optional[int] = None):
        self.path = str(path)
        self.rank = rank
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f: Optional[IO[str]] = open(self.path, "a")

    def write(self, record: Dict[str, Any], kind: str = "step") -> Dict[str, Any]:
        if self._f is None:
            raise ValueError(f"MetricsWriter({self.path!r}) is closed")
        rec = {"schema": SCHEMA, "kind": kind, "t": round(time.time(), 3)}
        if self.rank is not None:
            rec["rank"] = int(self.rank)
        rec.update(record)
        # the stream's stamps are authoritative: a payload carrying its
        # own schema/kind (e.g. a skew report) keeps it under payload_*
        if record.get("schema") not in (None, SCHEMA):
            rec["payload_schema"] = record["schema"]
        rec["schema"] = SCHEMA
        rec["kind"] = kind
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        return rec

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_metrics_jsonl(path: str, strict: bool = True) -> List[Dict[str, Any]]:
    """Parse a JSONL metrics stream, validating the schema stamp.

    ``strict`` raises ``ValueError`` on a record with a missing/unknown
    schema id (consumer contract: refuse to mis-parse); non-strict skips
    such records.  A trailing torn line (killed writer) is always
    tolerated.
    """
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn final line from a killed writer
            raise ValueError(f"{path}:{i + 1}: unparseable JSONL line")
        schema = rec.get("schema")
        if schema != SCHEMA:
            if strict:
                raise ValueError(
                    f"{path}:{i + 1}: unknown metrics schema {schema!r} "
                    f"(this reader speaks {SCHEMA!r})")
            continue
        records.append(rec)
    return records


def health_snapshot(trainer=None, monitor=None,
                    extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One dict answering "what was this process doing": tracer summary,
    comm ledger, last per-step comm report, trainer position, anomaly
    findings.  Everything host-side; safe to call from the Watchdog's
    abort path."""
    tr = trace.get_tracer()
    acct = get_accountant()
    snap: Dict[str, Any] = {
        "schema": SCHEMA,
        "kind": "health_snapshot",
        "t": round(time.time(), 3),
        "tracing_enabled": tr.enabled,
        "spans": tr.summary()["spans"],
        "counters": tr.counters(),
        "gauges": tr.gauges(),
        "comm": acct.report(),
        "last_step_comm": acct.last_step_report,
    }
    if trainer is not None:
        snap["iteration"] = getattr(trainer, "iteration", None)
        snap["last_phase"] = getattr(trainer, "last_phase", None)
        snap["elapsed_time"] = getattr(trainer, "elapsed_time", None)
    if monitor is not None and hasattr(monitor, "health"):
        snap["anomalies"] = monitor.health()
    if extra:
        snap.update(extra)
    return snap


class MetricsReport:
    """Trainer extension streaming per-iteration metrics to JSONL (and,
    optionally, a Prometheus textfile refreshed every ``prom_every``
    iterations).

    Records carry every *host-side numeric* observation entry (device
    scalars are skipped, not synced — add a LogReport/PrintReport if you
    want forced readbacks), the step-time phases, and the per-step comm
    report.  ``finalize`` appends a ``summary`` record with the full
    :func:`health_snapshot` and writes the final textfile, so a clean
    run's last line is always the roll-up.

    Priority 330: after StepBreakdownReport (350) and HealthMonitor (340)
    have produced their keys/findings, before the ObservationAggregator
    (300) replaces local values with rank means — the stream records what
    THIS rank saw, which is the whole point of a per-rank export.
    """

    trigger = (1, "iteration")
    priority = 330

    def __init__(self, path: str, every: int = 1,
                 prometheus_path: Optional[str] = None,
                 prom_every: int = 10, monitor=None,
                 rank: Optional[int] = None):
        self.writer = MetricsWriter(path, rank=rank)
        self.every = max(int(every), 1)
        self.prometheus_path = prometheus_path
        self.prom_every = max(int(prom_every), 1)
        self.monitor = monitor
        self._trainer = None

    def observe(self, trainer) -> None:
        self._trainer = trainer
        it = trainer.iteration
        if it % self.every:
            return
        rec: Dict[str, Any] = {"iteration": it}
        for key, val in trainer.observation.items():
            num = _numeric(val)
            if num is not None:
                rec[key] = num
        phases = getattr(trainer.updater, "phase_times", None)
        if phases:
            for phase, dt in phases.items():
                rec.setdefault(f"time/{phase}", float(dt))
        step_rep = get_accountant().last_step_report
        if step_rep is not None:
            rec.setdefault("comm/bytes", step_rep["bytes"])
            rec.setdefault("comm/calls", step_rep["calls"])
        self.writer.write(rec, kind="step")
        if self.prometheus_path and it % self.prom_every == 0:
            write_prometheus_textfile(self.prometheus_path)

    def __call__(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        try:
            self.writer.write(
                health_snapshot(self._trainer, self.monitor),
                kind="summary")
            if self.prometheus_path:
                write_prometheus_textfile(self.prometheus_path)
        finally:
            self.writer.close()

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass
