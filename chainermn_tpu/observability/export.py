"""Machine-readable metrics export: Prometheus textfile + JSONL stream.

The ROADMAP north star is a production service, and production gates on
what machines can scrape — not on a Perfetto file a human eyeballs.  Two
export faces, one source of truth (the tracer + comm accountant + the
trainer's observation path):

* **Prometheus textfile** (:func:`write_prometheus_textfile`) — the
  node-exporter textfile-collector contract: counters as ``_total``,
  gauges as-is, all under the ``chainermn_tpu_`` namespace, written
  atomically so a scrape never sees a torn file.
* **JSONL metrics stream** (:class:`MetricsWriter` /
  :class:`MetricsReport`) — one JSON object per line, append-only, each
  record stamped with the versioned schema id (``SCHEMA``), a kind, a
  wall-clock timestamp, and (under multi-controller) the writing rank.
  Append-only + per-line flush means a killed run keeps every record up
  to the kill, and ``scripts/check_perf_regression.py`` can diff two
  streams without any end-of-run finalization having happened.

:func:`health_snapshot` assembles the "what was this process doing"
dict — counters, gauges, span summary, comm ledger, last step report,
anomaly findings — that the Watchdog dumps before aborting a stalled
gang and that the train CLI writes at clean exit.

Schema evolution rule: bump :data:`SCHEMA` whenever a consumer-visible
field changes meaning; readers (``read_metrics_jsonl``) reject streams
whose major schema id they do not know, loudly, instead of mis-parsing.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, IO, List, Optional

from . import trace
from .comm import get_accountant

#: Versioned schema id stamped on every JSONL record and snapshot.
SCHEMA = "chainermn_tpu.metrics.v1"

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _atomic_write_text(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _prom_name(name: str) -> str:
    return "chainermn_tpu_" + _PROM_BAD.sub("_", name).strip("_")


def prometheus_text(extra_gauges: Optional[Dict[str, float]] = None) -> str:
    """Render the tracer's counters/gauges + the comm ledger in the
    Prometheus text exposition format (version 0.0.4)."""
    tr = trace.get_tracer()
    lines: List[str] = []

    def esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"')

    def emit(name: str, kind: str, value: float,
             labels: Optional[Dict[str, str]] = None) -> None:
        lines.append(f"# TYPE {name} {kind}")
        lab = ""
        if labels:
            inner = ",".join(f'{k}="{esc(v)}"'
                             for k, v in sorted(labels.items()))
            lab = "{" + inner + "}"
        lines.append(f"{name}{lab} {float(value)}")

    for name, total in sorted(tr.counters().items()):
        emit(_prom_name(name) + "_total", "counter", total)
    for name, value in sorted(tr.gauges().items()):
        emit(_prom_name(name), "gauge", value)
    for name, value in sorted((extra_gauges or {}).items()):
        emit(_prom_name(name), "gauge", value)
    spans = tr.summary()["spans"]
    if spans:
        for family, field, scale in (
                ("chainermn_tpu_span_seconds_total", "total_ms", 1e-3),
                ("chainermn_tpu_span_count_total", "count", 1.0)):
            lines.append(f"# TYPE {family} counter")
            for name, row in sorted(spans.items()):
                lines.append(f'{family}{{name="{esc(name)}"}} '
                             f"{float(row[field]) * scale}")
    rep = get_accountant().report()
    if rep["per_op"]:
        # one TYPE line per family, then every labeled sample
        for family, field in (("chainermn_tpu_comm_bytes_total", "bytes"),
                              ("chainermn_tpu_comm_calls_total", "calls"),
                              ("chainermn_tpu_comm_host_seconds_total",
                               "host_time_s")):
            lines.append(f"# TYPE {family} counter")
            for key, row in sorted(rep["per_op"].items()):
                op, _, axis = key.partition("@")
                lab = f'{{axis="{esc(axis)}",op="{esc(op)}"}}'
                lines.append(
                    f"{family}{lab} {float(row.get(field, 0.0))}")
    return "\n".join(lines) + "\n"


def write_prometheus_textfile(path: str,
                              extra_gauges: Optional[Dict[str, float]]
                              = None) -> str:
    """Atomically write :func:`prometheus_text` to ``path``; returns the
    rendered text."""
    text = prometheus_text(extra_gauges)
    _atomic_write_text(path, text)
    return text


def _numeric(v) -> Optional[float]:
    """Host-side numeric or None — deliberately does NOT call float() on
    device arrays: an exporter must never force a device sync."""
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    # 0-d numpy scalars (np.float32(…)) are host-side and cheap
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "shape", None) == () \
            and type(v).__module__.startswith("numpy"):
        try:
            return float(item())
        except (TypeError, ValueError):
            return None
    return None


class MetricsWriter:
    """Append-only JSONL stream with a versioned schema stamp per record.

    One writer per process; under multi-controller each rank writes its
    own file (``shard_path``-style suffix chosen by the caller) or passes
    ``rank`` so records are attributable after a cat-merge.  Lines are
    flushed as written: a SIGKILL loses at most the current line, never
    the stream.
    """

    def __init__(self, path: str, rank: Optional[int] = None):
        self.path = str(path)
        self.rank = rank
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f: Optional[IO[str]] = open(self.path, "a")

    def write(self, record: Dict[str, Any], kind: str = "step") -> Dict[str, Any]:
        if self._f is None:
            raise ValueError(f"MetricsWriter({self.path!r}) is closed")
        rec = {"schema": SCHEMA, "kind": kind, "t": round(time.time(), 3)}
        if self.rank is not None:
            rec["rank"] = int(self.rank)
        rec.update(record)
        # the stream's stamps are authoritative: a payload carrying its
        # own schema/kind (e.g. a skew report) keeps it under payload_*
        if record.get("schema") not in (None, SCHEMA):
            rec["payload_schema"] = record["schema"]
        rec["schema"] = SCHEMA
        rec["kind"] = kind
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        return rec

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_metrics_jsonl(path: str, strict: bool = True) -> List[Dict[str, Any]]:
    """Parse a JSONL metrics stream, validating the schema stamp.

    ``strict`` raises ``ValueError`` on a record with a missing/unknown
    schema id (consumer contract: refuse to mis-parse); non-strict skips
    such records.  A trailing torn line (killed writer) is always
    tolerated.
    """
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn final line from a killed writer
            raise ValueError(f"{path}:{i + 1}: unparseable JSONL line")
        schema = rec.get("schema")
        if schema != SCHEMA:
            if strict:
                raise ValueError(
                    f"{path}:{i + 1}: unknown metrics schema {schema!r} "
                    f"(this reader speaks {SCHEMA!r})")
            continue
        records.append(rec)
    return records


def health_snapshot(trainer=None, monitor=None,
                    extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One dict answering "what was this process doing": tracer summary,
    comm ledger, last per-step comm report, trainer position, anomaly
    findings.  Everything host-side; safe to call from the Watchdog's
    abort path."""
    tr = trace.get_tracer()
    acct = get_accountant()
    snap: Dict[str, Any] = {
        "schema": SCHEMA,
        "kind": "health_snapshot",
        "t": round(time.time(), 3),
        "tracing_enabled": tr.enabled,
        "spans": tr.summary()["spans"],
        "counters": tr.counters(),
        "gauges": tr.gauges(),
        "comm": acct.report(),
        "last_step_comm": acct.last_step_report,
    }
    if trainer is not None:
        snap["iteration"] = getattr(trainer, "iteration", None)
        snap["last_phase"] = getattr(trainer, "last_phase", None)
        snap["elapsed_time"] = getattr(trainer, "elapsed_time", None)
    if monitor is not None and hasattr(monitor, "health"):
        snap["anomalies"] = monitor.health()
    if extra:
        snap.update(extra)
    return snap


class MetricsReport:
    """Trainer extension streaming per-iteration metrics to JSONL (and,
    optionally, a Prometheus textfile refreshed every ``prom_every``
    iterations).

    Records carry every *host-side numeric* observation entry (device
    scalars are skipped, not synced — add a LogReport/PrintReport if you
    want forced readbacks), the step-time phases, and the per-step comm
    report.  ``finalize`` appends a ``summary`` record with the full
    :func:`health_snapshot` and writes the final textfile, so a clean
    run's last line is always the roll-up.

    Priority 330: after StepBreakdownReport (350) and HealthMonitor (340)
    have produced their keys/findings, before the ObservationAggregator
    (300) replaces local values with rank means — the stream records what
    THIS rank saw, which is the whole point of a per-rank export.
    """

    trigger = (1, "iteration")
    priority = 330

    def __init__(self, path: str, every: int = 1,
                 prometheus_path: Optional[str] = None,
                 prom_every: int = 10, monitor=None,
                 rank: Optional[int] = None):
        self.writer = MetricsWriter(path, rank=rank)
        self.every = max(int(every), 1)
        self.prometheus_path = prometheus_path
        self.prom_every = max(int(prom_every), 1)
        self.monitor = monitor
        self._trainer = None

    def observe(self, trainer) -> None:
        self._trainer = trainer
        it = trainer.iteration
        if it % self.every:
            return
        rec: Dict[str, Any] = {"iteration": it}
        for key, val in trainer.observation.items():
            num = _numeric(val)
            if num is not None:
                rec[key] = num
        phases = getattr(trainer.updater, "phase_times", None)
        if phases:
            for phase, dt in phases.items():
                rec.setdefault(f"time/{phase}", float(dt))
        step_rep = get_accountant().last_step_report
        if step_rep is not None:
            rec.setdefault("comm/bytes", step_rep["bytes"])
            rec.setdefault("comm/calls", step_rep["calls"])
        self.writer.write(rec, kind="step")
        if self.prometheus_path and it % self.prom_every == 0:
            write_prometheus_textfile(self.prometheus_path)

    def __call__(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        try:
            self.writer.write(
                health_snapshot(self._trainer, self.monitor),
                kind="summary")
            if self.prometheus_path:
                write_prometheus_textfile(self.prometheus_path)
        finally:
            self.writer.close()

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass
