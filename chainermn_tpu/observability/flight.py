"""Black-box flight recorder: bounded event ring + crash debug bundles.

Everything the PR 1/2 layers record *evaporates with the process*: the
tracer buffer, the comm ledger, the serving queue state all live in
memory, so a Watchdog abort, an uncaught exception, or a SIGTERM from
the scheduler leaves nothing to explain the death.  This module is the
black box that survives it (the production triad's first leg —
docs/OBSERVABILITY.md "Flight recorder & postmortems"):

* **Ring buffer** (:class:`FlightRecorder`) — a bounded, lock-cheap
  deque of recent structured events.  Every existing emitter tees in:
  span closes and instants via a tracer sink
  (:func:`install_tracer_tee`), per-collective accounting deltas
  (``observability.comm``), anomaly trips (``HealthMonitor``), serving
  admissions/evictions (``serving.frontend``), and phase stamps.  At
  capacity the oldest events fall off — the ring always holds the LAST
  moments, which is the only part a postmortem needs.

* **Debug bundle** (:func:`dump_bundle`) — an atomic, versioned
  directory snapshot: ring contents, :func:`~.export.health_snapshot`,
  the trace tail, every registered state provider (serving queue/slot
  state, goodput ledger, SLO state, jit-cache counts), and env + mesh
  topology.  Written to a temp dir then ``os.rename``\\ d into place, so
  a bundle either exists completely or not at all.  Renderable by
  ``scripts/explain_bundle.py`` into a human postmortem.

* **Triggers** — the Watchdog abort path, the global except hook, and
  :func:`install_signal_handlers` (SIGTERM = dump then die with the
  default disposition; SIGUSR1 = dump and keep running — the live
  "what is it doing" probe for a process with no statusz port).

Stdlib only; safe to import and dump before/without a JAX backend.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import trace

#: Schema stamp carried by every bundle MANIFEST and ring record.
BUNDLE_SCHEMA = "chainermn_tpu.debug_bundle.v1"

#: Files a COMPLETE bundle always contains (explain_bundle checks this).
BUNDLE_REQUIRED_FILES = (
    "MANIFEST.json", "flight.jsonl", "health.json", "env.json")


class FlightRecorder:
    """Bounded ring of recent structured events (thread-safe, cheap).

    One event = one dict with a monotonically increasing ``seq``, a
    wall-clock stamp, a ``kind``, and free-form fields.  ``capacity``
    bounds memory hard; total-seen minus retained = dropped-from-head,
    reported in the bundle manifest so a reader knows how far back the
    record goes.
    """

    DEFAULT_CAPACITY = 4096

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped: Dict[str, int] = {}
        self.enabled = True

    def record(self, kind: str, **fields) -> None:
        """Append one event; never raises, never blocks beyond the one
        ring lock (the hot-path contract: emitters call this inline)."""
        if not self.enabled:
            return
        ev = {"kind": str(kind), "t": round(time.time(), 6)}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self.capacity:
                # the deque is about to evict its head silently: count
                # the loss PER EMITTER KIND so a postmortem knows whose
                # evidence fell off (ISSUE 17 satellite)
                evicted = self._ring[0].get("kind", "?")
                self._dropped[evicted] = self._dropped.get(evicted, 0) + 1
            self._ring.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    @property
    def total_seen(self) -> int:
        with self._lock:
            return self._seq

    def dropped_counts(self) -> Dict[str, int]:
        """Events dropped from the ring head, per kind — the
        ``flight/dropped/*`` gauges and the bundle MANIFEST's loss
        accounting."""
        with self._lock:
            return dict(self._dropped)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = {}

    def last(self, kind: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Most recent event (optionally of one ``kind``), or None."""
        with self._lock:
            ring = list(self._ring)
        for ev in reversed(ring):
            if kind is None or ev.get("kind") == kind:
                return ev
        return None


_GLOBAL = FlightRecorder()

#: Named state providers: ``name -> fn() -> JSON-able`` snapshots pulled
#: into every bundle AND served live by ``introspect.StatusServer``.
#: Subsystems register at construction (the serving engine registers its
#: queue/slot/request state; the train CLI registers the trainer).
_PROVIDERS: Dict[str, Callable[[], Any]] = {}

#: Where crash-triggered dumps land (except hook / signal handlers).
_CRASH_DUMP_DIR: Optional[str] = None

_LAST_BUNDLE: Optional[str] = None
_tee_installed = False

#: When the causal journal is configured (observability.journal), every
#: module-level note tees into it too — the journal registers itself
#: here so this module stays ignorant of it (and note() stays one
#: attribute load + None check when journaling is off).
_JOURNAL_TEE: Optional[Callable[[str, Dict[str, Any]], None]] = None


def set_journal_tee(fn: Optional[Callable[[str, Dict[str, Any]], None]]
                    ) -> None:
    global _JOURNAL_TEE
    _JOURNAL_TEE = fn


def get_flight_recorder() -> FlightRecorder:
    return _GLOBAL


def note(kind: str, **fields) -> None:
    """Module-level convenience over the global ring."""
    _GLOBAL.record(kind, **fields)
    tee = _JOURNAL_TEE
    if tee is not None:
        tee(kind, fields)


def register_provider(name: str, fn: Callable[[], Any]) -> None:
    """Register (or replace) a named state provider.  ``fn`` must be
    host-side, cheap, and exception-safe enough to call from a crash
    path — a raising provider is recorded as an error string, never
    propagated."""
    _PROVIDERS[str(name)] = fn


def unregister_provider(name: str) -> None:
    _PROVIDERS.pop(name, None)


def provider_snapshots() -> Dict[str, Any]:
    """Every registered provider's current snapshot (errors inline)."""
    out: Dict[str, Any] = {}
    for name, fn in list(_PROVIDERS.items()):
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = {"error": repr(e)}
    return out


def set_crash_dump_dir(path: Optional[str]) -> None:
    """Where the except hook / signal handlers drop bundles (None
    disables crash dumping)."""
    global _CRASH_DUMP_DIR
    _CRASH_DUMP_DIR = path


def crash_dump_dir() -> Optional[str]:
    return _CRASH_DUMP_DIR


def last_bundle() -> Optional[str]:
    """Path of the most recent bundle this process dumped, or None."""
    return _LAST_BUNDLE


# ---------------------------------------------------------------------------
# tees from existing emitters
# ---------------------------------------------------------------------------

def _tracer_sink(ev: Dict[str, Any]) -> None:
    kind = {"X": "span", "i": "instant"}.get(ev.get("ph"))
    if kind is None:
        return  # counters/gauges are too hot and live in the snapshot
    rec = {"name": ev.get("name"), "cat": ev.get("cat")}
    if kind == "span":
        rec["dur_us"] = ev.get("dur")
    args = ev.get("args")
    if args:
        rec["args"] = args
    _GLOBAL.record(kind, **rec)


def install_tracer_tee(tracer: Optional[trace.Tracer] = None) -> None:
    """Tee every span close / instant the tracer records into the ring
    (idempotent).  Counters are deliberately excluded: the ring holds
    *moments*; totals come from the health snapshot."""
    global _tee_installed
    tr = tracer or trace.get_tracer()
    tr.add_sink(_tracer_sink)
    _tee_installed = True


def uninstall_tracer_tee(tracer: Optional[trace.Tracer] = None) -> None:
    global _tee_installed
    (tracer or trace.get_tracer()).remove_sink(_tracer_sink)
    _tee_installed = False


# ---------------------------------------------------------------------------
# the debug bundle
# ---------------------------------------------------------------------------

def _env_snapshot() -> Dict[str, Any]:
    """Environment + topology the postmortem reader always asks for
    first.  Env vars are allowlisted by prefix — a bundle may end up in
    a bug report, so secrets must never ride along."""
    prefixes = ("JAX_", "XLA_", "TPU_", "LIBTPU", "CHAINERMN_",
                "CUDA_VISIBLE", "SLURM_JOB", "HOSTNAME")
    env = {k: v for k, v in os.environ.items()
           if any(k.startswith(p) for p in prefixes)}
    snap: Dict[str, Any] = {
        "argv": list(sys.argv),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "cwd": os.getcwd(),
        "env": env,
    }
    # Topology only if a backend is ALREADY initialized — a crash dump
    # must never be the thing that boots one (jax.devices() would), nor
    # block on a wedged runtime (the Watchdog-abort case).  "imported"
    # is not "initialized": probe the backend cache directly.
    jax = sys.modules.get("jax")
    if jax is not None:
        snap["jax_version"] = getattr(jax, "__version__", None)
        try:
            from jax._src import xla_bridge as _xb  # noqa: SLF001
            initialized = bool(getattr(_xb, "_backends", None))
        except Exception:
            initialized = False
        if initialized:
            try:
                snap["process_index"] = jax.process_index()
                snap["process_count"] = jax.process_count()
                devs = jax.devices()
                snap["devices"] = {
                    "count": len(devs),
                    "kinds": sorted({d.device_kind for d in devs}),
                    "platform": devs[0].platform if devs else None,
                }
                snap["jit_cache_size"] = _jit_cache_size()
            except Exception as e:
                snap["jax_error"] = repr(e)
        else:
            snap["jax_backend"] = "uninitialized (not probed)"
    return snap


def _jit_cache_size() -> Optional[int]:
    """Live pjit-cache entry count (the recompile post-mortem signal),
    from whichever internal cache this jax version exposes; None when
    none does (the probe must never crash a dump)."""
    try:
        from jax._src import pjit as _pjit  # noqa: SLF001
    except Exception:
        return None
    for attr in ("_cpp_pjit_cache_fun_only", "_infer_params_cached"):
        cache = getattr(_pjit, attr, None)
        info = getattr(cache, "cache_info", None)
        if info is None:
            continue
        try:
            return int(info().currsize)
        except Exception:
            continue
    return None


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, default=str, sort_keys=True)


def dump_bundle(out_dir: str, reason: str, *,
                trainer=None, monitor=None,
                rank: Optional[int] = None,
                trace_tail: int = 5000,
                extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Atomically write one versioned debug bundle; returns its path,
    or None when the dump failed (callers must not advertise a
    half-written ``.tmp`` dir as evidence).

    Layout (``BUNDLE_SCHEMA``)::

        <out_dir>/bundle-<utcstamp>-<reason>[-rankN]/
            MANIFEST.json     schema, reason, stamps, file list, drops
            flight.jsonl      the ring, oldest first, one event per line
            health.json       export.health_snapshot (+ monitor findings)
            trace_tail.json   last ``trace_tail`` tracer events as a
                              loadable Chrome-trace doc (when tracing on)
            providers.json    every registered state provider's snapshot
            env.json          argv, allowlisted env, mesh topology,
                              jit-cache size

    The directory is assembled under a ``.tmp`` name and renamed into
    place, so a reader never sees a half-written bundle; a crashing dump
    leaves only the temp dir.  Never raises — the dump path runs inside
    abort handlers where a second failure must not mask the first.
    """
    global _LAST_BUNDLE
    t = time.time()
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(t))
    safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                          for c in str(reason)) or "unknown"
    name = f"bundle-{stamp}-{safe_reason}"
    if rank is not None:
        name += f"-rank{int(rank):05d}"
    final = os.path.join(out_dir, name)
    # two dumps in the same second (SIGTERM races the watchdog) must not
    # collide: suffix with the pid + a counter
    n = 0
    while os.path.exists(final):
        n += 1
        final = os.path.join(out_dir, f"{name}.{n}")
    tmp = f"{final}.tmp-{os.getpid()}"
    try:
        os.makedirs(tmp, exist_ok=True)
        files: List[str] = []

        events = _GLOBAL.events()
        with open(os.path.join(tmp, "flight.jsonl"), "w") as f:
            for ev in events:
                f.write(json.dumps(ev, sort_keys=True, default=str) + "\n")
        files.append("flight.jsonl")

        from . import export as _export
        try:
            health = _export.health_snapshot(trainer, monitor=monitor)
        except Exception as e:
            health = {"error": repr(e)}
        _write_json(os.path.join(tmp, "health.json"), health)
        files.append("health.json")

        tr = trace.get_tracer()
        if tr.enabled:
            tail = tr.events()[-int(trace_tail):]
            _write_json(os.path.join(tmp, "trace_tail.json"),
                        {"traceEvents": tail, "displayTimeUnit": "ms"})
            files.append("trace_tail.json")

        providers = provider_snapshots()
        if providers:
            _write_json(os.path.join(tmp, "providers.json"), providers)
            files.append("providers.json")

        _write_json(os.path.join(tmp, "env.json"), _env_snapshot())
        files.append("env.json")

        manifest: Dict[str, Any] = {
            "schema": BUNDLE_SCHEMA,
            "reason": str(reason),
            "t": round(t, 3),
            "utc": stamp,
            "pid": os.getpid(),
            "rank": rank,
            "files": sorted(files + ["MANIFEST.json"]),
            "ring_events": len(events),
            "ring_capacity": _GLOBAL.capacity,
            "ring_dropped_from_head": max(
                _GLOBAL.total_seen - len(events), 0),
            "ring_dropped_by_kind": _GLOBAL.dropped_counts(),
        }
        if extra:
            manifest["extra"] = extra
        _write_json(os.path.join(tmp, "MANIFEST.json"), manifest)
        os.rename(tmp, final)
        _LAST_BUNDLE = final
        print(f"[chainermn_tpu flight] debug bundle written: {final}",
              file=sys.stderr, flush=True)
        return final
    except Exception as e:
        print(f"[chainermn_tpu flight] bundle dump FAILED: {e!r} "
              f"(partial remains at {tmp})", file=sys.stderr, flush=True)
        return None


def read_bundle(path: str) -> Dict[str, Any]:
    """Load a bundle directory back into one dict (explain_bundle's and
    the tests' reader).  Missing optional files are simply absent;
    missing REQUIRED files raise ``FileNotFoundError``."""
    out: Dict[str, Any] = {"path": path}
    for fname in BUNDLE_REQUIRED_FILES:
        if not os.path.exists(os.path.join(path, fname)):
            raise FileNotFoundError(
                f"bundle {path!r} is incomplete: missing {fname}")
    with open(os.path.join(path, "MANIFEST.json")) as f:
        out["manifest"] = json.load(f)
    events = []
    with open(os.path.join(path, "flight.jsonl")) as f:
        for line in f:
            if line.strip():
                try:
                    events.append(json.loads(line))
                except ValueError:
                    pass  # torn tail line: the dump was mid-crash
    out["flight"] = events
    for opt in ("health", "env", "providers", "trace_tail"):
        p = os.path.join(path, f"{opt}.json")
        if os.path.exists(p):
            with open(p) as f:
                out[opt] = json.load(f)
    return out


def find_bundles(out_dir: str) -> List[str]:
    """All complete bundle dirs under ``out_dir``, oldest first."""
    if not os.path.isdir(out_dir):
        return []
    out = []
    for entry in sorted(os.listdir(out_dir)):
        p = os.path.join(out_dir, entry)
        # ".tmp-<pid>" anywhere marks an in-flight/abandoned dump — a
        # killed dump's leftovers must never read as a complete bundle
        if (entry.startswith("bundle-") and ".tmp-" not in entry
                and os.path.isdir(p)
                and os.path.exists(os.path.join(p, "MANIFEST.json"))):
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# triggers
# ---------------------------------------------------------------------------

_prev_handlers: Dict[int, Any] = {}


def _signal_dump(signum, frame) -> None:
    sig = signal.Signals(signum).name
    out = _CRASH_DUMP_DIR
    note("signal", signal=sig)
    if out:
        # Bounded SIDE-THREAD dump (same discipline as the except hook
        # and the Watchdog): the handler may have interrupted the main
        # thread INSIDE a ring/tracer lock, and an inline dump would
        # self-deadlock on that non-reentrant lock — a hang instead of
        # a death.  The join timeout guarantees the process still dies.
        t = threading.Thread(
            target=lambda: dump_bundle(out, f"signal_{sig.lower()}"),
            daemon=True)
        t.start()
        t.join(timeout=10.0)
        if t.is_alive():
            print(f"[chainermn_tpu flight] {sig} bundle dump still "
                  "running after 10s — proceeding to die",
                  file=sys.stderr, flush=True)
    if signum == signal.SIGTERM:
        # die with the default disposition so the parent sees a real
        # SIGTERM death, not a bundle-dumper exit code
        prev = _prev_handlers.get(signum)
        signal.signal(signum, prev if callable(prev)
                      else signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_signal_handlers(dump_dir: Optional[str] = None,
                            signals=(signal.SIGTERM,
                                     signal.SIGUSR1)) -> None:
    """SIGTERM: dump a bundle, then die with the default disposition.
    SIGUSR1: dump and keep running (the poor man's /debugz).  Main
    thread only (CPython restriction); ``dump_dir`` defaults to the
    configured crash dump dir."""
    if dump_dir is not None:
        set_crash_dump_dir(dump_dir)
    for sig in signals:
        cur = signal.getsignal(sig)
        if cur is not _signal_dump:
            # idempotent: never record OURSELVES as the previous
            # handler, or SIGTERM would re-dispatch to _signal_dump
            # forever instead of dying
            _prev_handlers[sig] = cur
        signal.signal(sig, _signal_dump)


def dump_on_crash(exc_type, exc_value) -> Optional[str]:
    """Best-effort bundle from an exception-abort path (the global
    except hook calls this before killing the gang)."""
    out = _CRASH_DUMP_DIR
    if not out:
        return None
    note("crash", exc_type=getattr(exc_type, "__name__", str(exc_type)),
         exc=repr(exc_value))
    return dump_bundle(out, "uncaught_exception")
