"""Goodput attribution + SLO burn-rate tracking (train and serve).

Comm-dominated systems live or die on time *attribution* — EQuARX and
GC3 (PAPERS.md) both start by measuring where collective wall-time
actually goes.  This module gives the repo the production vocabulary
for that:

* :class:`GoodputLedger` — partitions wall-clock into named buckets
  (``compute`` / ``comm`` / ``host`` / ``compile`` / ``queue_wait`` /
  ``stall``).  Goodput = the compute fraction; everything else is
  attributed badput.  The serving engine measures its step phases into
  one; the train CLI folds the updater's phase stamps in.  The
  acceptance contract: bucket sums match wall time within 5% on the
  serve demo — the ledger is a *partition*, not a sampling.

* :class:`SLOTracker` — target TTFT and tokens/s with multi-window
  burn-rate alerting (the SRE-workbook pattern: a violation-fraction
  budget burning faster than ``burn_threshold``× in BOTH a short and a
  long window pages; either alone is noise or too slow).  Findings are
  shaped exactly like ``anomaly.HealthMonitor`` findings and fan out
  the same three ways: tracer instant, structured stderr JSON, and a
  pluggable ``escalate`` callback — so SLO breaches ride the PR 2
  escalation path unchanged.

* :class:`ReservoirSample` — fixed-size uniform reservoir (Vitter's
  algorithm R) keeping p50/p99 semantics O(1)-memory for long-running
  serve loops (the unbounded per-request latency lists it replaces grew
  forever).

Pure stdlib + optional numpy for percentiles; no JAX anywhere.
"""

from __future__ import annotations

import json
import random
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import trace
from . import flight as _flight


def percentile_of(values: List[float], q: float) -> Optional[float]:
    """Linear-interpolated percentile (numpy's default definition) over
    an UNSORTED value list, or None when empty — the one implementation
    behind :meth:`ReservoirSample.percentile` and the router's fleet
    TTFT merge (serving/router.py)."""
    vals = sorted(values)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * (float(q) / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1 - frac) + vals[hi] * frac


class ReservoirSample:
    """Fixed-size uniform sample of an unbounded stream (algorithm R).

    Percentiles over the reservoir converge on the stream's percentiles
    (uniform inclusion probability ``k/n``), so p50/p99 stay meaningful
    after millions of requests at constant memory.  Deterministic given
    ``seed`` — same stream, same reservoir — which keeps tests exact.
    """

    def __init__(self, capacity: int = 1024, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._values: List[float] = []
        self._n = 0
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self._n += 1
        if len(self._values) < self.capacity:
            self._values.append(float(value))
            return
        j = self._rng.randrange(self._n)
        if j < self.capacity:
            self._values[j] = float(value)

    def __len__(self) -> int:
        return len(self._values)

    @property
    def total_seen(self) -> int:
        return self._n

    def values(self) -> List[float]:
        return list(self._values)

    def percentile(self, q: float) -> Optional[float]:
        """Linear-interpolated percentile over the retained sample (the
        same definition numpy uses), or None when empty."""
        return percentile_of(self._values, q)


class RateMeter:
    """Sliding-window rate over a CUMULATIVE counter.

    ``observe(total)`` stamps ``(t, total)``; :meth:`rate` is the delta
    per second between the oldest in-window sample and the newest — the
    *recent* rate a long healthy history cannot pin (the run-cumulative
    average problem the serving SLO throughput observation already
    solves ad hoc).  Used by the drain-aware ``retry_after_ms``
    derivation and the autoscaler's shed-rate / offered-load signals
    (ISSUE 11).  Pure stdlib; pass ``now`` explicitly for
    receiver-clocked deterministic tests.
    """

    def __init__(self, window_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque = deque()   # (t, total)

    def observe(self, total: float, now: Optional[float] = None) -> None:
        t = self._clock() if now is None else float(now)
        with self._lock:
            self._samples.append((t, float(total)))
            # keep ONE sample older than the window so rate() always
            # spans at least window_s once enough history exists
            cutoff = t - self.window_s
            while len(self._samples) > 2 and self._samples[1][0] < cutoff:
                self._samples.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        """Counter delta per second over the retained window (0.0 until
        two samples with distinct timestamps exist — callers treat that
        as "no throughput measured yet", the zero-throughput edge the
        retry derivation clamps)."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            (t0, v0), (t1, v1) = self._samples[0], self._samples[-1]
        if t1 <= t0:
            return 0.0
        return max(v1 - v0, 0.0) / (t1 - t0)


class GoodputLedger:
    """Wall-time partition into attribution buckets.

    ``measure(bucket)`` brackets a code region; ``add(bucket, s)`` books
    an already-measured duration (e.g. the updater's phase stamps).  The
    report reconciles attributed seconds against the wall clock since
    construction/reset — ``unattributed_s`` is the ledger's own error
    bar, and the serve-demo acceptance keeps it under 5%.
    """

    #: ``checkpoint`` (ISSUE 8): final-save overhead on the preemption
    #: path and periodic-save flush time — booked, not vanished, so the
    #: goodput table shows what fault tolerance actually costs.
    #: ``transfer`` (ISSUE 9): KV-slab transfer wall on the
    #: disaggregated serving path (prefill worker → decode worker) —
    #: its own bucket so the P:D tuning loop sees what the transfer
    #: plane costs instead of it hiding inside ``host``.
    #: ``supervise`` (ISSUE 10): the fleet router's health-plane wall —
    #: lease reads, death detection, failover bookkeeping — booked so
    #: the supervision tax on the dispatch loop is visible, not hidden
    #: in ``host``.
    BUCKETS = ("compute", "comm", "host", "compile", "queue_wait", "stall",
               "checkpoint", "transfer", "supervise")

    def __init__(self, wall_clock: Callable[[], float] = time.monotonic):
        self._clock = wall_clock
        self._buckets: Dict[str, float] = {b: 0.0 for b in self.BUCKETS}
        self._wire_s = 0.0
        self._hidden_s = 0.0
        self._t0 = self._clock()

    def reset(self) -> None:
        self._buckets = {b: 0.0 for b in self.BUCKETS}
        self._wire_s = 0.0
        self._hidden_s = 0.0
        self._t0 = self._clock()

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in self._buckets:
            raise ValueError(
                f"unknown goodput bucket {bucket!r} (have {self.BUCKETS})")
        self._buckets[bucket] += max(float(seconds), 0.0)

    @contextmanager
    def measure(self, bucket: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(bucket, self._clock() - t0)

    def add_overlap(self, wire_s: float, hidden_s: float) -> None:
        """Book comm-overlap attribution (ISSUE 20): ``wire_s`` seconds
        of measured wire time, of which ``hidden_s`` were hidden behind
        other work (off the critical path — the schedule profiler's
        ``wire_hidden_us``).  Deliberately NOT a bucket: hidden wire
        time overlaps compute that is already booked, so adding it to
        the partition would double-count the wall.  It is a first-class
        attribution axis ON TOP of the partition — the overlap fraction
        ROADMAP item 5's async-dispatch refactor is gated on."""
        wire_s = max(float(wire_s), 0.0)
        self._wire_s += wire_s
        self._hidden_s += min(max(float(hidden_s), 0.0), wire_s)

    def buckets(self) -> Dict[str, float]:
        return dict(self._buckets)

    def report(self) -> Dict[str, Any]:
        wall = max(self._clock() - self._t0, 1e-12)
        attributed = sum(self._buckets.values())
        rep: Dict[str, Any] = {
            "wall_s": round(wall, 6),
            "attributed_s": round(attributed, 6),
            "unattributed_s": round(wall - attributed, 6),
            "coverage_frac": round(min(attributed / wall, 1.0), 4),
            "goodput_frac": round(self._buckets["compute"] / wall, 4),
            "buckets_s": {k: round(v, 6)
                          for k, v in self._buckets.items()},
            "buckets_frac": {k: round(v / wall, 4)
                             for k, v in self._buckets.items()},
            "comm_wire_s": round(self._wire_s, 6),
            "comm_hidden_s": round(self._hidden_s, 6),
            "comm_exposed_s": round(self._wire_s - self._hidden_s, 6),
            "overlap_frac": round(self._hidden_s / self._wire_s, 4)
            if self._wire_s > 0 else 0.0,
        }
        return rep

    def gauges(self, prefix: str = "goodput") -> Dict[str, float]:
        """Prometheus-ready flat gauges (``extra_gauges`` shape)."""
        rep = self.report()
        out = {f"{prefix}/goodput_frac": rep["goodput_frac"],
               f"{prefix}/coverage_frac": rep["coverage_frac"],
               f"{prefix}/overlap_frac": rep["overlap_frac"]}
        for k, v in rep["buckets_s"].items():
            out[f"{prefix}/{k}_s"] = v
        return out


class SLOTracker:
    """Multi-window burn-rate tracking for TTFT and throughput targets.

    Each TTFT observation is good (≤ ``ttft_target_ms``) or a violation;
    each throughput observation is good (≥ ``tokens_per_sec_target``) or
    a violation.  With an SLO objective of ``objective`` (default 0.99 —
    1% violation budget), the burn rate over a window is::

        violations/window_total  /  (1 - objective)

    A page fires when the burn rate exceeds ``burn_threshold`` in BOTH
    the short and the long window (the multi-window rule: the short
    window proves it is happening *now*, the long one that it is not a
    blip).  Findings carry ``kind="slo_burn"`` in the HealthMonitor
    shape and fan out identically: tracer instant + structured stderr
    JSON + ``escalate`` callback + a flight-recorder event.
    """

    def __init__(self, ttft_target_ms: Optional[float] = None,
                 tokens_per_sec_target: Optional[float] = None,
                 objective: float = 0.99,
                 windows_s: Tuple[float, float] = (60.0, 600.0),
                 burn_threshold: float = 2.0,
                 min_observations: int = 10,
                 escalate: Optional[Callable[[Dict[str, Any]], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 log_stream=None):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.ttft_target_ms = ttft_target_ms
        self.tokens_per_sec_target = tokens_per_sec_target
        self.objective = float(objective)
        self.windows_s = (float(windows_s[0]), float(windows_s[1]))
        if self.windows_s[0] >= self.windows_s[1]:
            raise ValueError("windows_s must be (short, long) with "
                             f"short < long, got {windows_s}")
        self.burn_threshold = float(burn_threshold)
        self.min_observations = int(min_observations)
        self.escalate = escalate
        self._clock = clock
        self._log = log_stream
        # (t, ok) per observation, bounded by the long window at read
        # time; hard cap so a pathological rate cannot eat the host
        self._obs: Dict[str, deque] = {
            "ttft": deque(maxlen=100_000),
            "throughput": deque(maxlen=100_000)}
        self._obs_lock = threading.Lock()   # engine thread vs /statusz
        self.findings: List[Dict[str, Any]] = []
        self._fired_at: Dict[str, float] = {}

    # ---- observation ----
    def _append(self, metric: str, ok: bool) -> None:
        """Record one observation and prune everything older than the
        long window — the scan in ``_window_stats`` (and its snapshot
        copy) stays bounded by the window, not by run length."""
        now = self._clock()
        obs_q = self._obs[metric]
        with self._obs_lock:
            obs_q.append((now, ok))
            cutoff = now - self.windows_s[1]
            while obs_q and obs_q[0][0] < cutoff:
                obs_q.popleft()

    def observe_ttft(self, ttft_ms: float) -> None:
        if self.ttft_target_ms is None:
            return
        self._append("ttft", float(ttft_ms) <= self.ttft_target_ms)
        self._check("ttft", float(ttft_ms), self.ttft_target_ms)

    def observe_throughput(self, tokens_per_sec: float) -> None:
        if self.tokens_per_sec_target is None:
            return
        self._append("throughput",
                     float(tokens_per_sec) >= self.tokens_per_sec_target)
        self._check("throughput", float(tokens_per_sec),
                    self.tokens_per_sec_target)

    # ---- burn-rate math ----
    def _window_stats(self, metric: str, window_s: float
                      ) -> Tuple[int, int]:
        cutoff = self._clock() - window_s
        total = bad = 0
        # locked snapshot: the serving thread appends/prunes while a
        # /statusz scrape reads burn rates, and iterating (or copying)
        # a mutating deque raises RuntimeError
        with self._obs_lock:
            snapshot = list(self._obs[metric])
        for t, ok in reversed(snapshot):
            if t < cutoff:
                break
            total += 1
            if not ok:
                bad += 1
        return total, bad

    def burn_rate(self, metric: str, window_s: float) -> Optional[float]:
        total, bad = self._window_stats(metric, window_s)
        if total < self.min_observations:
            return None
        budget = 1.0 - self.objective
        return (bad / total) / budget

    def short_window_burn(self, metrics: Tuple[str, ...] = ("ttft",
                                                            "throughput")
                          ) -> Optional[float]:
        """Worst short-window burn across ``metrics`` (None when no
        metric has enough observations) — THE overload scalar the shed
        gate, the degradation ladder, and the autoscaler all read; one
        definition so they can never disagree on what "burning" means
        (ISSUE 11)."""
        burns = [self.burn_rate(m, self.windows_s[0]) for m in metrics]
        burns = [b for b in burns if b is not None]
        return max(burns) if burns else None

    def _check(self, metric: str, value: float, target: float) -> None:
        short, long_ = self.windows_s
        b_short = self.burn_rate(metric, short)
        b_long = self.burn_rate(metric, long_)
        if b_short is None or b_long is None:
            return
        if b_short <= self.burn_threshold or b_long <= self.burn_threshold:
            return
        # debounce: at most one page per metric per short window
        now = self._clock()
        if now - self._fired_at.get(metric, -1e18) < short:
            return
        self._fired_at[metric] = now
        finding = {
            "kind": "slo_burn", "metric": metric,
            "iteration": len(self._obs[metric]),
            "value": round(value, 4), "expected": target,
            "detail": (f"{metric} SLO burning {b_short:.1f}x budget over "
                       f"{short:.0f}s and {b_long:.1f}x over {long_:.0f}s "
                       f"(objective {self.objective}, threshold "
                       f"{self.burn_threshold}x)"),
            "burn_rate_short": round(b_short, 2),
            "burn_rate_long": round(b_long, 2),
        }
        self.findings.append(finding)
        _flight.note("slo_burn", **{k: v for k, v in finding.items()
                                    if k != "kind"})
        tr = trace.get_tracer()
        tr.instant("anomaly/slo_burn", cat="anomaly",
                   **{k: v for k, v in finding.items() if k != "kind"})
        line = dict(finding, ts=round(time.time(), 3))
        print(f"[chainermn_tpu slo] {json.dumps(line, sort_keys=True)}",
              file=self._log or sys.stderr, flush=True)
        if self.escalate is not None:
            try:
                self.escalate(finding)
            except Exception as e:
                print(f"[chainermn_tpu slo] escalation callback failed: "
                      f"{e!r}", file=self._log or sys.stderr, flush=True)

    # ---- read-out ----
    def status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "objective": self.objective,
            "windows_s": list(self.windows_s),
            "burn_threshold": self.burn_threshold,
            "targets": {"ttft_ms": self.ttft_target_ms,
                        "tokens_per_sec": self.tokens_per_sec_target},
            "pages": len(self.findings),
            "last_finding": self.findings[-1] if self.findings else None,
        }
        for metric in ("ttft", "throughput"):
            short, long_ = self.windows_s
            out[metric] = {
                "observations": len(self._obs[metric]),
                "burn_rate_short": self.burn_rate(metric, short),
                "burn_rate_long": self.burn_rate(metric, long_),
            }
        return out

    def health(self) -> Dict[str, Any]:
        """HealthMonitor-compatible contribution to health_snapshot."""
        return {"counts": {"slo_burn": len(self.findings)},
                "findings": list(self.findings[-50:]),
                "findings_dropped": 0}
