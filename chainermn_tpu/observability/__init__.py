"""Unified tracing + metrics layer.

Three pieces (docs/OBSERVABILITY.md is the user guide):

* :mod:`.trace` — nested span tracer with counters/gauges and a
  Chrome-trace / Perfetto JSON exporter; no-op when disabled.
* :mod:`.comm` — collective-communication accounting threaded through
  the in-jit collective face (``chainermn_tpu.ops.collective``) and the
  eager communicators (op, axis, payload bytes, dtype, host latency).
* :mod:`.metrics` — step-time breakdown / throughput / MFU published
  through the trainer observation path so the values are rank-aggregated
  like any other metric.

Fleet layer (ISSUE 2):

* :mod:`.aggregate` — per-rank trace shard merge (one Perfetto lane per
  rank) and the cross-rank skew report naming the straggler rank.
* :mod:`.anomaly` — rolling-window detectors (step-time spikes, loss
  NaN/divergence, comm-bytes drift, MFU drop) behind the
  :class:`HealthMonitor` trainer extension.
* :mod:`.export` — Prometheus textfile + versioned JSONL metrics stream
  (:class:`MetricsReport`) and the :func:`health_snapshot` dict the
  Watchdog dumps before aborting a stalled gang.

Production triad (ISSUE 5):

* :mod:`.flight` — black-box flight recorder: bounded ring of recent
  structured events every emitter tees into, dumped as an atomic
  versioned **debug bundle** on Watchdog abort / uncaught exception /
  SIGTERM / SIGUSR1 (``scripts/explain_bundle.py`` renders it).
* :mod:`.slo` — :class:`GoodputLedger` wall-time attribution
  (compute/comm/host/compile/queue-wait/stall), :class:`SLOTracker`
  multi-window burn-rate alerting, :class:`ReservoirSample` O(1)-memory
  percentiles.
* :mod:`.introspect` — live ``/statusz`` / ``/metricsz`` / ``/requestz``
  / ``/debugz`` HTTP endpoint (``--statusz-port`` in the train/serve
  CLIs and bench.py).

Quick start::

    import chainermn_tpu as mn
    mn.observability.enable()
    ... train ...
    mn.observability.export_chrome_trace("trace.json")   # load in Perfetto
    print(mn.observability.comm_report())                # bytes per collective
"""

from .trace import (  # noqa: F401
    Tracer,
    add_counter,
    async_event,
    complete_event,
    disable,
    enable,
    enabled,
    export_chrome_trace,
    get_tracer,
    instant,
    now_us,
    reset,
    set_gauge,
    span,
    traced,
)
from .comm import (  # noqa: F401
    CommAccountant,
    accounted_method,
    collective,
    get_accountant,
)
from .metrics import (  # noqa: F401
    StepBreakdownReport,
    hbm_bw_for,
    peak_flops_for,
)
from .aggregate import (  # noqa: F401
    cross_rank_report,
    find_shards,
    local_rank_summary,
    merge_trace_shards,
    shard_path,
)
from .anomaly import (  # noqa: F401
    CommBytesDriftDetector,
    HealthMonitor,
    LossAnomalyDetector,
    MFUDropDetector,
    StepTimeSpikeDetector,
    default_detectors,
)
from .export import (  # noqa: F401
    SCHEMA as METRICS_SCHEMA,
    MetricsReport,
    MetricsWriter,
    health_snapshot,
    parse_prometheus_text,
    prometheus_text,
    read_metrics_jsonl,
    write_prometheus_textfile,
)
from .flight import (  # noqa: F401
    BUNDLE_SCHEMA,
    FlightRecorder,
    dump_bundle,
    find_bundles,
    get_flight_recorder,
    install_signal_handlers,
    install_tracer_tee,
    read_bundle,
    register_provider,
    set_crash_dump_dir,
)
from .slo import (  # noqa: F401
    GoodputLedger,
    ReservoirSample,
    SLOTracker,
)
from .introspect import (  # noqa: F401
    StatusServer,
    start_status_server,
)


def comm_report():
    """Cumulative per-collective byte/call/latency totals."""
    return get_accountant().report()


def reset_all() -> None:
    """Clear trace events AND comm totals (tests, fresh capture)."""
    reset()
    get_accountant().reset()


__all__ = [
    "Tracer",
    "enable",
    "disable",
    "enabled",
    "reset",
    "reset_all",
    "span",
    "traced",
    "instant",
    "add_counter",
    "set_gauge",
    "get_tracer",
    "export_chrome_trace",
    "CommAccountant",
    "get_accountant",
    "collective",
    "accounted_method",
    "comm_report",
    "StepBreakdownReport",
    "peak_flops_for",
    "hbm_bw_for",
    # fleet layer (ISSUE 2)
    "shard_path",
    "find_shards",
    "merge_trace_shards",
    "local_rank_summary",
    "cross_rank_report",
    "HealthMonitor",
    "StepTimeSpikeDetector",
    "LossAnomalyDetector",
    "CommBytesDriftDetector",
    "MFUDropDetector",
    "default_detectors",
    "METRICS_SCHEMA",
    "MetricsWriter",
    "MetricsReport",
    "read_metrics_jsonl",
    "health_snapshot",
    "prometheus_text",
    "parse_prometheus_text",
    "write_prometheus_textfile",
    # flight recorder / SLO / introspection (ISSUE 5)
    "BUNDLE_SCHEMA",
    "FlightRecorder",
    "get_flight_recorder",
    "install_tracer_tee",
    "install_signal_handlers",
    "set_crash_dump_dir",
    "register_provider",
    "dump_bundle",
    "read_bundle",
    "find_bundles",
    "GoodputLedger",
    "ReservoirSample",
    "SLOTracker",
    "StatusServer",
    "start_status_server",
    "async_event",
    "complete_event",
    "now_us",
]
