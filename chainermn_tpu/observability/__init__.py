"""Unified tracing + metrics layer.

Three pieces (docs/OBSERVABILITY.md is the user guide):

* :mod:`.trace` — nested span tracer with counters/gauges and a
  Chrome-trace / Perfetto JSON exporter; no-op when disabled.
* :mod:`.comm` — collective-communication accounting threaded through
  the in-jit collective face (``chainermn_tpu.ops.collective``) and the
  eager communicators (op, axis, payload bytes, dtype, host latency).
* :mod:`.metrics` — step-time breakdown / throughput / MFU published
  through the trainer observation path so the values are rank-aggregated
  like any other metric.

Quick start::

    import chainermn_tpu as mn
    mn.observability.enable()
    ... train ...
    mn.observability.export_chrome_trace("trace.json")   # load in Perfetto
    print(mn.observability.comm_report())                # bytes per collective
"""

from .trace import (  # noqa: F401
    Tracer,
    add_counter,
    disable,
    enable,
    enabled,
    export_chrome_trace,
    get_tracer,
    instant,
    reset,
    set_gauge,
    span,
    traced,
)
from .comm import (  # noqa: F401
    CommAccountant,
    accounted_method,
    collective,
    get_accountant,
)
from .metrics import (  # noqa: F401
    StepBreakdownReport,
    hbm_bw_for,
    peak_flops_for,
)


def comm_report():
    """Cumulative per-collective byte/call/latency totals."""
    return get_accountant().report()


def reset_all() -> None:
    """Clear trace events AND comm totals (tests, fresh capture)."""
    reset()
    get_accountant().reset()


__all__ = [
    "Tracer",
    "enable",
    "disable",
    "enabled",
    "reset",
    "reset_all",
    "span",
    "traced",
    "instant",
    "add_counter",
    "set_gauge",
    "get_tracer",
    "export_chrome_trace",
    "CommAccountant",
    "get_accountant",
    "collective",
    "accounted_method",
    "comm_report",
    "StepBreakdownReport",
    "peak_flops_for",
    "hbm_bw_for",
]
