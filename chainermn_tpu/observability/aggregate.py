"""Fleet-level trace/report aggregation: merge per-rank shards, name the
straggler.

A distributed job is only as fast as its slowest rank, and the PR-1
single-process layer could not say *which* rank that is.  Two pieces close
the gap:

* **Trace shard merge** — every controller process exports its own shard
  (``Tracer.export_chrome_trace(path, rank=r)`` → ``trace.rank00002.json``)
  and :func:`merge_trace_shards` folds them into ONE Perfetto document
  with one process lane (``pid``) per rank, so cross-rank skew is visible
  as staircased ``step`` spans on a shared timeline.  Shards may arrive
  with out-of-order timestamps (each rank's clock is its own
  ``perf_counter`` epoch — lanes are comparable in shape, not in absolute
  offset) and a missing shard is tolerated with a warning: a crashed rank
  must not take the evidence of the surviving ranks with it.

* **Cross-rank skew report** — :func:`cross_rank_report` reduces each
  rank's local step-time/comm summary over the existing ``allgather_obj``
  DCN object lane (the same transport the ObservationAggregator rides)
  into per-rank step-time min/mean/max, allreduce wait-time imbalance,
  and a *named* straggler rank.  This is the EQuARX-style evidence
  (PAPERS.md: allreduce-tuning argues from exactly this skew) produced
  in-tree instead of by eyeballing a Perfetto file.

Both faces are stdlib + numpy only and never require a JAX backend.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence

from . import trace
from .comm import get_accountant

#: Schema stamp carried by merged documents and skew reports.
AGGREGATE_SCHEMA = "chainermn_tpu.trace_merge.v1"

_SHARD_RE = re.compile(r"\.rank(\d+)(\.[^.]+)?$")


def shard_path(path: str, rank: int) -> str:
    """``trace.json`` → ``trace.rank00002.json`` (stable, sortable)."""
    base, ext = os.path.splitext(path)
    return f"{base}.rank{int(rank):05d}{ext or '.json'}"


def find_shards(path: str) -> Dict[int, str]:
    """All on-disk shards for a base trace path, as ``{rank: file}``."""
    base, ext = os.path.splitext(path)
    out: Dict[int, str] = {}
    for f in _glob.glob(f"{base}.rank*{ext or '.json'}"):
        m = _SHARD_RE.search(f)
        if m:
            out[int(m.group(1))] = f
    return dict(sorted(out.items()))


def _shard_rank(doc: Dict[str, Any], fallback: int) -> int:
    meta = doc.get("metadata") or {}
    try:
        return int(meta["rank"])
    except (KeyError, TypeError, ValueError):
        return fallback


def merge_trace_shards(path_or_paths,
                       out_path: Optional[str] = None,
                       expected_ranks: Optional[int] = None
                       ) -> Dict[str, Any]:
    """Merge per-rank trace shards into one Perfetto/Chrome document.

    ``path_or_paths`` is either the BASE trace path (shards discovered via
    :func:`find_shards`) or an explicit sequence of shard files.  Every
    event is re-homed to ``pid = rank`` so Perfetto renders one process
    lane per rank; events are sorted by timestamp (shards written by
    independent processes interleave arbitrarily — out-of-order input is
    the normal case, not an error).  A shard that is missing (fewer found
    than ``expected_ranks``) or unreadable is skipped with a warning on
    stderr; the merge never fails because one rank died.

    Returns the merged document; also writes it to ``out_path``
    (atomically) when given.
    """
    if isinstance(path_or_paths, (str, os.PathLike)):
        shards = find_shards(str(path_or_paths))
        paths = list(shards.values())
        ranks = list(shards.keys())
    else:
        paths = [str(p) for p in path_or_paths]
        ranks = [None] * len(paths)

    events: List[Dict[str, Any]] = []
    merged_ranks: List[int] = []
    for i, p in enumerate(paths):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"[chainermn_tpu aggregate] WARNING: trace shard {p!r} "
                  f"unreadable ({e}) — merging without it",
                  file=sys.stderr, flush=True)
            continue
        rank = _shard_rank(doc, ranks[i] if ranks[i] is not None else i)
        merged_ranks.append(rank)
        for ev in doc.get("traceEvents", []):
            ev = dict(ev, pid=rank)
            events.append(ev)

    if expected_ranks is not None and len(merged_ranks) < expected_ranks:
        missing = sorted(set(range(expected_ranks)) - set(merged_ranks))
        print(f"[chainermn_tpu aggregate] WARNING: expected "
              f"{expected_ranks} trace shards, merged {len(merged_ranks)} "
              f"(missing ranks {missing}) — timeline is partial",
              file=sys.stderr, flush=True)

    # Metadata events carry no "ts"; keep them first (per rank) so lane
    # names resolve before any real event, then real events by timestamp.
    meta = [e for e in events if e.get("ph") == "M"]
    real = sorted((e for e in events if e.get("ph") != "M"),
                  key=lambda e: (e.get("ts", 0), e["pid"]))
    doc = {
        "traceEvents": meta + real,
        "displayTimeUnit": "ms",
        "metadata": {"schema": AGGREGATE_SCHEMA,
                     "merged_ranks": sorted(merged_ranks),
                     "expected_ranks": expected_ranks},
    }
    if out_path:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, out_path)
    return doc


def local_rank_summary(rank: Optional[int] = None) -> Dict[str, Any]:
    """This process's contribution to the cross-rank skew report, read from
    the live tracer + comm accountant: per-step host wall clock (the
    ``step`` spans the Trainer emits) and the cumulative collective
    ledger (bytes + eager host wait time)."""
    tr = trace.get_tracer()
    step_s = [ev["dur"] / 1e6 for ev in tr.events()
              if ev.get("ph") == "X" and ev.get("name") == "step"]
    rep = get_accountant().report()
    return {
        "rank": rank,
        "steps": len(step_s),
        "step_time_s": step_s,
        "comm_bytes": rep["bytes"],
        "comm_calls": rep["calls"],
        "comm_wait_s": rep["host_time_s"],
    }


def _stats(vals: Sequence[float]) -> Dict[str, float]:
    vals = list(vals)
    if not vals:
        return {"min": 0.0, "mean": 0.0, "max": 0.0}
    return {"min": min(vals), "mean": sum(vals) / len(vals),
            "max": max(vals)}


def cross_rank_report(comm, local: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
    """Collective: every rank calls this with its local summary (default:
    :func:`local_rank_summary`) and receives the fleet view.

    The reduction rides ``comm.allgather_obj`` — the DCN object lane, NOT
    a wire collective — so it is a setup/teardown-path operation, never
    hot.  The report names:

    * ``step_time`` — min/mean/max of the per-rank MEAN step times, plus
      the full per-rank list (``per_rank``), so "how skewed is the gang"
      is one line;
    * ``straggler_rank`` — the rank with the largest mean step time, and
      ``straggler_slowdown`` = its mean over the fleet-fastest mean
      (1.0 = perfectly balanced);
    * ``comm_wait`` — per-rank eager-collective host wait totals and
      ``imbalance`` = max/mean (the allreduce wait-time imbalance the
      allreduce-tuning literature argues from: a rank that waits least is
      usually the one everyone else is waiting FOR).
    """
    if local is None:
        local = local_rank_summary(rank=getattr(comm, "rank", None))
    gathered = comm.allgather_obj(local)
    # one entry per rank; fill in rank ids where the caller left None
    per_rank = []
    for i, g in enumerate(gathered):
        g = dict(g)
        if g.get("rank") is None:
            g["rank"] = i
        per_rank.append(g)
    # under a single controller every "rank" reports the same process-wide
    # summary — collapse duplicates by rank id so the stats stay honest
    seen: Dict[int, Dict[str, Any]] = {}
    for g in per_rank:
        seen.setdefault(int(g["rank"]), g)
    per_rank = [seen[r] for r in sorted(seen)]

    mean_step = {g["rank"]: (sum(g["step_time_s"]) / len(g["step_time_s"])
                             if g["step_time_s"] else 0.0)
                 for g in per_rank}
    waits = {g["rank"]: float(g.get("comm_wait_s") or 0.0) for g in per_rank}
    stats = _stats(list(mean_step.values()))
    straggler = (max(mean_step, key=lambda r: mean_step[r])
                 if mean_step else None)
    fastest = stats["min"]
    wait_stats = _stats(list(waits.values()))
    report = {
        "schema": AGGREGATE_SCHEMA,
        "ranks": sorted(mean_step),
        "step_time": dict(
            stats, per_rank={str(r): round(v, 6)
                             for r, v in sorted(mean_step.items())}),
        "straggler_rank": straggler,
        "straggler_slowdown": (
            round(mean_step[straggler] / fastest, 4)
            if straggler is not None and fastest > 0 else None),
        "comm_wait": {
            "per_rank": {str(r): round(v, 6)
                         for r, v in sorted(waits.items())},
            "imbalance": (round(wait_stats["max"] / wait_stats["mean"], 4)
                          if wait_stats["mean"] > 0 else None),
        },
        "comm_bytes": {str(g["rank"]): int(g.get("comm_bytes") or 0)
                       for g in per_rank},
    }
    return report
