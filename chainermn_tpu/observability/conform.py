"""Runtime protocol conformance: replay REAL runs through the models.

PR 15's model checker (``analysis/protocol.py``) proves the fleet's
three load-bearing protocols correct over EVERY interleaving of a small
bounded model; this module closes the other half of the loop — it maps
a REAL run's causal journal (``journal.py``) onto those models' action
alphabets and replays it, so every chaos test, every bench chaos
section, and any production run with ``--journal`` is continuously
model-checked:

* ``done_xor_shed`` — every request's fleet lifecycle (``submitted`` /
  ``redispatched`` / ``finished`` / ``shed`` plus the interleaved
  ``worker_lost``/``drained`` deaths) replays per trace id.  A second
  terminal outcome, a result from a worker that was never dispatched
  the current attempt, or a failover that contradicts ownership is a
  violation.
* ``lease_fence`` — per worker, ``beat`` events are the model's writes
  and ``lease_judged`` events are the deliveries: at each judged beat
  the model's land/refuse prediction is compared against what the real
  :class:`~..serving.health.EpochFence` actually decided, and the
  model's own invariant (a fenced writer's artifact never lands) runs
  over the replay — which is how a mutation-injected run (an un-fenced
  zombie write via :meth:`~..analysis.protocol.Model.replace`) is
  caught with the exact ``beat → lease_judged`` HLC edge named.
* ``slot_lifecycle`` — per allocator, ``slot`` events replay the
  free→reserved→busy→cached(rc)→free lifecycle; the model's
  exact-partition invariant (no leak, no alias) runs after every op.

Violations are rendered as minimal causal chains: the journal events
(HLC-stamped, :func:`~.journal.format_event` lines) that force the bad
step, plus the explicit happens-before edge where one exists ("this
shed happened-after that done", with the HLC path).  Requests that
simply have no terminal event yet (a journal captured mid-run) are
reported as ``incomplete``, never as violations.

``mutate`` maps a model name to a ``Model -> Model`` function applied
before replay — the acceptance hook proving the monitor catches what
the checker catches (tests mutate ``fence.deliver_write`` to land
everything and assert the zombie write is named).

Pure stdlib; no JAX.  ``scripts/check_conformance.py`` is the CLI face
(exit 0/1/2), and the chaos suites assert zero violations on their
recorded journals.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.protocol import (Model, make_done_xor_shed_model,
                                 make_lease_fence_model, make_slot_model)
from .journal import format_event

#: Schema of the conformance report document.
CONFORMANCE_SCHEMA = "chainermn_tpu.conformance.v1"

#: Cap on the causal-chain length attached to one violation (the chain
#: is MINIMAL context for a human, not a full dump — the merged journal
#: has the rest).
_CHAIN_CAP = 12

Mutators = Optional[Dict[str, Callable[[Model], Model]]]


class _Replay:
    """One protocol model stepped through journal-mapped actions."""

    def __init__(self, model: Model):
        self.model = model
        self.state = model.initial
        self.transitions = {t.name: t for t in model.transitions}
        #: journal events that produced applied steps (causal context)
        self.trail: List[Dict[str, Any]] = []

    def step(self, action: str, ev: Dict[str, Any]) -> Optional[str]:
        """Apply ``action``; returns a violation description when the
        action is disabled in the current model state or the invariant
        breaks after it, else None."""
        t = self.transitions.get(action)
        if t is None:
            return (f"journal demands action {action!r} which model "
                    f"{self.model.name!r} does not have")
        if not t.guard(self.state):
            return (f"{action} is DISABLED in model state "
                    f"{self.state}")
        self.state = t.apply(self.state)
        self.trail.append(ev)
        return self.model.invariant(self.state)

    def try_step(self, action: str, ev: Dict[str, Any]
                 ) -> Optional[str]:
        """Apply ``action`` if enabled, silently skip otherwise (for
        events that are legitimately idempotent/duplicated on the real
        side, e.g. a second death report of one worker).  Returns an
        invariant violation if the APPLIED step breaks it."""
        t = self.transitions.get(action)
        if t is None or not t.guard(self.state):
            return None
        self.state = t.apply(self.state)
        self.trail.append(ev)
        return self.model.invariant(self.state)

    def force(self, **fields) -> None:
        """Overwrite model-state fields with wire truth (epoch numbers
        ride the real messages; the model need not re-derive them)."""
        self.state = self.state._replace(**fields)

    def chain(self, ev: Dict[str, Any]) -> List[Dict[str, Any]]:
        evs = self.trail[-(_CHAIN_CAP - 1):] + [ev]
        seen = set()
        out = []
        for e in evs:
            key = (e.get("proc"), e.get("seq"))
            if key not in seen:
                seen.add(key)
                out.append(e)
        return out


def _violation(model: str, subject: str, action: str, reason: str,
               events: List[Dict[str, Any]],
               edge: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return {"model": model, "subject": subject, "action": action,
            "reason": reason,
            "chain": [format_event(e) for e in events],
            "events": [e.get("idx") for e in events],
            "edge": edge}


def _hb_edge(kind: str, src: Dict[str, Any],
             dst: Dict[str, Any]) -> Dict[str, Any]:
    return {"kind": kind, "src": src.get("idx"), "dst": dst.get("idx"),
            "src_hlc": src.get("hlc"), "dst_hlc": dst.get("hlc")}


# ==========================================================================
# done_xor_shed: per-request fleet lifecycle
# ==========================================================================

def _mutated(factory, mutator, **kw) -> Model:
    m = factory(**kw)
    return mutator(m) if mutator is not None else m


def replay_done_xor_shed(merged: Dict[str, Any],
                         mutator=None) -> Tuple[List[Dict[str, Any]],
                                                int, List[str]]:
    """Replay every request's fleet lifecycle; returns
    ``(violations, n_traces_checked, incomplete_trace_ids)``."""
    fleet = [e for e in merged["events"] if e.get("kind") == "fleet"]

    # incarnation bookkeeping: a readmitted worker NAME is a NEW model
    # worker (the old incarnation's epoch is fenced forever) — the
    # incarnation index is the count of prior readmissions of the name
    inc: Dict[str, int] = {}
    per_trace: Dict[str, List[Tuple[int, Dict[str, Any], Any]]] = {}
    deaths: List[Tuple[int, Tuple[str, int], Dict[str, Any]]] = []
    for pos, ev in enumerate(fleet):
        event = ev.get("event")
        w = ev.get("worker")
        tid = ev.get("trace_id")
        if event == "readmitted":
            inc[w] = inc.get(w, 0) + 1
            continue
        if event in ("worker_lost", "drained"):
            deaths.append((pos, (str(w), inc.get(w, 0)), ev))
            continue
        if tid is None:
            continue
        if event == "submitted":
            per_trace.setdefault(tid, []).append(
                (pos, ev, ("submit", (str(w), inc.get(w, 0)))))
        elif event == "redispatched":
            to = ev.get("to")
            # a re-dispatch caused by a LIVE worker's shed-back
            # (queue_full backpressure) is a give-back + failover, not
            # a death failover — the why names the worker-side shed
            op = ("giveback_failover" if "shed:" in str(ev.get("why"))
                  else "failover")
            per_trace.setdefault(tid, []).append(
                (pos, ev, (op, (str(to), inc.get(to, 0)))))
        elif event == "finished":
            per_trace.setdefault(tid, []).append(
                (pos, ev, ("finished", (str(w), inc.get(w, 0)))))
        elif event == "shed":
            detail = str((ev.get("payload") or {}).get("detail"))
            op = ("giveback_shed"
                  if detail.startswith("worker") and "shed:" in detail
                  else "shed")
            per_trace.setdefault(tid, []).append(
                (pos, ev, (op, None)))

    violations: List[Dict[str, Any]] = []
    incomplete: List[str] = []
    for tid, items in per_trace.items():
        # the per-trace worker universe: every incarnation the router
        # dispatched this request to, in first-dispatch order
        universe: List[Tuple[str, int]] = []
        for _, _, (op, who) in items:
            if who is not None and who not in universe:
                universe.append(who)
        if not universe:
            continue   # nothing dispatch-shaped journaled (torn head)
        n_failovers = sum(1 for _, _, (op, _) in items
                          if op in ("failover", "giveback_failover"))
        model = _mutated(make_done_xor_shed_model, mutator,
                         n_workers=len(universe),
                         max_attempts=1 + n_failovers)
        r = _Replay(model)
        submit_pos = items[0][0]
        # deaths interleave in ROUTER program order (every fleet event
        # is router-emitted, so fleet order IS program order); deaths
        # before this trace's submit are irrelevant to it
        timeline = sorted(
            [(pos, ev, tag) for pos, ev, tag in items]
            + [(pos, ev, ("death", who)) for pos, who, ev in deaths
               if who in universe and pos > submit_pos],
            key=lambda x: x[0])

        def idx(who) -> Optional[int]:
            return universe.index(who) if who in universe else None

        bad = None
        for pos, ev, (op, who) in timeline:
            if op == "submit":
                bad = r.step(f"submit(->w{idx(who)})", ev)
            elif op == "death":
                i = idx(who)
                bad = (r.try_step(f"worker{i}.dies", ev)
                       or r.try_step(f"supervisor.detect(w{i})", ev))
            elif op in ("failover", "giveback_failover"):
                cur = r.state.owner
                if cur is None:
                    bad = "failover of a request with no owner"
                else:
                    if op == "giveback_failover":
                        # the live owner returned the request first
                        # (no-op if the model already saw it die)
                        r.try_step(f"worker{cur}.give_back", ev)
                    bad = r.step(
                        f"supervisor.failover(w{cur}->w{idx(who)})", ev)
            elif op == "finished":
                i = idx(who)
                if i is None:
                    bad = (f"result accepted from {who} which this "
                           f"request was never dispatched to")
                else:
                    att = r.state.has_req[i]
                    if att is None:
                        bad = (f"result accepted from w{i} ({who[0]}) "
                               "with no dispatched attempt in flight")
                    else:
                        bad = (r.step(f"worker{i}.produce_result", ev)
                               or r.step(
                                   f"router.deliver_result(w{i},"
                                   f"att{att})", ev))
            elif op in ("shed", "giveback_shed"):
                cur = r.state.owner
                if cur is None:
                    bad = r.try_step("submit(reject:no_live_worker)",
                                     ev) or None
                else:
                    if op == "giveback_shed":
                        r.try_step(f"worker{cur}.give_back", ev)
                    bad = r.step(f"supervisor.shed(w{cur})", ev)
            if bad:
                violations.append(_violation(
                    "done_xor_shed", tid, f"{op}", bad, r.chain(ev)))
                break
        if bad:
            continue
        if r.state.registered and r.state.done + r.state.shed == 0:
            incomplete.append(tid)
    return violations, len(per_trace), incomplete


# ==========================================================================
# lease_fence: per-worker zombie fencing
# ==========================================================================

def replay_lease_fence(merged: Dict[str, Any],
                       mutator=None) -> Tuple[List[Dict[str, Any]], int]:
    """Replay each worker's beat/fence/judge stream; returns
    ``(violations, n_workers_checked)``."""
    per_worker: Dict[str, List[Dict[str, Any]]] = {}
    for ev in merged["events"]:
        kind = ev.get("kind")
        if kind in ("beat", "lease_judged", "fence", "hello_processed"):
            per_worker.setdefault(str(ev.get("worker")), []).append(ev)
        elif kind == "fleet" and ev.get("event") == "readmitted":
            per_worker.setdefault(str(ev.get("worker")), []).append(ev)

    violations: List[Dict[str, Any]] = []
    for worker, evs in per_worker.items():
        e0 = next((int(e["epoch"]) for e in evs
                   if e.get("epoch") is not None), 1)
        model = _mutated(make_lease_fence_model, mutator,
                         max_writes=1 << 60, max_readmits=1 << 60,
                         max_pending=1 << 60)
        model = Model(model.name,
                      model.initial._replace(worker_epoch=e0,
                                             current_epoch=e0),
                      model.transitions, model.invariant,
                      model.terminal_invariant)
        r = _Replay(model)
        pending: List[Tuple[int, Dict[str, Any]]] = []  # (lseq, beat ev)
        last_fence: Optional[Dict[str, Any]] = None

        def deliver(judged_ev, compare: bool) -> Optional[str]:
            lseq, beat_ev = pending.pop(0)
            before = len(r.state.landed)
            bad = r.step("fence.deliver_write", judged_ev)
            if bad:
                return bad
            if compare:
                model_admit = len(r.state.landed) > before
                real_admit = bool(judged_ev.get("admitted"))
                if model_admit != real_admit:
                    return (f"epoch fence diverges from model at lseq "
                            f"{lseq}: model says "
                            f"{'land' if model_admit else 'refuse'}, "
                            f"real fence "
                            f"{'admitted' if real_admit else 'refused'}")
            return None

        for ev in evs:
            kind = ev.get("kind")
            bad = None
            beat_ev = None
            if kind == "beat":
                # the wire epoch is the worker's truth — force it so
                # merged-order jitter around hello cannot desync it
                r.force(worker_epoch=int(ev.get("epoch", e0)))
                bad = r.step("worker.write", ev)
                if not bad:
                    pending.append((int(ev.get("lseq", -1)), ev))
            elif kind == "lease_judged":
                lseq = int(ev.get("lseq", -1))
                # beats superseded before the router read them were
                # never judged: deliver them uncompared to keep the
                # model's FIFO aligned with the real lease table
                while pending and pending[0][0] < lseq and not bad:
                    bad = deliver(ev, compare=False)
                if not bad and pending and pending[0][0] == lseq:
                    beat_ev = pending[0][1]
                    bad = deliver(ev, compare=True)
            elif kind == "fence":
                last_fence = ev
                bad = r.try_step("supervisor.fence", ev)
            elif kind == "fleet":   # readmitted
                bad = r.try_step("supervisor.readmit", ev)
                if ev.get("epoch") is not None:
                    r.force(current_epoch=int(ev["epoch"]))
            elif kind == "hello_processed":
                r.try_step("worker.process_hello", ev)
                # wire truth again: adopt the epoch the hello carried,
                # and the zombie window closes exactly here
                r.force(worker_epoch=int(ev.get("epoch", e0)),
                        zombie=False, hello_pending=False)
            if bad:
                chain = [e for e in (last_fence, beat_ev) if e]
                chain = [e for e in chain
                         if e not in r.trail[-(_CHAIN_CAP - 1):]]
                edge = (_hb_edge("lease", beat_ev, ev)
                        if beat_ev is not None else None)
                violations.append(_violation(
                    "lease_fence", worker, kind, bad,
                    chain + r.chain(ev), edge))
                break
    return violations, len(per_worker)


# ==========================================================================
# slot_lifecycle: per-allocator slot partition
# ==========================================================================

def replay_slot_lifecycle(merged: Dict[str, Any],
                          mutator=None) -> Tuple[List[Dict[str, Any]],
                                                 int]:
    """Replay each allocator's op stream; returns
    ``(violations, n_allocators_checked)``."""
    streams: Dict[Tuple[str, Any], Optional[_Replay]] = {}
    violations: List[Dict[str, Any]] = []
    for ev in merged["events"]:
        if ev.get("kind") != "slot":
            continue
        key = (str(ev.get("proc")), ev.get("alloc"))
        op = ev.get("op")
        if op == "init":
            streams[key] = _Replay(_mutated(
                make_slot_model, mutator,
                n_slots=int(ev.get("n_slots", 1)), max_rc=1 << 30))
            continue
        r = streams.get(key)
        if r is None:
            # allocator born before journaling started (or its replay
            # already failed): nothing sound to check against
            continue
        subject = f"{key[0]}/alloc{key[1]}"
        bad = None
        if op in ("acquire", "reserve"):
            expect = r.state.free[0] if r.state.free else None
            real = ev.get("slot")
            if expect is None:
                bad = (f"{op} returned slot {real} but the model free "
                       "list is empty (slot materialized from nowhere)")
            elif int(real) != int(expect):
                bad = (f"{op} returned slot {real}; lowest-free "
                       f"discipline demands {expect} "
                       f"(free={list(r.state.free)})")
            else:
                bad = r.step(op, ev)
        else:
            bad = r.step(f"{op}({ev.get('slot')})", ev)
        if bad:
            violations.append(_violation(
                "slot_lifecycle", subject, str(op), bad, r.chain(ev)))
            streams[key] = None   # stop cascading from one bad step
    checked = sum(1 for _ in streams)
    return violations, checked


# ==========================================================================
# the monitor: one merged journal -> one conformance report
# ==========================================================================

def check_conformance(merged: Dict[str, Any],
                      mutate: Mutators = None) -> Dict[str, Any]:
    """Replay one merged journal (:func:`~.journal.merge_journals`
    output) through all three protocol models.

    Returns ``{"schema", "ok", "violations", "checked", "incomplete"}``
    — ``checked`` counts replayed subjects per model (traces, workers,
    allocators), ``incomplete`` lists trace ids with no terminal
    outcome in the journal window (mid-run capture, not a violation).
    """
    mutate = mutate or {}
    dxs_v, n_traces, incomplete = replay_done_xor_shed(
        merged, mutate.get("done_xor_shed"))
    lf_v, n_workers = replay_lease_fence(merged,
                                         mutate.get("lease_fence"))
    slot_v, n_allocs = replay_slot_lifecycle(
        merged, mutate.get("slot_lifecycle"))
    violations = dxs_v + lf_v + slot_v
    return {
        "schema": CONFORMANCE_SCHEMA,
        "ok": not violations,
        "violations": violations,
        "checked": {"done_xor_shed": n_traces,
                    "lease_fence": n_workers,
                    "slot_lifecycle": n_allocs},
        "incomplete": incomplete,
    }


def check_dir(journal_dir: str, mutate: Mutators = None
              ) -> Dict[str, Any]:
    """Merge a journal directory and run the monitor over it."""
    from .journal import merge_journals
    return check_conformance(merge_journals(journal_dir), mutate)


def render_report(report: Dict[str, Any]) -> str:
    """Human rendering: verdict line, per-model counts, and each
    violation as its minimal causal chain."""
    checked = report.get("checked", {})
    lines = [
        ("conformance: "
         + ("OK" if report.get("ok") else
            f"{len(report['violations'])} VIOLATION(S)")
         + " ("
         + ", ".join(f"{k}: {v} checked"
                     for k, v in sorted(checked.items()))
         + (f", {len(report['incomplete'])} incomplete"
            if report.get("incomplete") else "")
         + ")")]
    for v in report.get("violations", []):
        lines.append(f"  [{v['model']}] {v['subject']}: {v['reason']}")
        lines.append("    causal chain (HLC order):")
        for c in v.get("chain", []):
            lines.append(f"      {c}")
        e = v.get("edge")
        if e:
            lines.append(
                f"    offending happens-before edge: {e['kind']} "
                f"hlc={tuple(e.get('src_hlc') or ())} -> "
                f"hlc={tuple(e.get('dst_hlc') or ())} "
                f"(events {e.get('src')} -> {e.get('dst')})")
    return "\n".join(lines)
