"""Collective-communication accounting.

Every collective in the in-jit face (``chainermn_tpu.ops.collective``) and
the eager face (``communicators/``) reports through here: op name, axis,
payload bytes, wire dtype, and — when the call runs eagerly, outside a
trace — host-side latency.  The EQuARX-style question ("how many bytes
crossed the wire per step, through which collective?") becomes readable
from the training log and the exported Chrome trace instead of requiring
an external profiler.

Two call regimes, one ledger
----------------------------
* **Eager** (communicator methods, setup paths): each call records bytes
  AND host latency; a ``comm/<op>`` span brackets it on the timeline.
* **In-jit** (ops wrappers under ``jit``/``shard_map``): the wrapper runs
  at TRACE time, so a record lands once per compilation, not per
  execution.  The :meth:`CommAccountant.step` capture fixes the
  per-step view: collectives recorded while tracing a step program are
  remembered as that program's *profile*, and every later execution of
  the same program re-books the profile — the compiled program really
  does replay those collectives each step.  Latency inside jit is XLA's
  business (overlapped with compute); only bytes/calls are booked.

All recording is a no-op while tracing is disabled (one attribute read).

CAVEAT — enable BEFORE the first compile: in-jit records land at trace
time, so a program compiled while tracing was disabled carries no
bookings and no stored profile — its collectives stay invisible to the
ledger for as long as the jit cache serves it (re-jitting, e.g. after a
shape change, repairs this).  Enable tracing before building/warming the
step to get in-jit accounting; eager calls are always booked live.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

import numpy as np

from . import trace
# stdlib-only at module level (its lane/store imports are lazy), so this
# costs nothing and keeps the guarded hot path at ONE global read/call
from ..health import collective_guard as _collective_guard


def _as_dtype(dt) -> np.dtype:
    """np.dtype over names numpy alone doesn't know ('bfloat16')."""
    try:
        return np.dtype(dt)
    except TypeError:
        import jax.numpy as jnp
        return np.dtype(getattr(jnp, str(dt)))


def payload_info(tree) -> tuple:
    """``(nbytes, dtype_str, n_elements, in_jit)`` over a pytree's leaves.

    Works on concrete arrays and on tracers (via ``aval``) so the same
    accounting serves the eager and in-jit faces.  This function IS the
    ledger's byte convention — one logical payload per call, shape ×
    itemsize, independent of axis size — and the static cost model
    computes its per-equation bytes through it
    (``analysis.shardflow._aval_nbytes`` feeds avals in), so the two
    sides of the reconciliation can never diverge on the formula.
    """
    import jax

    nbytes = 0
    n_elems = 0
    dtype = None
    in_jit = False
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.core.Tracer):
            in_jit = True
        aval = getattr(leaf, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is None:
            shape = getattr(leaf, "shape", ())
        dt = getattr(aval, "dtype", None)
        if dt is None:
            dt = getattr(leaf, "dtype", None)
        dt = np.dtype(dt) if dt is not None else np.dtype(np.float32)
        n = int(np.prod(shape)) if shape else 1
        n_elems += n
        nbytes += n * dt.itemsize
        dtype = dtype or str(dt)
    return nbytes, dtype or "float32", n_elems, in_jit


class CommAccountant:
    """Ledger of collective calls: cumulative totals, per-program trace
    profiles, and a per-step report."""

    def __init__(self):
        self._lock = threading.Lock()
        self.totals: Dict[str, Dict[str, float]] = {}
        self._programs: Dict[Any, Dict[str, Dict[str, float]]] = {}
        self._step_accum: Optional[Dict[str, Dict[str, float]]] = None
        # in-jit-only rows of the current step — ONLY these become the
        # program profile (an eager collective recorded in the same
        # bracket is live every step and must not be replayed on top of
        # itself)
        self._step_jit: Optional[Dict[str, Dict[str, float]]] = None
        self._step_traced = False
        self.last_step_report: Optional[Dict[str, Any]] = None

    def reset(self) -> None:
        with self._lock:
            self.totals = {}
            self._programs = {}
            self._step_accum = None
            self._step_jit = None
            self._step_traced = False
            self.last_step_report = None

    # ---- recording ----
    def record(self, op: str, axis, nbytes: int, dtype: str,
               in_jit: bool, latency_s: Optional[float] = None,
               noted: bool = False) -> None:
        """``noted=True`` marks a DECLARED collective (booked via
        :func:`note` — the host's knowledge of traffic no wrapper sees,
        e.g. the autodiff-inserted gradient psum).  Noted bytes
        accumulate in a separate ``noted_bytes`` field on the row, so a
        key shared between wrapped calls and notes (rows aggregate per
        ``op@axis``) still splits exactly — the shard-flow
        reconciliation holds wrapped bytes to the traced equations and
        noted bytes to the entry point's declaration."""
        axis_key = "+".join(axis) if isinstance(axis, (tuple, list)) else str(axis)
        key = f"{op}@{axis_key}"
        with self._lock:
            row = self.totals.setdefault(
                key, {"calls": 0, "bytes": 0, "host_time_s": 0.0})
            row["calls"] += 1
            row["bytes"] += int(nbytes)
            if latency_s is not None:
                row["host_time_s"] += float(latency_s)
            if noted:
                row["noted_bytes"] = row.get("noted_bytes", 0) + int(nbytes)
            # a key can aggregate calls of several dtypes (fp32 loss +
            # int32 counters through the same psum@axis) — keep the set
            dts = row.setdefault("dtypes", [])
            if dtype not in dts:
                dts.append(dtype)
            if self._step_accum is not None:
                srow = self._step_accum.setdefault(
                    key, {"calls": 0, "bytes": 0, "host_time_s": 0.0})
                srow["calls"] += 1
                srow["bytes"] += int(nbytes)
                if latency_s is not None:
                    srow["host_time_s"] += float(latency_s)
                if noted:
                    srow["noted_bytes"] = (srow.get("noted_bytes", 0)
                                           + int(nbytes))
                if in_jit:
                    self._step_traced = True
                    jrow = self._step_jit.setdefault(
                        key, {"calls": 0, "bytes": 0, "host_time_s": 0.0})
                    jrow["calls"] += 1
                    jrow["bytes"] += int(nbytes)
                    if noted:
                        jrow["noted_bytes"] = (jrow.get("noted_bytes", 0)
                                               + int(nbytes))
        tr = trace.get_tracer()
        tr.add_counter(f"comm/{op}/bytes", nbytes)
        tr.add_counter(f"comm/{op}/calls", 1)
        # flight-recorder tee: one ring event per accounting delta, so a
        # postmortem shows the last collectives the process completed
        from . import flight as _flight
        _flight.note("comm", op=op, axis=axis_key, bytes=int(nbytes),
                     dtype=dtype, in_jit=bool(in_jit))

    # ---- per-step capture ----
    @contextmanager
    def step(self, program_key: Any = "step"):
        """Bracket one training step.  Collectives recorded inside are
        the step's comm work; in-jit ops traced during a (re)compile are
        stored as the program's profile and re-booked on cache-hit
        executions.  ``last_step_report`` holds the finished report
        afterwards.

        CONTRACT: one ``program_key`` ↔ one jitted program (the
        ``StandardUpdater`` bracket wraps exactly its ``step_fn`` call).
        A retrace REPLACES the stored profile — correct for shape-change
        recompiles of the same program.  If a bracket spans several
        independently-compiled jits, give each its own bracket/key;
        under one key, whichever traced last would win and cache-hit
        replays would misattribute the others."""
        if not trace.get_tracer().enabled:
            # no report for an untraced step — and clear any earlier one
            # so consumers (StepBreakdownReport) don't republish frozen
            # values forever after tracing is disabled mid-run (locked:
            # the traced finalize writes it under _lock on another
            # thread's step bracket)
            with self._lock:
                self.last_step_report = None
            yield None
            return
        with self._lock:
            self._step_accum = {}
            self._step_jit = {}
            self._step_traced = False
        try:
            yield self
        finally:
            replayed = {}
            with self._lock:
                accum = self._step_accum or {}
                jit_rows = self._step_jit or {}
                self._step_accum = None
                self._step_jit = None
                if self._step_traced:
                    # a compile happened: remember the program's
                    # structural (in-jit ONLY) collectives for cache-hit
                    # steps — eager rows recorded in the same bracket are
                    # live every step and must not be replayed too
                    self._programs[program_key] = {
                        k: dict(v) for k, v in jit_rows.items()}
                else:
                    # cache hit: the compiled program still ran its
                    # collectives — book the remembered profile (without
                    # host latency, which XLA overlaps internally) into
                    # BOTH the step report and the cumulative ledger, so
                    # totals reflect executed collectives, not compiles.
                    replayed = self._programs.get(program_key, {})
                    for k, v in replayed.items():
                        for dest in (accum, self.totals):
                            row = dest.setdefault(
                                k, {"calls": 0, "bytes": 0,
                                    "host_time_s": 0.0})
                            row["calls"] += v["calls"]
                            row["bytes"] += v["bytes"]
                            if v.get("noted_bytes"):
                                row["noted_bytes"] = (
                                    row.get("noted_bytes", 0)
                                    + v["noted_bytes"])
                self.last_step_report = self._summarize(accum)
            # mirror the replayed bookings into the trace counter tracks
            # (outside our lock — the tracer takes its own), so the
            # exported comm/<op> counters advance every step, not just on
            # the compile step
            tr = trace.get_tracer()
            for k, v in replayed.items():
                op = k.split("@", 1)[0]
                tr.add_counter(f"comm/{op}/bytes", v["bytes"])
                tr.add_counter(f"comm/{op}/calls", v["calls"])

    @staticmethod
    def _summarize(accum: Dict[str, Dict[str, float]]) -> Dict[str, Any]:
        def snap(v):
            # deep enough that the report is a true snapshot — the
            # 'dtypes' list keeps growing in the live row
            out = dict(v)
            if "dtypes" in out:
                out["dtypes"] = list(out["dtypes"])
            return out

        return {
            "per_op": {k: snap(v) for k, v in accum.items()},
            "bytes": int(sum(v["bytes"] for v in accum.values())),
            "calls": int(sum(v["calls"] for v in accum.values())),
            "host_time_s": float(sum(v.get("host_time_s", 0.0)
                                     for v in accum.values())),
        }

    def report(self) -> Dict[str, Any]:
        """Cumulative per-op totals since enable/reset."""
        with self._lock:
            return self._summarize(self.totals)


_ACCOUNTANT = CommAccountant()


def get_accountant() -> CommAccountant:
    return _ACCOUNTANT


def note(op: str, axis, tree) -> None:
    """Book a collective the host cannot wrap — e.g. the psum that
    autodiff inserts for replicated-param cotangents on the default
    train-step path.  The caller knows the op happens and what it moves
    (the pytree's size); this records that knowledge so the flagship
    path's gradient traffic appears in the ledger instead of reading as
    a 4-byte loss pmean.  In-jit-ness is inferred from the leaves, so a
    note recorded at trace time replays per step like any wrapped
    collective."""
    if not trace.get_tracer().enabled:
        return
    nbytes, dtype, _, in_jit = payload_info(tree)
    _ACCOUNTANT.record(op, axis, nbytes, dtype, in_jit=in_jit, noted=True)


def collective(op: str, axis, x, thunk, wire_dtype=None):
    """Run ``thunk()`` (the actual ``jax.lax`` collective) under
    accounting.  The in-jit face's single entry point: bytes/dtype come
    from ``x``'s leaves; host latency is recorded only for eager calls;
    ``wire_dtype`` overrides the byte count for compressed-wire ops
    (quantized ring: int8 payload regardless of ``x.dtype``).

    When a :class:`~chainermn_tpu.health.CollectiveGuard` is installed
    (``health.set_collective_guard`` — the training gang's collective
    watchdog, ISSUE 13), every EAGER call is bracketed by
    ``guard.enter/exit``: a call that outlives the guard window is
    aborted loudly with the missing rank(s) NAMED from the lease table
    instead of hanging anonymously.  Trace-time (in-jit) calls complete
    at trace and are not guarded; guarding works with tracing disabled.
    """
    tr = trace.get_tracer()
    guard = _collective_guard()
    if not tr.enabled:
        if guard is None:
            return thunk()
        tok = guard.enter(op)
        try:
            return thunk()
        finally:
            guard.exit(tok)
    nbytes, dtype, n_elems, in_jit = payload_info(x)
    if wire_dtype is not None:
        wd = _as_dtype(wire_dtype)
        dtype = str(wd)
        nbytes = n_elems * wd.itemsize
    if in_jit:
        out = thunk()
        _ACCOUNTANT.record(op, axis, nbytes, dtype, in_jit=True)
        return out
    tok = guard.enter(op) if guard is not None else None
    t0 = time.perf_counter()
    try:
        with tr.span(f"comm/{op}", cat="comm", axis=str(axis), bytes=nbytes):
            out = thunk()
    finally:
        if tok is not None:
            guard.exit(tok)
    _ACCOUNTANT.record(op, axis, nbytes, dtype, in_jit=False,
                       latency_s=time.perf_counter() - t0)
    return out


_EAGER_DEPTH = threading.local()


def accounted_method(op: str):
    """Decorator for eager communicator collectives (``comm.allreduce``
    and friends): bytes from the rank-major stack, host-side dispatch
    latency, a ``comm/<op>`` span on the timeline.  Applied
    automatically to every backend by ``CommunicatorBase
    .__init_subclass__`` — naive, xla, and any future subclass.

    Re-entrancy guarded: only the OUTERMOST accounted call records, so a
    subclass override delegating to ``super().allreduce(...)`` (both
    levels wrapped by ``__init_subclass__``) books one logical
    collective once, and helpers implemented in terms of other wrapped
    collectives (``multi_node_mean_grad`` → ``allreduce``) book under
    the caller's name rather than double.

    The installed :class:`~chainermn_tpu.health.CollectiveGuard` (if
    any) brackets the OUTERMOST call too — the communicator hot path's
    bounded-timeout watchdog (ISSUE 13), active even with tracing off.
    """
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, x, *args, **kwargs):
            tr = trace.get_tracer()
            nested = getattr(_EAGER_DEPTH, "d", 0)
            guard = None if nested else _collective_guard()
            tok = guard.enter(op) if guard is not None else None
            try:
                if not tr.enabled or nested:
                    if guard is None:
                        return fn(self, x, *args, **kwargs)
                    # outermost-with-guard, tracing off: still mark the
                    # depth so a delegating helper (multi_node_mean_grad
                    # -> allreduce) cannot double-enter the guard
                    _EAGER_DEPTH.d = 1
                    try:
                        return fn(self, x, *args, **kwargs)
                    finally:
                        _EAGER_DEPTH.d = 0
                nbytes, dtype, _, _ = payload_info(x)
                axis = getattr(self, "axis_name", "world")
                _EAGER_DEPTH.d = 1
                t0 = time.perf_counter()
                try:
                    with tr.span(f"comm/{op}", cat="comm", axis=str(axis),
                                 bytes=nbytes):
                        out = fn(self, x, *args, **kwargs)
                finally:
                    _EAGER_DEPTH.d = 0
                _ACCOUNTANT.record(op, axis, nbytes, dtype, in_jit=False,
                                   latency_s=time.perf_counter() - t0)
                return out
            finally:
                if tok is not None:
                    guard.exit(tok)
        wrapper._obs_wrapped = True
        return wrapper
    return deco


#: Back-compat alias (the helper predates its public face).
_payload_info = payload_info


# --------------------------------------------------------------------------
# schedule-execution counters + the /statusz calibration provider
# (ISSUE 20: the collective truth plane's always-on aggregate face)
# --------------------------------------------------------------------------

_SCHED_LOCK = threading.Lock()
_SCHED_EXEC: Dict[str, float] = {}
_ACTIVE_CALIBRATION: Optional[Dict[str, Any]] = None
_CAL_PROVIDER_REGISTERED = False


def _register_calibration_provider() -> None:
    global _CAL_PROVIDER_REGISTERED
    if _CAL_PROVIDER_REGISTERED:
        return
    from . import flight as _flight
    _flight.register_provider("calibration", calibration_snapshot)
    _CAL_PROVIDER_REGISTERED = True


def record_schedule_exec(records) -> None:
    """Book one profiled schedule execution's records into the
    ``schedule_exec/*`` counters (/metricsz face) and tracer counters
    (Chrome trace face).  Called by ``reshard._emit_schedule_exec``;
    first booking registers the /statusz ``calibration`` provider."""
    if not records:
        return
    with _SCHED_LOCK:
        for r in records:
            link = r.get("link", "?")
            _SCHED_EXEC[f"schedule_exec/{link}/ops"] = \
                _SCHED_EXEC.get(f"schedule_exec/{link}/ops", 0.0) + 1
            _SCHED_EXEC[f"schedule_exec/{link}/bytes"] = \
                _SCHED_EXEC.get(f"schedule_exec/{link}/bytes", 0.0) \
                + float(r.get("bytes", 0))
            _SCHED_EXEC[f"schedule_exec/{link}/wall_us"] = \
                _SCHED_EXEC.get(f"schedule_exec/{link}/wall_us", 0.0) \
                + float(r.get("wall_us", 0.0))
        _SCHED_EXEC["schedule_exec/records"] = \
            _SCHED_EXEC.get("schedule_exec/records", 0.0) + len(records)
        _SCHED_EXEC["schedule_exec/executions"] = \
            _SCHED_EXEC.get("schedule_exec/executions", 0.0) + 1
    tr = trace.get_tracer()
    if tr.enabled:
        tr.add_counter("schedule_exec/records", float(len(records)))
    _register_calibration_provider()


def schedule_exec_gauges() -> Dict[str, float]:
    """Snapshot of the ``schedule_exec/*`` counters (merged into
    /metricsz the same way the flight drop counts are)."""
    with _SCHED_LOCK:
        return dict(_SCHED_EXEC)


def set_active_calibration(cal: Optional[Dict[str, Any]]) -> None:
    """Install (or clear) the calibration artifact the process is
    currently pricing schedules with; surfaces via the /statusz
    ``calibration`` provider."""
    global _ACTIVE_CALIBRATION
    with _SCHED_LOCK:
        _ACTIVE_CALIBRATION = cal
    if cal is not None:
        _register_calibration_provider()


def calibration_snapshot() -> Dict[str, Any]:
    """The /statusz ``calibration`` provider: live counters plus the
    active artifact's fitted constants (if one is installed)."""
    with _SCHED_LOCK:
        counters = dict(_SCHED_EXEC)
        cal = _ACTIVE_CALIBRATION
    out: Dict[str, Any] = {"counters": counters}
    if cal is None:
        out["calibration"] = None
    else:
        out["calibration"] = {
            "schema": cal.get("schema"),
            "n_records": cal.get("n_records"),
            "links": cal.get("links"),
        }
    return out


def reset_schedule_exec() -> None:
    """Test hook: clear counters and the active calibration."""
    global _ACTIVE_CALIBRATION
    with _SCHED_LOCK:
        _SCHED_EXEC.clear()
        _ACTIVE_CALIBRATION = None
