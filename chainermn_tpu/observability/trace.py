"""Low-overhead span tracer with Chrome-trace / Perfetto JSON export.

SURVEY.md §5: the reference had no in-tree observability beyond wrapping
nvprof by hand; the related work this repo chases (EQuARX, redistribution
scheduling — PAPERS.md) argues entirely from per-collective byte/latency
accounting.  This module is the substrate for that accounting: nested
spans, counters and gauges recorded host-side with microsecond stamps,
exported in the Chrome Trace Event format that ``chrome://tracing`` and
``ui.perfetto.dev`` load directly.

Design rules:

* **No-op when disabled.**  ``span()`` returns a shared singleton context
  manager and every record call bails on one attribute read — tracing
  must be free enough to leave the call sites in the hot path permanently
  (the acceptance gate is <1% step-time regression with tracing off).
* **Thread-local nesting.**  Each thread keeps its own span stack, so
  iterator workers and the watchdog thread trace independently; Chrome
  renders nesting per ``tid`` from the timestamps.
* **Stdlib only.**  Importable everywhere, including before a JAX
  backend exists.

Usage::

    from chainermn_tpu import observability as obs
    obs.enable()
    with obs.span("step", iteration=3):
        with obs.span("step/data", cat="phase"):
            ...
    obs.add_counter("comm/psum/bytes", 4096)
    obs.export_chrome_trace("trace.json")

or as a decorator::

    @obs.traced("load_batch")
    def load_batch(...): ...
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class _NullSpan:
    """Shared do-nothing context manager — the disabled-tracer fast path.

    A singleton so ``span()`` with tracing off allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Records one Chrome ``X`` (complete) event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._tracer._stack().append(self.name)
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        t1 = tr._now_us()
        stack = tr._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        ev = {"name": self.name, "cat": self.cat, "ph": "X",
              "ts": self._t0, "dur": max(t1 - self._t0, 0),
              "pid": tr._pid, "tid": tr._tid()}
        if self.args:
            ev["args"] = self.args
        tr._commit(ev)
        return False


class Tracer:
    """Process-wide event recorder (use the module-level singleton via
    :func:`get_tracer`; independent instances are for tests)."""

    #: Hard cap on buffered events (spans + counters).  At the cap the
    #: tracer stops appending EVENTS (counter/gauge TOTALS stay exact)
    #: and counts drops; the export marks the truncation.  ~200-400 B
    #: per event keeps worst-case buffer memory in the low hundreds of
    #: MB — multi-hour runs with tracing left on degrade gracefully
    #: instead of eating the host.
    DEFAULT_MAX_EVENTS = 1_000_000

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        self.enabled = False
        self.max_events = int(max_events)
        self._dropped = 0
        self._events: List[Dict[str, Any]] = []
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tids: Dict[int, int] = {}
        self._pid = os.getpid()
        self._epoch_ns = time.perf_counter_ns()
        # Event sinks (the flight-recorder tee): called with every
        # appended event dict, OUTSIDE the buffer lock.  A sink must be
        # cheap and must never call back into the tracer.
        self._sinks: List[Callable[[Dict[str, Any]], None]] = []

    # ---- lifecycle ----
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events = []
            self._dropped = 0
            self._counters = {}
            self._gauges = {}
            self._epoch_ns = time.perf_counter_ns()

    # ---- internals ----
    def _now_us(self) -> int:
        return (time.perf_counter_ns() - self._epoch_ns) // 1000

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _append(self, ev: Dict[str, Any]) -> None:
        # holds-lock: _lock  (callers serialize; the concurrency lint
        # verifies every intra-class call site against this contract)
        if len(self._events) >= self.max_events:
            self._dropped += 1
            return
        self._events.append(ev)

    def _commit(self, ev: Dict[str, Any]) -> None:
        """Buffer ``ev`` (under the lock), then fan it out to any
        registered sinks (outside the lock — a sink taking its own lock
        must never nest inside ours)."""
        with self._lock:
            self._append(ev)
        for sink in self._sinks:
            try:
                sink(ev)
            except Exception:
                pass  # a broken tee must never break tracing itself

    def add_sink(self, sink: Callable[[Dict[str, Any]], None]) -> None:
        """Register an event tee (e.g. the flight recorder); idempotent
        per callable."""
        if sink not in self._sinks:
            self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[Dict[str, Any]], None]) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)

    def now_us(self) -> int:
        """Public face of the tracer clock (µs since this tracer's
        epoch) — for callers recording retrospective spans via
        :meth:`complete_event`."""
        return self._now_us()

    # ---- recording surface ----
    def span(self, name: str, cat: str = "span", **args):
        """Context manager timing a nested span; no-op singleton when
        disabled (zero allocation on the hot path)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def traced(self, name: Optional[str] = None, cat: str = "span"):
        """Decorator face of :meth:`span`."""
        import functools

        def wrap(fn: Callable) -> Callable:
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with self.span(label, cat=cat):
                    return fn(*a, **kw)
            return inner
        return wrap

    def current_span(self) -> Optional[str]:
        """Innermost open span NAME on this thread (the thread-local
        context), or None outside any span."""
        stack = self._stack()
        return stack[-1] if stack else None

    def add_counter(self, name: str, value: float = 1.0) -> float:
        """Accumulate a monotonic counter; emits a Chrome ``C`` event
        carrying the running total.  Returns the new total."""
        if not self.enabled:
            return 0.0
        with self._lock:
            total = self._counters.get(name, 0.0) + value
            self._counters[name] = total
            self._append({
                "name": name, "ph": "C", "ts": self._now_us(),
                "pid": self._pid, "tid": 0,
                "args": {name.rsplit("/", 1)[-1]: total}})
        # counters are too hot for the tee: flight consumers read the
        # comm ledger's deltas instead (observability.comm tees those)
        return total

    def set_gauge(self, name: str, value: float) -> None:
        """Instantaneous value (throughput, MFU); emits a ``C`` event."""
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)
            self._append({
                "name": name, "ph": "C", "ts": self._now_us(),
                "pid": self._pid, "tid": 0,
                "args": {name.rsplit("/", 1)[-1]: float(value)}})

    def instant(self, name: str, cat: str = "instant", **args) -> None:
        """Point-in-time marker (Chrome ``i`` event)."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._now_us(), "pid": self._pid, "tid": self._tid()}
        if args:
            ev["args"] = args
        self._commit(ev)

    def complete_event(self, name: str, t0_us: int, dur_us: int,
                       cat: str = "span", **args) -> None:
        """Record a RETROSPECTIVE span from explicit tracer-clock stamps
        (see :meth:`now_us`) — e.g. a request's queue-wait, whose start
        was observed before anyone knew whether it would be admitted."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "X", "ts": int(t0_us),
              "dur": max(int(dur_us), 0), "pid": self._pid,
              "tid": self._tid()}
        if args:
            ev["args"] = args
        self._commit(ev)

    def async_event(self, ph: str, name: str, async_id, cat: str = "flow",
                    ts_us: Optional[int] = None, **args) -> None:
        """Chrome ASYNC event (``ph`` in ``b``/``n``/``e``): all events
        sharing ``(cat, id)`` render as one flow track in Perfetto —
        the per-request lane keyed by trace id.  ``ts_us`` overrides the
        stamp for retrospective emission."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": ph, "id": str(async_id),
              "ts": self._now_us() if ts_us is None else int(ts_us),
              "pid": self._pid, "tid": self._tid()}
        if ph == "n":
            ev["s"] = "t"
        if args:
            ev["args"] = args
        self._commit(ev)

    # ---- read-out ----
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def summary(self) -> Dict[str, Any]:
        """Aggregate view: per-span-name {count, total_ms} + counters."""
        spans: Dict[str, Dict[str, float]] = {}
        for ev in self.events():
            if ev.get("ph") != "X":
                continue
            s = spans.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
            s["count"] += 1
            s["total_ms"] += ev["dur"] / 1e3
        for s in spans.values():
            s["total_ms"] = round(s["total_ms"], 3)
        return {"spans": spans, "counters": self.counters(),
                "gauges": self.gauges(), "dropped_events": self._dropped}

    def export_chrome_trace(self, path: str,
                            rank: Optional[int] = None) -> Dict[str, Any]:
        """Write the Chrome Trace Event JSON (loadable in Perfetto /
        ``chrome://tracing``); returns the document.

        ``rank`` switches on the **rank-sharded mode** for multi-controller
        jobs: the file goes to :func:`shard_path` (``trace.json`` →
        ``trace.rank00003.json``), every event's ``pid`` is rewritten to
        the rank (one Perfetto lane per rank after the merge), the process
        lane is named ``rank N``, and the document carries a
        ``metadata.rank`` stamp that ``observability.aggregate
        .merge_trace_shards`` reads back.  Each shard is itself a valid
        standalone trace.
        """
        pid = self._pid if rank is None else int(rank)
        pname = "chainermn_tpu" if rank is None else f"rank {int(rank)}"
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "tid": 0, "args": {"name": pname}}]
        with self._lock:
            for ident, tid in sorted(self._tids.items(),
                                     key=lambda kv: kv[1]):
                meta.append({"name": "thread_name", "ph": "M",
                             "pid": pid, "tid": tid,
                             "args": {"name": f"thread-{tid}"
                                      if tid else "main"}})
            events = meta + (
                list(self._events) if rank is None
                else [dict(ev, pid=pid) for ev in self._events])
            if self._dropped:
                events.append({
                    "name": "trace/truncated", "cat": "tracer", "ph": "i",
                    "s": "g", "ts": self._now_us(), "pid": pid,
                    "tid": 0,
                    "args": {"dropped_events": self._dropped,
                             "max_events": self.max_events}})
            doc = {"traceEvents": events, "displayTimeUnit": "ms"}
            if rank is not None:
                doc["metadata"] = {"rank": int(rank),
                                   "host_pid": self._pid}
        if rank is not None:
            from .aggregate import shard_path
            path = shard_path(path, rank)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # partial runs never leave a truncated file
        return doc


_GLOBAL = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL


# ---- module-level conveniences over the global tracer ----
def enable() -> None:
    _GLOBAL.enable()


def disable() -> None:
    _GLOBAL.disable()


def enabled() -> bool:
    return _GLOBAL.enabled


def reset() -> None:
    _GLOBAL.reset()


def span(name: str, cat: str = "span", **args):
    return _GLOBAL.span(name, cat=cat, **args)


def traced(name: Optional[str] = None, cat: str = "span"):
    return _GLOBAL.traced(name, cat=cat)


def instant(name: str, cat: str = "instant", **args) -> None:
    _GLOBAL.instant(name, cat=cat, **args)


def complete_event(name: str, t0_us: int, dur_us: int,
                   cat: str = "span", **args) -> None:
    _GLOBAL.complete_event(name, t0_us, dur_us, cat=cat, **args)


def async_event(ph: str, name: str, async_id, cat: str = "flow",
                ts_us: Optional[int] = None, **args) -> None:
    _GLOBAL.async_event(ph, name, async_id, cat=cat, ts_us=ts_us, **args)


def now_us() -> int:
    return _GLOBAL.now_us()


def add_counter(name: str, value: float = 1.0) -> float:
    return _GLOBAL.add_counter(name, value)


def set_gauge(name: str, value: float) -> None:
    _GLOBAL.set_gauge(name, value)


def export_chrome_trace(path: str,
                        rank: Optional[int] = None) -> Dict[str, Any]:
    return _GLOBAL.export_chrome_trace(path, rank=rank)
