"""Rolling-window anomaly detection for training runs.

The observability layer so far *records*; this module *judges*.  Four
detector families cover the failure modes a production trainer actually
hits (ROADMAP north star: a service, not a notebook):

* :class:`StepTimeSpikeDetector` — EWMA + EW-variance z-score on the
  per-iteration wall clock.  Catches a wedging rank, a thermally
  throttled chip, a preempting neighbor — *before* the Watchdog's hard
  timeout, while the job is still degraded rather than dead.
* :class:`LossAnomalyDetector` — NaN/Inf immediately (one poisoned
  gradient allreduce poisons the gang), plus divergence: loss rising a
  configurable factor above its exponential baseline.
* :class:`CommBytesDriftDetector` — a compiled SPMD step moves the SAME
  bytes every execution; per-step comm bytes drifting from the warmup
  baseline means a silent recompile (shape leak) or a collective that
  stopped being booked.
* :class:`MFUDropDetector` — sustained utilization collapse relative to
  the run's own peak.

Detectors are pure host-side arithmetic over already-observed scalars —
no device syncs beyond what the caller already forced — and are wired
into the trainer through :class:`HealthMonitor`, whose findings become
(1) trace instant-events on the Perfetto timeline, (2) one structured
JSON log line per finding on stderr, and (3) calls to a pluggable
``escalate`` callback (page, abort, checkpoint-and-drain — policy lives
with the caller, detection lives here).

Threshold tuning guidance lives in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import math
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import trace
from .comm import get_accountant


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


class Ewma:
    """Exponentially-weighted mean + variance (West's recurrence)."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, v: float) -> None:
        v = float(v)
        if self.n == 0:
            self.mean = v
        else:
            d = v - self.mean
            self.mean += self.alpha * d
            self.var = (1 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1

    @property
    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))


class Detector:
    """One named check over a scalar stream.

    ``update(value, iteration)`` returns a finding dict (``kind``,
    ``iteration``, ``value``, ``expected``, ``detail``) when the value is
    anomalous, else None.  Detectors keep their own rolling state; a None
    value (metric absent this iteration) is skipped without advancing the
    baseline.
    """

    #: observation-side metric this detector consumes (HealthMonitor key).
    metric = ""
    kind = ""

    def update(self, value, iteration: int) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def _finding(self, iteration: int, value, expected,
                 detail: str) -> Dict[str, Any]:
        return {"kind": self.kind, "metric": self.metric,
                "iteration": int(iteration), "value": float(value),
                "expected": expected, "detail": detail}


class StepTimeSpikeDetector(Detector):
    """Step-time spike: z-score vs an EWMA baseline.

    ``threshold_z`` sigmas above the EW mean (and at least
    ``min_ratio``× it — the z-score alone misfires when early variance is
    ~0) after ``warmup`` clean iterations.  The spike sample is NOT folded
    into the baseline (a wedged run must keep alarming, not teach the
    baseline that slow is normal).
    """

    metric = "step_time_s"
    kind = "step_time_spike"

    def __init__(self, threshold_z: float = 4.0, min_ratio: float = 1.5,
                 warmup: int = 5, alpha: float = 0.2):
        self.threshold_z = float(threshold_z)
        self.min_ratio = float(min_ratio)
        self.warmup = int(warmup)
        self._ewma = Ewma(alpha)

    def update(self, value, iteration):
        if value is None or not _finite(value):
            return None
        v = float(value)
        e = self._ewma
        if e.n >= self.warmup and e.mean > 0:
            sigma = max(e.std, 1e-12)
            z = (v - e.mean) / sigma
            if z > self.threshold_z and v > self.min_ratio * e.mean:
                return self._finding(
                    iteration, v, round(e.mean, 6),
                    f"step took {v:.4f}s, {v / e.mean:.1f}x the EWMA "
                    f"baseline {e.mean:.4f}s (z={z:.1f})")
        e.update(v)
        return None


class LossAnomalyDetector(Detector):
    """Loss NaN/Inf (immediate) and divergence (vs the EW baseline).

    Divergence fires when the loss exceeds ``divergence_factor`` × the
    EW mean of the |loss| baseline after ``warmup`` samples — loose
    enough for normal training noise, tight enough that a blown-up run
    alarms within a few iterations.  Non-finite values fire on the very
    first sample: there is no baseline that makes NaN acceptable.
    """

    metric = "loss"
    kind = "loss_anomaly"

    def __init__(self, divergence_factor: float = 3.0, warmup: int = 5,
                 alpha: float = 0.1):
        self.divergence_factor = float(divergence_factor)
        self.warmup = int(warmup)
        self._ewma = Ewma(alpha)

    def update(self, value, iteration):
        if value is None:
            return None
        if not _finite(value):
            return dict(self._finding(
                iteration, float("nan"), None,
                f"loss is non-finite ({value!r})"), kind="loss_nonfinite")
        v = float(value)
        e = self._ewma
        if (e.n >= self.warmup
                and abs(v) > self.divergence_factor * max(abs(e.mean), 1e-12)
                and abs(v) > abs(e.mean)):
            return self._finding(
                iteration, v, round(e.mean, 6),
                f"loss {v:.4g} is {abs(v) / max(abs(e.mean), 1e-12):.1f}x "
                f"the EWMA baseline {e.mean:.4g} — divergence")
        e.update(v)
        return None


class CommBytesDriftDetector(Detector):
    """Per-step comm bytes drifting from the compiled baseline.

    The baseline is the median of the first ``warmup`` per-step byte
    totals (median, not mean: the compile step itself can book extra
    eager traffic).  After that, any step whose total deviates more than
    ``rel_tol`` relatively fires — the step program either recompiled
    with different collectives (shape leak) or a collective went missing
    from the ledger.
    """

    metric = "comm_bytes"
    kind = "comm_bytes_drift"

    def __init__(self, rel_tol: float = 0.25, warmup: int = 3):
        self.rel_tol = float(rel_tol)
        self.warmup = int(warmup)
        self._seen: List[float] = []
        self.baseline: Optional[float] = None

    def update(self, value, iteration):
        if value is None or not _finite(value):
            return None
        v = float(value)
        if self.baseline is None:
            self._seen.append(v)
            if len(self._seen) >= self.warmup:
                s = sorted(self._seen)
                self.baseline = s[len(s) // 2]
            return None
        base = self.baseline
        if base <= 0:
            return None
        drift = abs(v - base) / base
        if drift > self.rel_tol:
            return self._finding(
                iteration, v, base,
                f"comm bytes/step {v:.0f} drifted {drift * 100:.0f}% from "
                f"the warmup baseline {base:.0f} — recompile or unbooked "
                f"collective")
        return None


class MFUDropDetector(Detector):
    """Utilization collapse: MFU under ``frac`` × the run's rolling peak
    for ``patience`` consecutive iterations (one slow step is the spike
    detector's job; a sustained drop is a different failure)."""

    metric = "mfu"
    kind = "mfu_drop"

    def __init__(self, frac: float = 0.5, warmup: int = 5,
                 patience: int = 3, window: int = 100):
        self.frac = float(frac)
        self.warmup = int(warmup)
        self.patience = int(patience)
        self._peaks = deque(maxlen=int(window))
        self._low = 0

    def update(self, value, iteration):
        if value is None or not _finite(value):
            return None
        v = float(value)
        peak = max(self._peaks) if self._peaks else 0.0
        self._peaks.append(v)
        if len(self._peaks) <= self.warmup or peak <= 0:
            return None
        if v < self.frac * peak:
            self._low += 1
            if self._low >= self.patience:
                self._low = 0
                return self._finding(
                    iteration, v, round(peak, 4),
                    f"MFU {v:.3f} below {self.frac:.0%} of rolling peak "
                    f"{peak:.3f} for {self.patience} consecutive steps")
        else:
            self._low = 0
        return None


def default_detectors() -> List[Detector]:
    return [StepTimeSpikeDetector(), LossAnomalyDetector(),
            CommBytesDriftDetector(), MFUDropDetector()]


class HealthMonitor:
    """Trainer extension running the detector battery every iteration.

    Metric sourcing (all host-side values other code already produced —
    the monitor forces **no** extra device syncs):

    * ``step_time_s`` — the updater's phase stamps plus the previous
      extension pass (same accounting as StepBreakdownReport);
    * ``loss`` — ``trainer.observation[loss_key]`` *when it is already a
      host scalar or* ``sync_loss=True`` (default: True — one scalar
      readback per check; set ``loss_every > 1`` to amortize on TPU);
    * ``comm_bytes`` — the accountant's per-step report;
    * ``mfu`` — ``trainer.observation["perf/mfu"]`` when the
      StepBreakdownReport publishes it.

    Every finding becomes a trace instant event (``anomaly/<kind>``, so
    it lands on the merged cross-rank timeline at the exact step), one
    structured JSON log line on stderr
    (``[chainermn_tpu health] {...}``), and an ``escalate(finding)``
    call.  Escalation policy is the caller's: the default is log-only;
    pass e.g. ``escalate=lambda f: os._exit(44)`` for fail-fast gangs, or
    a checkpoint-then-abort closure.

    Priority 340: after StepBreakdownReport (350) has written the
    breakdown keys, before the ObservationAggregator (300) replaces the
    observation with rank means — the monitor judges THIS rank's local
    values, which is what makes a single slow rank detectable at all.
    """

    trigger = (1, "iteration")
    priority = 340

    def __init__(self, detectors: Optional[List[Detector]] = None,
                 escalate: Optional[Callable[[Dict[str, Any]], None]] = None,
                 loss_key: str = "main/loss", sync_loss: bool = True,
                 loss_every: int = 1, max_findings: int = 1000,
                 log_stream=None):
        self.detectors = (default_detectors() if detectors is None
                          else list(detectors))
        self.escalate = escalate
        self.loss_key = loss_key
        self.sync_loss = bool(sync_loss)
        self.loss_every = max(int(loss_every), 1)
        self.max_findings = int(max_findings)
        self.findings: List[Dict[str, Any]] = []
        self.counts: Dict[str, int] = {}
        self._dropped = 0
        self._log = log_stream  # None → sys.stderr at call time (testable)

    # -- metric assembly --
    def _metrics(self, trainer) -> Dict[str, Optional[float]]:
        updater = trainer.updater
        phases = getattr(updater, "phase_times", None) or {}
        step_t = sum(phases.values()) or None
        ext_t = getattr(trainer, "last_extension_time", None)
        if step_t is not None and ext_t is not None:
            step_t += ext_t
        loss = None
        if self.loss_key in trainer.observation \
                and trainer.iteration % self.loss_every == 0:
            raw = trainer.observation[self.loss_key]
            if isinstance(raw, (int, float)):
                loss = float(raw)
            elif self.sync_loss:
                try:
                    loss = float(raw)  # device scalar readback
                except (TypeError, ValueError):
                    loss = None
        rep = get_accountant().last_step_report
        comm_bytes = float(rep["bytes"]) if rep is not None else None
        mfu = trainer.observation.get("perf/mfu")
        mfu = float(mfu) if isinstance(mfu, (int, float)) else None
        return {"step_time_s": step_t, "loss": loss,
                "comm_bytes": comm_bytes, "mfu": mfu}

    # -- extension surface --
    def observe(self, trainer) -> None:
        metrics = self._metrics(trainer)
        it = trainer.iteration
        for det in self.detectors:
            finding = det.update(metrics.get(det.metric), it)
            if finding is not None:
                self._emit(finding)

    def __call__(self, trainer) -> None:
        pass

    # -- finding fan-out --
    def _emit(self, finding: Dict[str, Any]) -> None:
        self.counts[finding["kind"]] = self.counts.get(finding["kind"], 0) + 1
        if len(self.findings) < self.max_findings:
            self.findings.append(finding)
        else:
            self._dropped += 1
        tr = trace.get_tracer()
        tr.instant(f"anomaly/{finding['kind']}", cat="anomaly",
                   **{k: v for k, v in finding.items() if k != "kind"})
        from . import flight as _flight
        _flight.note("anomaly", finding_kind=finding["kind"],
                     **{k: v for k, v in finding.items() if k != "kind"})
        line = dict(finding, ts=round(time.time(), 3))
        print(f"[chainermn_tpu health] {json.dumps(line, sort_keys=True)}",
              file=self._log or sys.stderr, flush=True)
        if self.escalate is not None:
            try:
                self.escalate(finding)
            except Exception as e:  # escalation must not kill detection
                print(f"[chainermn_tpu health] escalation callback failed: "
                      f"{e!r}", file=self._log or sys.stderr, flush=True)

    def health(self) -> Dict[str, Any]:
        """Monitor's contribution to ``export.health_snapshot``."""
        return {"counts": dict(self.counts),
                "findings": list(self.findings[-50:]),
                "findings_dropped": self._dropped}

    # resume contract: detectors re-warm after a resume; counts persist
    def state_dict(self) -> dict:
        return {"counts": dict(self.counts)}

    def load_state_dict(self, state: dict) -> None:
        self.counts = dict(state.get("counts", {}))
