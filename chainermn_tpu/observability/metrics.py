"""Derived metrics: step-time breakdown, throughput, MFU gauges.

The trainer/updater stamp raw phase times (``updaters.StandardUpdater``
→ ``phase_times``; ``Trainer`` → ``last_extension_time``/``last_phase``)
and the comm accountant produces per-step byte/call reports; this module
turns them into observation entries that ride the normal reporting path —
:class:`~chainermn_tpu.extensions.ObservationAggregator` rank-means them,
``LogReport`` folds them into epoch means, and the ``Watchdog`` heartbeat
can name the last completed phase when a rank stalls.
"""

from __future__ import annotations

from typing import Optional

from . import trace
from .comm import get_accountant

# Peak dense bf16 FLOP/s per chip by TPU generation (public spec sheets).
# Matched by substring against jax.devices()[0].device_kind (lowercased).
# Single source of truth — bench.py and the breakdown extension both read
# this table.
PEAK_BF16_FLOPS = [
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]

# HBM bandwidth (bytes/s) per chip by TPU generation (public spec sheets).
HBM_BYTES_PER_S = [
    ("v6e", 1.64e12),
    ("trillium", 1.64e12),
    ("v5p", 2.765e12),
    ("v5e", 8.19e11),
    ("v5 lite", 8.19e11),
    ("v4", 1.228e12),
    ("v3", 9.0e11),
    ("v2", 7.0e11),
]


def peak_flops_for(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None  # CPU / unknown: MFU not meaningful


def hbm_bw_for(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for key, bw in HBM_BYTES_PER_S:
        if key in kind:
            return bw
    return None


class StepBreakdownReport:
    """Trainer extension publishing the step-time breakdown.

    Observation keys written every iteration (when the sources exist):

    * ``time/data``, ``time/compute`` — the updater's phase stamps
      (batch fetch+convert+upload vs. jitted-step call).  JAX dispatch
      is asynchronous, so host-side "compute" is dispatch time; the
      on-device tail of the step surfaces wherever the first sync
      happens (usually ``time/extensions``).  The per-iteration total
      across all phases is accurate wall clock.
    * ``time/extensions`` — the PREVIOUS iteration's extension pass
      (this extension runs inside the current pass, which has not
      finished yet).
    * ``time/comm``, ``comm/bytes``, ``comm/calls`` — the accountant's
      per-step report: host latency of eager collectives plus the byte/
      call profile of the collectives compiled into the step program.
    * ``throughput/items_per_sec`` — from the updater's observed batch
      size (override with ``items_per_step``); also published as a
      tracer gauge.
    * ``perf/mfu`` — when ``flops_per_item`` is given and the device's
      peak is known (or ``peak_flops`` is passed explicitly).

    All keys go through ``trainer.observation``, so with an
    ``ObservationAggregator`` registered ahead of ``LogReport`` the
    logged values are rank means — a straggling rank shows up as an
    inflated mean ``time/compute``, and the per-rank trace tells which.
    """

    trigger = (1, "iteration")
    # Above PRIORITY_EDITOR (300): the keys must land in the observation
    # BEFORE an ObservationAggregator replaces it with rank means —
    # that ordering is what makes the logged breakdown a cross-rank
    # mean.  Below the Watchdog (10k).
    priority = 350

    def __init__(self, items_per_step: Optional[int] = None,
                 flops_per_item: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        self.items_per_step = items_per_step
        self.flops_per_item = flops_per_item
        self._peak = peak_flops
        self._peak_resolved = peak_flops is not None

    def _peak_flops(self) -> Optional[float]:
        if not self._peak_resolved:
            self._peak_resolved = True
            try:
                import jax
                self._peak = peak_flops_for(jax.devices()[0].device_kind)
            except Exception:
                self._peak = None
        return self._peak

    def observe(self, trainer) -> None:
        obs = trainer.observation
        updater = trainer.updater
        phases = getattr(updater, "phase_times", None)
        total = 0.0
        if phases:
            for phase, dt in phases.items():
                obs[f"time/{phase}"] = dt
                total += dt
        ext_t = getattr(trainer, "last_extension_time", None)
        if ext_t is not None:
            obs["time/extensions"] = ext_t
            total += ext_t
        rep = get_accountant().last_step_report
        if rep is not None:
            obs["comm/bytes"] = rep["bytes"]
            obs["comm/calls"] = rep["calls"]
            obs["time/comm"] = rep["host_time_s"]
        items = self.items_per_step or getattr(updater, "last_batch_size",
                                               None)
        tr = trace.get_tracer()
        if items and total > 0:
            ips = items / total
            obs["throughput/items_per_sec"] = ips
            tr.set_gauge("throughput/items_per_sec", ips)
            if self.flops_per_item:
                peak = self._peak_flops()
                if peak:
                    mfu = self.flops_per_item * ips / peak
                    obs["perf/mfu"] = mfu
                    tr.set_gauge("perf/mfu", mfu)

    def __call__(self, trainer) -> None:
        pass

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass
