"""In-jit collective face — what the hot path uses.

The reference's hot loop calls eager NCCL collectives between autograd and
the optimizer (SURVEY.md §3.2).  TPU-native, the entire training step is ONE
compiled SPMD program, and collectives are `jax.lax` ops *inside* it that
XLA lowers onto ICI and schedules/overlaps itself — this module is the thin,
named wrapper layer so framework code and user code share one vocabulary
with the eager face (`communicators/`).

All functions take `axis_name` (default ``"mn"``) and must be called inside
a `shard_map`/`pmap` context where that axis is bound.  `pmean_if_bound`
(the gradient-sync primitive) degrades to identity when the axis is not
bound, which lets the same optimizer wrapper run unmodified under
(a) shard_map SPMD, (b) plain pjit (where XLA inserts gradient reductions
automatically from shardings), and (c) single-device tests.
"""

from __future__ import annotations

from typing import Optional

import jax

from .._compat import axis_size as _axis_size_compat
from .._compat import pcast_varying as _pcast_varying
from ..observability.comm import collective as _acc
from ..topology import DEFAULT_AXIS_NAME


#: Ledger-op → jaxpr collective primitive: which equation each wrapper's
#: wire leg lowers to.  This is the join key of the static↔dynamic
#: reconciliation (``analysis/shardflow.py``): the runtime comm ledger is
#: keyed by WRAPPER name (``reduce_scatter@mn``), the traced program by
#: PRIMITIVE name (``psum_scatter`` / this jax's ``reduce_scatter``), and
#: several wrappers share one primitive (``psum``/``pmean``/the autodiff
#: grad note all land on ``psum``), so reconciliation happens per
#: primitive group.  ``None`` marks a COMPOSITE op whose wire legs are a
#: hand-written schedule (the quantized int8 ring: ppermute/psum pairs at
#: the wire dtype plus fp32 scales) — its cost comes from
#: :func:`quantized_ring_cost`, not from a single equation.  Kept as a
#: literal so the jax-free analysis registry can read it by parsing.
LEDGER_TO_PRIMITIVE = {
    "psum": "psum",
    "pmean": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "pmean_if_bound": "psum",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "psum_scatter",
    "ppermute": "ppermute",
    "shift": "ppermute",
    "bcast": "all_gather",
    "hierarchical_pmean": "psum",
    "quantized_ring_pmean": None,
    # comm.note() declarations used by the shipped builders (train.py):
    # the autodiff-inserted cross-rank gradient psum.
    "grad_allreduce_ad": "psum",
}


def collective_wire_cost(primitive: str, payload_bytes: int,
                         axis_size: int) -> dict:
    """Physical wire cost of ONE collective equation on a ring schedule:
    ``{"wire_bytes": per-rank bytes on the wire, "messages": per-rank
    message count}``.

    ``payload_bytes`` follows the LEDGER convention (the input payload of
    the call — ``observability.comm.payload_info``); this function maps
    it to the ring decomposition every textbook (and XLA's default ICI
    schedule) uses: an all-reduce is reduce-scatter + all-gather, each
    moving ``(P-1)/P`` of the payload over ``P-1`` hops.  At axis size 1
    everything is free.  Used by the shard-flow cost model and the bench
    wire-byte gate — one formula, not two.
    """
    p = int(axis_size)
    if p <= 1:
        return {"wire_bytes": 0, "messages": 0}
    b = int(payload_bytes)
    if primitive in ("psum", "pmax", "pmin"):            # all-reduce
        return {"wire_bytes": 2 * b * (p - 1) // p, "messages": 2 * (p - 1)}
    if primitive in ("psum_scatter", "reduce_scatter"):  # reduce-scatter
        return {"wire_bytes": b * (p - 1) // p, "messages": p - 1}
    if primitive == "all_gather":   # payload = the PER-RANK input block
        return {"wire_bytes": b * (p - 1), "messages": p - 1}
    if primitive == "all_to_all":
        return {"wire_bytes": b * (p - 1) // p, "messages": p - 1}
    if primitive in ("ppermute", "pshuffle"):
        return {"wire_bytes": b, "messages": 1}
    return {"wire_bytes": b, "messages": 1}  # unknown: conservative


def quantized_ring_cost(n_elements: int, axis_size: int,
                        wire_dtype="int8") -> dict:
    """Analytic wire cost of :func:`quantized_ring_pmean` — the composite
    op ``LEDGER_TO_PRIMITIVE`` maps to ``None``.

    Returns ``{"ledger_bytes", "wire_bytes", "scale_bytes", "messages"}``
    per rank: ``ledger_bytes`` is what the accountant books for the call
    (``n_elements × itemsize(wire_dtype)`` — the documented compressed-
    wire convention), ``wire_bytes`` the physical payload hops (the
    reduce-scatter phase re-quantizes and forwards one ``N/P`` chunk per
    hop for ``P-1`` hops, the all-gather phase is one psum of a one-hot
    ``N``-row buffer), and ``scale_bytes`` the fp32 per-chunk scales that
    ride alongside — the dtype-dependent padding the reconciliation
    contract tolerates (docs/ANALYSIS.md).
    """
    p = int(axis_size)
    item = _as_wire_itemsize(wire_dtype)
    n = int(n_elements)
    if p <= 1:
        return {"ledger_bytes": 0, "wire_bytes": 0, "scale_bytes": 0,
                "messages": 0}
    chunk = -(-n // p)  # padded chunk length
    rs_bytes = (p - 1) * chunk * item
    ag_bytes = 2 * (p * chunk * item) * (p - 1) // p  # psum of one-hot buffer
    scales = (p - 1) * 4 + 2 * (p * 4) * (p - 1) // p
    return {
        "ledger_bytes": n * item,
        "wire_bytes": rs_bytes + ag_bytes,
        "scale_bytes": scales,
        # the FULL physical schedule, scale traffic included: the RS
        # phase sends 2 ppermutes per hop (q + scale) over p-1 hops, the
        # AG phase is TWO ring all-reduces (psum of buf_q and of buf_s)
        # at 2(p-1) messages each — 6(p-1) total
        "messages": 2 * (p - 1) + 2 * (2 * (p - 1)),
    }


def _as_wire_itemsize(wire_dtype) -> int:
    # one dtype-coercion fallback for the whole codebase: the
    # accountant's (np.dtype, else getattr(jnp, name)) rule
    from ..observability.comm import _as_dtype

    return _as_dtype(wire_dtype).itemsize


def _axis_bound(axis_name) -> bool:
    """True when `axis_name` (a name or tuple of names) is bound in the
    current trace.

    Only the unbound-axis error (NameError in current JAX) means "not SPMD";
    anything else propagates — silently treating an unexpected failure as
    unbound would turn gradient averaging into identity and corrupt training.
    """
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    try:
        for name in names:
            jax.lax.axis_index(name)
        return True
    except NameError:
        return False


def zeros_like_vma(x, dtype=None, shape=None):
    """Zeros carrying ``x``'s varying-mesh-axes type.

    Inside ``shard_map``, ``lax.scan`` demands carry-in/out types agree,
    so accumulators must be *varying* like the data they will absorb — but
    deriving them as ``x * 0`` would turn a single inf/NaN in ``x`` into an
    all-NaN accumulator.  This builds honest zeros and pcasts them to
    ``x``'s vma set instead.
    """
    import jax.numpy as jnp

    z = jnp.zeros(x.shape if shape is None else shape,
                  x.dtype if dtype is None else dtype)
    vma = getattr(getattr(x, "aval", None), "vma", None)
    if vma:
        z = _pcast_varying(z, tuple(vma))
    return z


# Every public collective routes through the observability accounting
# (`observability.comm.collective`): op name, axis, payload bytes and wire
# dtype are booked per call — once per trace for in-jit calls, with host
# latency for eager ones.  With tracing disabled the wrapper is a single
# attribute read before dispatching to `jax.lax`.

def psum(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("psum", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.psum(v, axis_name), x))


def pmean(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmean", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmean(v, axis_name), x))


def pmax(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmax", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmax(v, axis_name), x))


def pmin(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmin", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmin(v, axis_name), x))


def pmean_if_bound(x, axis_name: Optional[str] = DEFAULT_AXIS_NAME):
    """Mean across the axis if it is bound; identity otherwise.

    This is the gradient-sync primitive of `create_multi_node_optimizer`:
    under shard_map it is a real ICI all-reduce; under pjit-with-shardings
    the axis is unbound and XLA's sharding propagation already produced
    globally-correct mean gradients, so identity is exactly right.
    """
    if axis_name is None or not _axis_bound(axis_name):
        return x
    return pmean(x, axis_name)


def all_gather(x, axis_name: str = DEFAULT_AXIS_NAME, axis: int = 0, tiled: bool = True):
    return _acc("all_gather", axis_name, x, lambda: jax.lax.all_gather(
        x, axis_name, axis=axis, tiled=tiled))


def all_to_all(x, axis_name: str = DEFAULT_AXIS_NAME, split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True):
    return _acc("all_to_all", axis_name, x, lambda: jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled))


def reduce_scatter(x, axis_name: str = DEFAULT_AXIS_NAME, scatter_axis: int = 0):
    return _acc("reduce_scatter", axis_name, x, lambda: jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_axis, tiled=True))


def ppermute(x, perm, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("ppermute", axis_name, x, lambda: jax.lax.ppermute(
        x, axis_name, perm=perm))


def shift(x, offset: int, axis_name: str = DEFAULT_AXIS_NAME, size: Optional[int] = None):
    """Ring shift by `offset` (the ring-attention / pipeline building block)."""
    if size is None:
        size = _axis_size_compat(axis_name)
    perm = [(i, (i + offset) % size) for i in range(size)]
    return _acc("shift", axis_name, x, lambda: jax.lax.ppermute(
        x, axis_name, perm=perm))


def axis_index(axis_name: str = DEFAULT_AXIS_NAME):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name: str = DEFAULT_AXIS_NAME) -> int:
    return _axis_size_compat(axis_name)


def bcast(x, root: int = 0, axis_name: str = DEFAULT_AXIS_NAME):
    """Every rank gets rank `root`'s block (in-jit broadcast)."""
    def one(v):
        g = jax.lax.all_gather(v, axis_name, axis=0, tiled=False)
        return g[root]
    return _acc("bcast", axis_name, x,
                lambda: jax.tree_util.tree_map(one, x))


def quantized_ring_pmean(x, axis_name: str = DEFAULT_AXIS_NAME,
                         wire_dtype="int8"):
    """Cross-rank mean with **int8 wire traffic**: a hand-scheduled ring
    all-reduce (reduce-scatter + all-gather over ``ppermute``) where every
    hop carries ``wire_dtype`` payloads plus one fp32 scale per chunk.

    Beyond the reference's fp16 ``allreduce_grad_dtype`` (its best was 2
    bytes/element; this is ~1): the EQuARX recipe (PAPERS.md) — block
    quantization with requantization at each reduce-scatter hop, a single
    quantization for the all-gather phase.  Deterministic symmetric
    quantization: ``q = round(v * 127 / max|v|)``, error per hop ≤
    ``max|v|/254``, compounding over ``P-1`` hops — use for gradients (noise-
    tolerant), not for activations.

    Call inside ``shard_map`` with ``axis_name`` bound.  Works per-leaf on a
    pytree.  Chunk layout pads ``x`` to a multiple of the axis size.
    """
    import jax.numpy as jnp

    p = _axis_size_compat(axis_name)
    if p == 1:
        return x
    wire = jnp.dtype(wire_dtype)
    if not jnp.issubdtype(wire, jnp.integer):
        raise ValueError(f"wire_dtype must be an integer type, got {wire}")
    qmax = float(jnp.iinfo(wire).max)  # symmetric: use [-qmax, qmax]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def quant(v):
        scale = jnp.maximum(jnp.max(jnp.abs(v)), 1e-30) / qmax
        q = jnp.clip(jnp.round(v / scale), -qmax, qmax).astype(wire)
        return q, scale.astype(jnp.float32)

    def one(leaf):
        flat = leaf.ravel().astype(jnp.float32)
        n = flat.shape[0]
        flat = jnp.pad(flat, (0, (-n) % p))
        chunks = flat.reshape(p, -1)

        # Reduce-scatter: at step s rank i forwards its running sum for
        # chunk (i - s) mod p; after P-1 hops rank i holds the full sum of
        # chunk (i + 1) mod p.  Each hop re-quantizes the running sum.
        send = jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)
        for s in range(p - 1):
            q, scale = quant(send)
            q = jax.lax.ppermute(q, axis_name, perm=perm)
            scale = jax.lax.ppermute(scale, axis_name, perm=perm)
            c = jnp.mod(idx - s - 1, p)
            send = (q.astype(jnp.float32) * scale
                    + jax.lax.dynamic_index_in_dim(chunks, c, 0,
                                                   keepdims=False))

        # All-gather phase: ONE quantization, then a psum of a one-hot row
        # buffer (rank r contributes its finished chunk at row r, zeros
        # elsewhere).  Every element has exactly ONE nonzero contributor, so
        # the int8 sum cannot overflow, the wire stays ~1 byte/element, and
        # — unlike ``all_gather`` or a ppermute gather ring, whose outputs
        # the shard_map VMA checker types as axis-varying — a psum is
        # provably replication-invariant, so the result can flow to
        # ``out_specs=P()`` (replicated params) without extra collectives.
        q, scale = quant(send)
        buf_q = jnp.zeros((p,) + q.shape, q.dtype)
        buf_q = jax.lax.dynamic_update_index_in_dim(buf_q, q, idx, axis=0)
        buf_s = jnp.zeros((p,), jnp.float32)
        buf_s = jax.lax.dynamic_update_index_in_dim(buf_s, scale, idx, axis=0)
        gq = jax.lax.psum(buf_q, axis_name)
        gs = jax.lax.psum(buf_s, axis_name)
        # Rank r finished chunk (r+1) mod p, so row r holds chunk (r+1);
        # rolling down one row puts chunk c at row c.
        deq = jnp.roll(gq.astype(jnp.float32) * gs[:, None], 1, axis=0)

        flat_out = deq.ravel()[:n] / p
        return flat_out.reshape(leaf.shape).astype(leaf.dtype)

    # Accounted at the WIRE dtype: the whole point of this op is that the
    # ring hops carry int8, so the byte ledger reflects ~1 byte/element,
    # not x's fp32 logical payload.
    return _acc("quantized_ring_pmean", axis_name, x,
                lambda: jax.tree_util.tree_map(one, x), wire_dtype=wire)


def hierarchical_pmean(x, chip_axis: str = "chip", slice_axis: str = "slice",
                       dcn_dtype=None):
    """Two-tier mean over a ``('slice', 'chip')`` multislice mesh.

    Reference analog: ``HierarchicalCommunicator`` [uv] (SURVEY.md §2.1) —
    reduce on the fast fabric first (intra-node NCCL), cross the slow one
    once (inter-node MPI).  TPU: mean over ``chip_axis`` rides ICI inside
    each slice; the already-reduced value then crosses DCN exactly once via
    the ``slice_axis`` mean.  The decomposition mean = mean_slice(mean_chip)
    is exact (equal slice sizes by mesh construction).

    ``dcn_dtype`` (e.g. ``'bfloat16'``) compresses ONLY the DCN leg — the
    two-tier version of the reference's fp16 allreduce: ICI is fast enough
    for fp32, the cross-slice hop is the bottleneck worth halving.

    Mesh recipe: ``topology.make_multislice_mesh()``; call this under
    ``shard_map`` with both axes bound (in place of the flat gradient
    pmean).  :func:`chainermn_tpu.optimizers.hierarchical_gradient_average`
    packages it as an optax transform.
    """
    import jax.numpy as jnp

    def one(v):
        local = jax.lax.pmean(v, chip_axis)           # ICI, within slice
        if dcn_dtype is not None:
            wire = jnp.dtype(dcn_dtype)
            return jax.lax.pmean(local.astype(wire), slice_axis).astype(v.dtype)
        return jax.lax.pmean(local, slice_axis)       # DCN, once
    return _acc("hierarchical_pmean", (chip_axis, slice_axis), x,
                lambda: jax.tree_util.tree_map(one, x),
                wire_dtype=dcn_dtype)
