"""In-jit collective face — what the hot path uses.

The reference's hot loop calls eager NCCL collectives between autograd and
the optimizer (SURVEY.md §3.2).  TPU-native, the entire training step is ONE
compiled SPMD program, and collectives are `jax.lax` ops *inside* it that
XLA lowers onto ICI and schedules/overlaps itself — this module is the thin,
named wrapper layer so framework code and user code share one vocabulary
with the eager face (`communicators/`).

All functions take `axis_name` (default ``"mn"``) and must be called inside
a `shard_map`/`pmap` context where that axis is bound.  `pmean_if_bound`
(the gradient-sync primitive) degrades to identity when the axis is not
bound, which lets the same optimizer wrapper run unmodified under
(a) shard_map SPMD, (b) plain pjit (where XLA inserts gradient reductions
automatically from shardings), and (c) single-device tests.
"""

from __future__ import annotations

from typing import Optional

import jax

from .._compat import axis_size as _axis_size_compat
from .._compat import pcast_varying as _pcast_varying
from ..observability.comm import collective as _acc
from ..topology import DEFAULT_AXIS_NAME


#: Ledger-op → jaxpr collective primitive: which equation each wrapper's
#: wire leg lowers to.  This is the join key of the static↔dynamic
#: reconciliation (``analysis/shardflow.py``): the runtime comm ledger is
#: keyed by WRAPPER name (``reduce_scatter@mn``), the traced program by
#: PRIMITIVE name (``psum_scatter`` / this jax's ``reduce_scatter``), and
#: several wrappers share one primitive (``psum``/``pmean``/the autodiff
#: grad note all land on ``psum``), so reconciliation happens per
#: primitive group.  ``None`` marks a COMPOSITE op whose wire legs are a
#: hand-written schedule (the quantized int8 ring: per-hop sub-chunk
#: ppermutes at the wire dtype plus fp32 block scales, then a tiled int8
#: all_gather ring) — its cost comes from :func:`quantized_ring_cost`,
#: its per-equation groups from :func:`quantized_ring_static_groups`
#: (declared as ``composite`` by the owning entry point), never from a
#: single equation.  Kept as a literal so the jax-free analysis registry
#: can read it by parsing.
LEDGER_TO_PRIMITIVE = {
    "psum": "psum",
    "pmean": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "pmean_if_bound": "psum",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter": "psum_scatter",
    "ppermute": "ppermute",
    "shift": "ppermute",
    "bcast": "all_gather",
    "hierarchical_pmean": "psum",
    "quantized_ring_pmean": None,
    # comm.note() declarations used by the shipped builders (train.py):
    # the autodiff-inserted cross-rank gradient psum.
    "grad_allreduce_ad": "psum",
}


def collective_wire_cost(primitive: str, payload_bytes: int,
                         axis_size: int) -> dict:
    """Physical wire cost of ONE collective equation on a ring schedule:
    ``{"wire_bytes": per-rank bytes on the wire, "messages": per-rank
    message count}``.

    ``payload_bytes`` follows the LEDGER convention (the input payload of
    the call — ``observability.comm.payload_info``); this function maps
    it to the ring decomposition every textbook (and XLA's default ICI
    schedule) uses: an all-reduce is reduce-scatter + all-gather, each
    moving ``(P-1)/P`` of the payload over ``P-1`` hops.  At axis size 1
    everything is free.  Used by the shard-flow cost model and the bench
    wire-byte gate — one formula, not two.
    """
    p = int(axis_size)
    if p <= 1:
        return {"wire_bytes": 0, "messages": 0}
    b = int(payload_bytes)
    if primitive in ("psum", "pmax", "pmin"):            # all-reduce
        return {"wire_bytes": 2 * b * (p - 1) // p, "messages": 2 * (p - 1)}
    if primitive in ("psum_scatter", "reduce_scatter"):  # reduce-scatter
        return {"wire_bytes": b * (p - 1) // p, "messages": p - 1}
    if primitive == "all_gather":   # payload = the PER-RANK input block
        return {"wire_bytes": b * (p - 1), "messages": p - 1}
    if primitive == "all_to_all":
        return {"wire_bytes": b * (p - 1) // p, "messages": p - 1}
    if primitive in ("ppermute", "pshuffle"):
        return {"wire_bytes": b, "messages": 1}
    return {"wire_bytes": b, "messages": 1}  # unknown: conservative


#: Default quantization block: ~256 elements per fp32 scale bounds the
#: per-block error at ``blockmax/254`` while keeping scale traffic under
#: 1.6% of the int8 payload (4 bytes per 256).  EQuARX (PAPERS.md) uses
#: the same block ≪ chunk regime.
DEFAULT_QUANT_BLOCK = 256


def _ring_layout(n_elements: int, axis_size: int, block: int,
                 pipeline: int):
    """The ONE chunk/block/sub-chunk layout both the kernel
    (:func:`quantized_ring_pmean`) and the static cost model
    (:func:`quantized_ring_cost`) derive their numbers from — byte-exact
    reconciliation is only possible if padding is decided in one place.

    Returns ``(chunk_len, eff_block, nb_sub, k)``: each rank owns one
    chunk of ``chunk_len = k * nb_sub * eff_block`` elements (``n``
    padded up to ``p * chunk_len``), organized as ``k`` pipeline
    sub-chunks of ``nb_sub`` quantization blocks each.  ``eff_block``
    shrinks to the raw chunk for tiny leaves so a 64-element leaf is not
    padded to 256.
    """
    p = max(1, int(axis_size))
    raw = -(-max(1, int(n_elements)) // p)       # ceil(n / p)
    eff_block = max(1, min(int(block), raw))
    k = max(1, int(pipeline))
    nb_sub = -(-raw // (k * eff_block))          # blocks per sub-chunk
    return k * nb_sub * eff_block, eff_block, nb_sub, k


def quantized_ring_cost(n_elements: int, axis_size: int,
                        wire_dtype="int8",
                        block: int = DEFAULT_QUANT_BLOCK,
                        pipeline: int = 1) -> dict:
    """Analytic wire cost of :func:`quantized_ring_pmean` — the composite
    op ``LEDGER_TO_PRIMITIVE`` maps to ``None``.

    Returns ``{"ledger_bytes", "wire_bytes", "scale_bytes", "messages"}``
    per rank: ``ledger_bytes`` is what the accountant books for the call
    (``n_elements × itemsize(wire_dtype)`` — the documented compressed-
    wire convention), ``wire_bytes`` the physical payload hops, and
    ``scale_bytes`` the fp32 per-BLOCK scales that ride alongside — the
    scale-traffic carve-out of the reconciliation contract
    (docs/ANALYSIS.md).

    The schedule is the MINIMAL ring decomposition: the reduce-scatter
    phase re-quantizes and forwards one ``chunk`` per hop for ``P-1``
    hops (``k`` pipelined sub-chunk messages per hop, fp32 block scales
    bitcast IN-BAND behind each payload — one message, not two), and
    the gather phase is one tiled int8 ``all_gather`` of the packed
    finished chunk — a gather ring at ``(P-1) × (chunk + scales)`` wire
    bytes, replacing the old one-hot-psum phase that paid ``2×`` that
    (its ``ag_bytes = 2·(p·chunk)·(p−1)/p`` accounting is gone with it).
    """
    p = int(axis_size)
    item = _as_wire_itemsize(wire_dtype)
    n = int(n_elements)
    if p <= 1:
        return {"ledger_bytes": 0, "wire_bytes": 0, "scale_bytes": 0,
                "messages": 0}
    chunk, _, nb_sub, k = _ring_layout(n, p, block, pipeline)
    nb = k * nb_sub                              # scale blocks per chunk
    rs_bytes = (p - 1) * chunk * item            # k packed msgs per hop
    ag_bytes = (p - 1) * chunk * item            # tiled all_gather ring
    scales = 2 * (p - 1) * nb * 4                # in-band, both phases
    return {
        "ledger_bytes": n * item,
        "wire_bytes": rs_bytes + ag_bytes,
        "scale_bytes": scales,
        # RS phase: k packed sub-chunk ppermutes per hop over p-1 hops;
        # AG phase: one packed all_gather at p-1 ring messages
        "messages": k * (p - 1) + (p - 1),
    }


def quantized_ring_static_groups(n_elements: int, axis_size: int,
                                 axis_name: str = DEFAULT_AXIS_NAME,
                                 wire_dtype="int8",
                                 block: int = DEFAULT_QUANT_BLOCK,
                                 pipeline: int = 1) -> dict:
    """The quantized ring's traced equations as LEDGER-convention
    ``primitive@axis -> payload bytes`` groups — what
    ``analysis.shardflow.static_costs`` derives from the jaxpr.  A
    declaring entry point (``train.quantized_step``) passes this as its
    ``composite`` declaration so the reconciliation can hold the
    hand-written schedule to the traced program byte-exactly."""
    p = int(axis_size)
    if p <= 1:
        return {}
    item = _as_wire_itemsize(wire_dtype)
    chunk, _, nb_sub, k = _ring_layout(n_elements, p, block, pipeline)
    nb = k * nb_sub
    return {
        # per hop: k packed sub-chunk ppermutes (int8 payload + in-band
        # bitcast scales); payload convention = the call's input bytes
        f"ppermute@{axis_name}": (p - 1) * (chunk * item + nb * 4),
        # gather phase: one tiled all_gather of the packed finished
        # chunk (payload = the per-rank input block incl. scales)
        f"all_gather@{axis_name}": chunk * item + nb * 4,
    }


def choose_pipeline_depth(chunk_bytes: int, bw_bytes_per_s: float = 1.8e11,
                          alpha_s: float = 1e-6,
                          dequant_bytes_per_s: float = 4e11,
                          candidates=(1, 2, 4, 8)) -> int:
    """Pick the pipeline depth ``k`` for :func:`quantized_ring_pmean`
    from the r04 multislice cost-model terms (per-hop latency ``alpha``
    and link bandwidth — v5e ICI defaults, same table as
    ``bench.project_dp_scaling``).

    Model per ring hop with ``k`` sub-chunks: the transfer of sub-chunk
    ``j+1`` overlaps the dequant+accumulate of sub-chunk ``j``, so the
    hop costs ``k·alpha + max(T, D) + min(T, D)/k`` where ``T =
    chunk_bytes/bw`` and ``D = chunk_bytes/dequant_bw`` — deeper
    pipelines hide more of the smaller term but pay one ``alpha`` per
    extra message.  Tiny chunks pick ``k=1``; multi-MB chunks pick the
    deepest candidate that still amortizes its alphas."""
    chunk_bytes = max(0, int(chunk_bytes))
    t = chunk_bytes / float(bw_bytes_per_s)
    d = chunk_bytes / float(dequant_bytes_per_s)

    def hop_cost(k):
        return k * float(alpha_s) + max(t, d) + min(t, d) / k

    return min(candidates, key=hop_cost)


def block_quantize(v, wire_dtype="int8", block: int = DEFAULT_QUANT_BLOCK):
    """Symmetric per-BLOCK quantization: ``(q, scales)`` where ``v``
    (any shape) is flattened, zero-padded to a multiple of the effective
    block, and quantized as ``q = round(v / scale)`` with one fp32
    ``scale = blockmax / qmax`` per block — error ≤ ``blockmax/254`` per
    block for int8.  Pure arithmetic (no wire): the quantizer of the ring
    schedule and of the error-feedback residual, exposed so tests and the
    EF transform share the exact operator."""
    import jax.numpy as jnp

    wire = jnp.dtype(wire_dtype)
    if not jnp.issubdtype(wire, jnp.integer):
        raise ValueError(f"wire_dtype must be an integer type, got {wire}")
    qmax = float(jnp.iinfo(wire).max)
    flat = v.ravel().astype(jnp.float32)
    n = flat.shape[0]
    eff = max(1, min(int(block), n))
    flat = jnp.pad(flat, (0, (-n) % eff))
    vb = flat.reshape(-1, eff)
    scales = jnp.maximum(jnp.max(jnp.abs(vb), axis=-1), 1e-30) / qmax
    q = jnp.clip(jnp.round(vb / scales[:, None]), -qmax, qmax).astype(wire)
    return q, scales.astype(jnp.float32)


def block_dequantize(q, scales, shape=None, n_elements=None):
    """Inverse of :func:`block_quantize`: fp32 values, un-padded to
    ``n_elements`` (or ``prod(shape)``) and reshaped to ``shape``."""
    import jax.numpy as jnp
    import numpy as np

    flat = (q.astype(jnp.float32) * scales[:, None]).ravel()
    if shape is not None and n_elements is None:
        n_elements = int(np.prod(shape)) if shape else 1
    if n_elements is not None:
        flat = flat[:n_elements]
    return flat.reshape(shape) if shape is not None else flat


def _as_wire_itemsize(wire_dtype) -> int:
    # one dtype-coercion fallback for the whole codebase: the
    # accountant's (np.dtype, else getattr(jnp, name)) rule
    from ..observability.comm import _as_dtype

    return _as_dtype(wire_dtype).itemsize


def _axis_bound(axis_name) -> bool:
    """True when `axis_name` (a name or tuple of names) is bound in the
    current trace.

    Only the unbound-axis error (NameError in current JAX) means "not SPMD";
    anything else propagates — silently treating an unexpected failure as
    unbound would turn gradient averaging into identity and corrupt training.
    """
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    try:
        for name in names:
            jax.lax.axis_index(name)
        return True
    except NameError:
        return False


def zeros_like_vma(x, dtype=None, shape=None):
    """Zeros carrying ``x``'s varying-mesh-axes type.

    Inside ``shard_map``, ``lax.scan`` demands carry-in/out types agree,
    so accumulators must be *varying* like the data they will absorb — but
    deriving them as ``x * 0`` would turn a single inf/NaN in ``x`` into an
    all-NaN accumulator.  This builds honest zeros and pcasts them to
    ``x``'s vma set instead.
    """
    import jax.numpy as jnp

    z = jnp.zeros(x.shape if shape is None else shape,
                  x.dtype if dtype is None else dtype)
    vma = getattr(getattr(x, "aval", None), "vma", None)
    if vma:
        z = _pcast_varying(z, tuple(vma))
    return z


# Every public collective routes through the observability accounting
# (`observability.comm.collective`): op name, axis, payload bytes and wire
# dtype are booked per call — once per trace for in-jit calls, with host
# latency for eager ones.  With tracing disabled the wrapper is a single
# attribute read before dispatching to `jax.lax`.

def psum(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("psum", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.psum(v, axis_name), x))


def pmean(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmean", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmean(v, axis_name), x))


def pmax(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmax", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmax(v, axis_name), x))


def pmin(x, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("pmin", axis_name, x, lambda: jax.tree_util.tree_map(
        lambda v: jax.lax.pmin(v, axis_name), x))


def pmean_if_bound(x, axis_name: Optional[str] = DEFAULT_AXIS_NAME):
    """Mean across the axis if it is bound; identity otherwise.

    This is the gradient-sync primitive of `create_multi_node_optimizer`:
    under shard_map it is a real ICI all-reduce; under pjit-with-shardings
    the axis is unbound and XLA's sharding propagation already produced
    globally-correct mean gradients, so identity is exactly right.
    """
    if axis_name is None or not _axis_bound(axis_name):
        return x
    return pmean(x, axis_name)


def all_gather(x, axis_name: str = DEFAULT_AXIS_NAME, axis: int = 0, tiled: bool = True):
    return _acc("all_gather", axis_name, x, lambda: jax.lax.all_gather(
        x, axis_name, axis=axis, tiled=tiled))


def all_to_all(x, axis_name: str = DEFAULT_AXIS_NAME, split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True):
    return _acc("all_to_all", axis_name, x, lambda: jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled))


def reduce_scatter(x, axis_name: str = DEFAULT_AXIS_NAME, scatter_axis: int = 0):
    return _acc("reduce_scatter", axis_name, x, lambda: jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_axis, tiled=True))


def ppermute(x, perm, axis_name: str = DEFAULT_AXIS_NAME):
    return _acc("ppermute", axis_name, x, lambda: jax.lax.ppermute(
        x, axis_name, perm=perm))


def shift(x, offset: int, axis_name: str = DEFAULT_AXIS_NAME, size: Optional[int] = None):
    """Ring shift by `offset` (the ring-attention / pipeline building block)."""
    if size is None:
        size = _axis_size_compat(axis_name)
    perm = [(i, (i + offset) % size) for i in range(size)]
    return _acc("shift", axis_name, x, lambda: jax.lax.ppermute(
        x, axis_name, perm=perm))


def axis_index(axis_name: str = DEFAULT_AXIS_NAME):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name: str = DEFAULT_AXIS_NAME) -> int:
    return _axis_size_compat(axis_name)


def bcast(x, root: int = 0, axis_name: str = DEFAULT_AXIS_NAME):
    """Every rank gets rank `root`'s block (in-jit broadcast)."""
    def one(v):
        g = jax.lax.all_gather(v, axis_name, axis=0, tiled=False)
        return g[root]
    return _acc("bcast", axis_name, x,
                lambda: jax.tree_util.tree_map(one, x))


def quantized_ring_pmean(x, axis_name: str = DEFAULT_AXIS_NAME,
                         wire_dtype="int8",
                         block: int = DEFAULT_QUANT_BLOCK,
                         pipeline: int = 1):
    """Cross-rank mean with **block-scaled int8 wire traffic**: a
    hand-scheduled ring all-reduce where every hop carries ``wire_dtype``
    payloads plus one fp32 scale per ``block`` elements.

    Beyond the reference's fp16 ``allreduce_grad_dtype`` (its best was 2
    bytes/element; this is ~1): the EQuARX recipe (PAPERS.md, arxiv
    2506.17615) —

    * **block scales** — one fp32 scale per ``block`` elements (default
      256, shrunk to the chunk for tiny leaves) instead of one per
      ``N/P`` chunk: quantization error is bounded per BLOCK
      (``blockmax/254``), so one outlier no longer flattens the whole
      chunk's resolution.
    * **requantization per hop** — each reduce-scatter hop dequantizes
      the incoming running sum, accumulates its own chunk in fp32, and
      requantizes before forwarding (``P-1`` hops).
    * **pipelined sub-chunks** — ``pipeline=k`` splits each chunk into
      ``k`` independent sub-chunk rings (layout from
      :func:`_ring_layout`), so the ppermute of sub-chunk ``j+1`` can
      overlap the dequant+accumulate of sub-chunk ``j`` (XLA's async
      scheduler owns the actual overlap; the schedule merely exposes the
      independence).  :func:`choose_pipeline_depth` picks ``k`` from the
      alpha/bandwidth cost model.
    * **gather ring** — the all-gather phase is one tiled int8
      ``all_gather`` of the packed finished chunk (block scales bitcast
      in-band): the minimal ``(P-1)×chunk`` gather ring, typed
      replication-invariant by the collective itself (the one-hot-psum
      phase it replaces paid ~2× the minimal wire; its only virtue was
      the invariant typing, which ``all_gather`` provides for free).
      The ring's start offset makes rank ``r`` finish its OWN chunk
      ``r``, so the gathered rows concatenate in order — no fix-up
      permutation between the collective and the output.

    Use for gradients (noise-tolerant), not activations.  Call inside
    ``shard_map`` with ``axis_name`` bound.  Works per-leaf on a pytree
    (:func:`chainermn_tpu.optimizers.compressed_mean` buckets a whole
    gradient tree into one flat call).
    """
    import jax.numpy as jnp

    p = _axis_size_compat(axis_name)
    if p == 1:
        return x
    wire = jnp.dtype(wire_dtype)
    if not jnp.issubdtype(wire, jnp.integer):
        raise ValueError(f"wire_dtype must be an integer type, got {wire}")
    qmax = float(jnp.iinfo(wire).max)  # symmetric: use [-qmax, qmax]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def quant_rows(vb):
        # vb: (..., nb, B) -> per-block q + scales
        scale = jnp.maximum(jnp.max(jnp.abs(vb), axis=-1), 1e-30) / qmax
        q = jnp.clip(jnp.round(vb / scale[..., None]),
                     -qmax, qmax).astype(wire)
        return q, scale.astype(jnp.float32)

    def one(leaf):
        flat = leaf.ravel().astype(jnp.float32)
        n = flat.shape[0]
        chunk_len, eff_block, nb_sub, k = _ring_layout(n, p, block, pipeline)
        flat = jnp.pad(flat, (0, p * chunk_len - n))
        # (p, k, nb_sub, B): rank-major chunks, each k sub-chunks of
        # nb_sub quantization blocks
        chunks = flat.reshape(p, k, nb_sub, eff_block)

        # Reduce-scatter: rank i STARTS by forwarding chunk (i-1), so at
        # step s it carries the running sum of chunk (i - 1 - s) mod p
        # and after P-1 hops finishes its OWN chunk i — the gathered
        # rows then concatenate in order with no fix-up permutation (the
        # obvious start-at-own-chunk variant needs a roll after the
        # gather, and XLA's roll+slice simplification MISCOMPILES that
        # on the deployment floor's jax 0.4.37).  Each hop re-quantizes
        # the running sum per block and moves each sub-chunk as its own
        # packed ppermute, so hop s+1's transfers are independent of hop
        # s's dequants.
        # fp32 scales travel IN-BAND, bitcast to the wire dtype behind
        # the payload: ONE wire message per transfer — half the
        # rendezvous/DMA descriptors of a separate scale message, same
        # bytes (quantized_ring_cost's scale_bytes names the in-band
        # scale share)
        ratio = 4 // wire.itemsize  # wire words per fp32 scale

        def pack(q, scale):
            return jnp.concatenate(
                [q.reshape(-1),
                 jax.lax.bitcast_convert_type(scale, wire).reshape(-1)])

        def unpack(msg, nb):
            q = msg[:nb * eff_block].reshape(nb, eff_block)
            raw = msg[nb * eff_block:].reshape(
                (nb, ratio) if ratio > 1 else (nb,))
            return q, jax.lax.bitcast_convert_type(raw, jnp.float32)

        send = jax.lax.dynamic_index_in_dim(chunks, jnp.mod(idx - 1, p),
                                            0, keepdims=False)
        for s in range(p - 1):
            q, scale = quant_rows(send)            # (k, nb_sub, B), (k, nb_sub)
            msgs = [jax.lax.ppermute(pack(q[j], scale[j]), axis_name,
                                     perm=perm)
                    for j in range(k)]
            c = jnp.mod(idx - s - 2, p)
            nxt = jax.lax.dynamic_index_in_dim(chunks, c, 0, keepdims=False)
            parts = []
            for j in range(k):
                qr, sr = unpack(msgs[j], nb_sub)
                parts.append(qr.astype(jnp.float32) * sr[:, None] + nxt[j])
            send = jnp.stack(parts)

        # Gather ring: ONE block quantization of the finished chunk, then
        # a single tiled all_gather of the packed (q + in-band scales)
        # message — (P-1)×(chunk+scales) minimal wire, replication-
        # invariant output by construction (the collective itself is the
        # "replication fix-up": its output is invariant-typed, where a
        # hand-rolled ppermute gather ring would come out axis-varying).
        # tiled=True: the non-tiled form hits an XLA CPU fusion bug on
        # the deployment floor (jax 0.4.37) where the dequant reads the
        # wrong scale block under jit; the tiled lowering is also the
        # layout the reshape below wants directly.
        nb = k * nb_sub
        q, scale = quant_rows(send.reshape(nb, eff_block))
        ga = jax.lax.all_gather(pack(q, scale), axis_name, axis=0,
                                tiled=True).reshape(p, -1)
        gq = ga[:, :nb * eff_block].reshape(p, nb, eff_block)
        raw = ga[:, nb * eff_block:].reshape(
            (p, nb, ratio) if ratio > 1 else (p, nb))
        gs = jax.lax.bitcast_convert_type(raw, jnp.float32)
        # rank r finished chunk r, so the gathered rows ARE the chunks
        # in order — no permutation between gather and output
        full = (gq.astype(jnp.float32) * gs[..., None]).reshape(p, chunk_len)

        flat_out = full.ravel()[:n] / p
        return flat_out.reshape(leaf.shape).astype(leaf.dtype)

    # Accounted at the WIRE dtype: the whole point of this op is that the
    # ring hops carry int8, so the byte ledger reflects ~1 byte/element,
    # not x's fp32 logical payload (block scales are the documented
    # carve-out — quantized_ring_cost's scale_bytes).
    return _acc("quantized_ring_pmean", axis_name, x,
                lambda: jax.tree_util.tree_map(one, x), wire_dtype=wire)


def hierarchical_pmean(x, chip_axis: str = "chip", slice_axis: str = "slice",
                       dcn_dtype=None):
    """Two-tier mean over a ``('slice', 'chip')`` multislice mesh.

    Reference analog: ``HierarchicalCommunicator`` [uv] (SURVEY.md §2.1) —
    reduce on the fast fabric first (intra-node NCCL), cross the slow one
    once (inter-node MPI).  TPU: mean over ``chip_axis`` rides ICI inside
    each slice; the already-reduced value then crosses DCN exactly once via
    the ``slice_axis`` mean.  The decomposition mean = mean_slice(mean_chip)
    is exact (equal slice sizes by mesh construction).

    ``dcn_dtype`` (e.g. ``'bfloat16'``) compresses ONLY the DCN leg — the
    two-tier version of the reference's fp16 allreduce: ICI is fast enough
    for fp32, the cross-slice hop is the bottleneck worth halving.

    Mesh recipe: ``topology.make_multislice_mesh()``; call this under
    ``shard_map`` with both axes bound (in place of the flat gradient
    pmean).  :func:`chainermn_tpu.optimizers.hierarchical_gradient_average`
    packages it as an optax transform.
    """
    import jax.numpy as jnp

    def one(v):
        local = jax.lax.pmean(v, chip_axis)           # ICI, within slice
        if dcn_dtype is not None:
            wire = jnp.dtype(dcn_dtype)
            return jax.lax.pmean(local.astype(wire), slice_axis).astype(v.dtype)
        return jax.lax.pmean(local, slice_axis)       # DCN, once
    return _acc("hierarchical_pmean", (chip_axis, slice_axis), x,
                lambda: jax.tree_util.tree_map(one, x),
                wire_dtype=dcn_dtype)
