"""Fused softmax-cross-entropy over a large vocabulary (Pallas kernels).

The LM loss's last big non-MXU cost: ``logits = h @ table.T`` materializes
a ``(B·S, V)`` fp32 tensor (1 GB at the bench shape) that is written,
re-read for max/exp/sum/pick, and revisited by autodiff.  Same cure as
flash attention — the logits tile never leaves VMEM:

* **Forward** (``_stats_kernel``): grid ``(T/block_t, V/block_v)``, V
  sequential; each step matmuls an ``(block_t, D)×(D, block_v)`` tile on
  the MXU and folds it into online-softmax scratch (running max ``m``,
  rescaled ``sumexp l``, and the target logit picked via a one-hot
  reduction).  Outputs per-row ``(m, l, picked)`` — O(T) memory.
* **Backward** (``_dh_kernel`` / ``_dtable_kernel``): recompute each tile's
  probabilities from the saved LSE (``p = exp(s − lse)`` exactly), fold in
  the one-hot, and accumulate ``dh = ds @ table`` (V-sequential) and
  ``dtable = ds^T @ h`` (T-sequential) in fp32 VMEM scratch — the dQ/dKV
  recipe from ``flash_attention.py`` transplanted to the vocab axis.

Reference relationship: the reference had no LM head at all (SURVEY.md
§2.8); this is the "hand-write the hot kernel" perf identity
(``pure_nccl_communicator.py`` fused CUDA kernels [uv]) applied to the
biggest matmul in the modern stack.

TP composition: the kernels are shard-local.  ``fused_cross_entropy``
serves the single-shard case; the vocab-parallel path in
``parallel.transformer.vocab_parallel_logits_loss(ce_impl='fused')``
combines per-shard ``(m, l, picked)`` with the same pmax/psum legs as its
materializing form, then drives the backward kernels with the GLOBAL lse.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import shape_dtype_struct as _sds
from .._compat import tpu_compiler_params as _tpu_compiler_params

from .flash_attention import _inherit_vma, _pick_aligned_block, _LANES

NEG_INF = -1e30


def _stats_kernel(h_ref, t_ref, tgt_ref, m_ref, l_ref, p_ref,
                  m_acc, l_acc, p_acc, *, block_t, block_v, num_vblocks):
    it, jv = pl.program_id(0), pl.program_id(1)

    @pl.when(jv == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)
        p_acc[...] = jnp.zeros_like(p_acc)

    h = h_ref[...]                                     # (block_t, D)
    tab = t_ref[...]                                   # (block_v, D)
    s = jax.lax.dot_general(
        h, tab, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (block_t, block_v)

    tgt = tgt_ref[0, 0, pl.dslice(it * block_t, block_t)]   # (block_t,)
    local = tgt - jv * block_v
    col = jax.lax.broadcasted_iota(jnp.int32, (block_t, block_v), 1)
    onehot = col == local[:, None]
    p_acc[...] += jnp.broadcast_to(
        jnp.sum(jnp.where(onehot, s, 0.0), axis=1, keepdims=True),
        p_acc.shape)

    m_prev = m_acc[:, :1]
    m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
    l_acc[...] = (l_acc[...] * jnp.exp(m_prev - m_new)
                  + jnp.exp(s - m_new).sum(-1, keepdims=True))
    m_acc[...] = jnp.broadcast_to(m_new, m_acc.shape)

    @pl.when(jv == num_vblocks - 1)
    def _fin():
        m_ref[...] = m_acc[...]
        l_ref[...] = l_acc[...]
        p_ref[...] = p_acc[...]


def _dh_kernel(h_ref, t_ref, tgt_ref, lse_ref, dnll_ref, dh_ref, dh_acc,
               *, block_t, block_v, num_vblocks):
    it, jv = pl.program_id(0), pl.program_id(1)

    @pl.when(jv == 0)
    def _init():
        dh_acc[...] = jnp.zeros_like(dh_acc)

    h = h_ref[...]
    tab = t_ref[...]
    s = jax.lax.dot_general(
        h, tab, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    lse = lse_ref[0, 0, pl.dslice(it * block_t, block_t)]
    dnll = dnll_ref[0, 0, pl.dslice(it * block_t, block_t)]
    p = jnp.exp(s - lse[:, None])
    tgt = tgt_ref[0, 0, pl.dslice(it * block_t, block_t)]
    local = tgt - jv * block_v
    col = jax.lax.broadcasted_iota(jnp.int32, (block_t, block_v), 1)
    ds = (p - jnp.where(col == local[:, None], 1.0, 0.0)) * dnll[:, None]
    dh_acc[...] += jax.lax.dot_general(
        ds.astype(tab.dtype), tab, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jv == num_vblocks - 1)
    def _fin():
        dh_ref[...] = dh_acc[...].astype(dh_ref.dtype)


def _dtable_kernel(t_ref, h_ref, tgt_ref, lse_ref, dnll_ref, dt_ref, dt_acc,
                   *, block_t, block_v, num_tblocks):
    jv, it = pl.program_id(0), pl.program_id(1)

    @pl.when(it == 0)
    def _init():
        dt_acc[...] = jnp.zeros_like(dt_acc)

    h = h_ref[...]
    tab = t_ref[...]
    s = jax.lax.dot_general(
        h, tab, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (block_t, block_v)
    lse = lse_ref[0, 0, pl.dslice(it * block_t, block_t)]
    dnll = dnll_ref[0, 0, pl.dslice(it * block_t, block_t)]
    p = jnp.exp(s - lse[:, None])
    tgt = tgt_ref[0, 0, pl.dslice(it * block_t, block_t)]
    local = tgt - jv * block_v
    col = jax.lax.broadcasted_iota(jnp.int32, (block_t, block_v), 1)
    ds = (p - jnp.where(col == local[:, None], 1.0, 0.0)) * dnll[:, None]
    dt_acc[...] += jax.lax.dot_general(
        ds.astype(h.dtype), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (block_v, D)

    @pl.when(it == num_tblocks - 1)
    def _fin():
        dt_ref[...] = dt_acc[...].astype(dt_ref.dtype)


def _blocks_for(t, v, block_t, block_v):
    bt = _pick_aligned_block(t, block_t)
    bv = _pick_aligned_block(v, block_v)
    return bt, bv


def _vma_emulation(interpret, *xs) -> bool:
    """Interpreted Pallas cannot trace bodies whose operands carry
    varying-mesh-axes (multi-axis shard_map on CPU); those cases run an
    XLA emulation with identical math instead.  Standalone CPU calls (no
    vma) still exercise the real kernels in interpret mode, and TPU always
    compiles them."""
    return interpret and any(
        getattr(getattr(x, "aval", None), "vma", None) for x in xs)


def _stats_xla(h, table, targets):
    logits = jnp.einsum("td,vd->tv", h, table,
                        preferred_element_type=jnp.float32)
    m = logits.max(-1)
    l = jnp.exp(logits - m[:, None]).sum(-1)
    v = table.shape[0]
    onehot = (targets[:, None] == jnp.arange(v)[None, :])
    p = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)
    return m, l, p


def _grads_xla(h, table, targets, lse, dnll):
    logits = jnp.einsum("td,vd->tv", h, table,
                        preferred_element_type=jnp.float32)
    v = table.shape[0]
    onehot = (targets[:, None] == jnp.arange(v)[None, :]).astype(jnp.float32)
    ds = (jnp.exp(logits - lse[:, None]) - onehot) * dnll[:, None]
    dh = jnp.einsum("tv,vd->td", ds.astype(table.dtype), table,
                    preferred_element_type=jnp.float32).astype(h.dtype)
    dtable = jnp.einsum("tv,td->vd", ds.astype(h.dtype), h,
                        preferred_element_type=jnp.float32).astype(table.dtype)
    return dh, dtable


def ce_stats(h, table, targets, block_t: int = 256, block_v: int = 1024,
             interpret: Optional[bool] = None):
    """Per-row softmax statistics without materializing logits.

    ``h (T, D)``, ``table (V, D)``, ``targets (T,) int32`` →
    ``(m, l, picked)`` each ``(T,)`` fp32: running max, sum of
    ``exp(s − m)``, and the target-column logit.  NOT differentiable —
    use :func:`fused_cross_entropy` (or the vocab-parallel wrapper) for
    gradients.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t, d = h.shape
    v = table.shape[0]
    bt, bv = _blocks_for(t, v, block_t, block_v)
    if not (bt and bv):
        raise ValueError(
            f"T={t}, V={v} admit no Mosaic-aligned blocks ≤ ({block_t}, "
            f"{block_v}); pad T to a multiple of 8")
    if _vma_emulation(interpret, h, table):
        return _stats_xla(h, table, targets)
    vma = _inherit_vma(h, table)
    tgt_row = targets.astype(jnp.int32)[None, None, :]       # (1, 1, T)
    kern = functools.partial(_stats_kernel, block_t=bt, block_v=bv,
                             num_vblocks=v // bv)
    m, l, p = pl.pallas_call(
        kern,
        grid=(t // bt, v // bv),
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bt, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bt, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[_sds((t, _LANES), jnp.float32, vma=vma)
                   for _ in range(3)],
        scratch_shapes=[pltpu.VMEM((bt, _LANES), jnp.float32)
                        for _ in range(3)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(h, table, tgt_row)
    return m[:, 0], l[:, 0], p[:, 0]


def ce_grads(h, table, targets, lse, dnll, block_t: int = 256,
             block_v: int = 1024, interpret: Optional[bool] = None):
    """Backward kernels: ``(dh, dtable)`` for per-row NLL cotangent
    ``dnll (T,)`` given the (possibly globally-combined) ``lse (T,)``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t, d = h.shape
    v = table.shape[0]
    bt, bv = _blocks_for(t, v, block_t, block_v)
    if not (bt and bv):
        raise ValueError(
            f"T={t}, V={v} admit no Mosaic-aligned blocks ≤ ({block_t}, "
            f"{block_v}); pad T to a multiple of 8")
    if _vma_emulation(interpret, h, table):
        return _grads_xla(h, table, targets, lse, dnll)
    vma = _inherit_vma(h, table)
    tgt_row = targets.astype(jnp.int32)[None, None, :]
    lse_row = lse.astype(jnp.float32)[None, None, :]
    dnll_row = dnll.astype(jnp.float32)[None, None, :]

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, block_t=bt, block_v=bv,
                          num_vblocks=v // bv),
        grid=(t // bt, v // bv),
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, d), lambda i, j: (i, 0)),
        out_shape=_sds((t, d), h.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(h, table, tgt_row, lse_row, dnll_row)

    dtable = pl.pallas_call(
        functools.partial(_dtable_kernel, block_t=bt, block_v=bv,
                          num_tblocks=t // bt),
        grid=(v // bv, t // bt),
        in_specs=[
            pl.BlockSpec((bv, d), lambda j, i: (j, 0)),
            pl.BlockSpec((bt, d), lambda j, i: (i, 0)),
            pl.BlockSpec((1, 1, t), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda j, i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bv, d), lambda j, i: (j, 0)),
        out_shape=_sds((v, d), table.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(table, h, tgt_row, lse_row, dnll_row)
    return dh, dtable


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_cross_entropy(h, table, targets, block_t: int = 256,
                        block_v: int = 1024,
                        interpret: Optional[bool] = None):
    """Per-row NLL ``(T,)`` of ``softmax(h @ table.T)`` at ``targets`` —
    O(T) memory, logits tiles live only in VMEM, forward and backward.

    ``h (T, D)`` (flatten batch×sequence first), ``table (V, D)``,
    ``targets (T,) int32``.  Differentiable w.r.t. ``h`` and ``table``.
    Single-shard form; the vocab-parallel composition lives in
    ``parallel.transformer.vocab_parallel_logits_loss``.
    """
    m, l, p = ce_stats(h, table, targets, block_t, block_v, interpret)
    return m + jnp.log(l) - p


def _fce_fwd(h, table, targets, block_t, block_v, interpret):
    m, l, p = ce_stats(h, table, targets, block_t, block_v, interpret)
    lse = m + jnp.log(l)
    return lse - p, (h, table, targets, lse)


def _fce_bwd(block_t, block_v, interpret, res, dnll):
    h, table, targets, lse = res
    dh, dtable = ce_grads(h, table, targets, lse, dnll, block_t, block_v,
                          interpret)
    return dh, dtable, None


fused_cross_entropy.defvjp(_fce_fwd, _fce_bwd)
