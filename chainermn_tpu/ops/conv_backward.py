"""Pallas backward kernels for 3x3/1x1 stride-1 convolutions (TPU).

**Status: a measured NEGATIVE result — opt-in, not the default.**  See
docs/PERF.md "Conv backward: why the Pallas kernels lost".  The kernels
are parity-exact and compile inside the full sharded train step, but lose
to XLA's native conv engine at every ResNet shape (2x at 14x14x256 up to
~30x at 56x56x64; NF-ResNet-50 end-to-end 119.6 vs 40.6 ms/step,
scripts/ab_conv_impl.py).  Two findings worth the price of the experiment:

1. XLA's backward convs already run AT the HBM-roofline floor in
   wall-clock (56x56x64 dgrad: 0.12 ms measured vs 0.126 ms floor).  The
   "1.7-2.6x floor" excess that motivated this module came from XLA's
   ``bytes accessed`` cost analysis, which counts lane-padded logical
   bytes, not HBM traffic — the metric, not the lowering, carried the
   slack.  docs/PERF.md's round-4 "custom kernels worth ~41 -> ~25 ms"
   projection inherited that artifact and is withdrawn there.
2. A shifted-matmul (roll+mask) conv decomposition is VPU-bound on TPU:
   every tap pays ~2 full VMEM passes (rotate + mask/cast) over the
   activation plane, which exceeds the MXU cost of the tap's MACs at
   ResNet channel counts.  XLA's conv engine applies the 9 taps in
   registers while the plane streams once — a thing jnp-level kernel code
   cannot express.  Custom conv kernels on TPU need the conv unit's
   register-level reuse, not data-movement decompositions.

Design notes (kept for the record; the machinery is reused verbatim by
any future windowed kernel):

* ResNet bottleneck planes are small (56x56x64 bf16 = 401 KB ... 7x7x512 =
  50 KB), so a kernel instance holds the ENTIRE spatial extent of a few
  images in VMEM (~16 MB/core) and grids only over batch.  Each X / dY
  element is read from HBM exactly once; accumulation happens on-chip in
  fp32.  HBM traffic = the analytic floor.
* A 3x3/pad-1 conv is 9 shifted matmuls.  Mosaic cannot reshape or
  multi-dim-contract odd-sized slices (55x55 blocks fail layout
  inference), so the shift is done on a FLATTENED spatial axis: inputs
  arrive as (bn, H*W, C) and the tap shift (dh, dw) becomes one
  ``pltpu.roll`` by ``dh*W + dw`` along the second-minor dim, plus an
  iota-derived border mask.  Rolls only support 32-bit data, so the
  rolled operand upcasts to fp32 in VMEM (VPU work, no HBM bytes) and
  drops back to bf16 for the MXU dot:

      dW[kh,kw] = (roll(X) * mask)^T dY            contraction over bn*H*W
      dX       += (roll(dY) * mask) W[kh,kw]^T     9 taps, fp32 scratch

* Forward stays on XLA's conv (measured within ~1.2x of ITS floor);
  ``conv2d`` only swaps the VJP, and falls back to XLA's transpose rule
  for shapes the kernels don't cover — behavior never gates on coverage.

Parity: tests/test_conv_backward.py (interpret mode, any host) and the
real-chip A/B in scripts/ab_conv_impl.py.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import pcast_varying as _pcast_varying
from .._compat import shape_dtype_struct as _sds
from .._compat import tpu_compiler_params as _tpu_compiler_params

__all__ = ["conv2d", "conv3x3_dgrad", "conv3x3_wgrad"]

_VMEM_BUDGET = 5 * 1024 * 1024  # headroom under the 16 MB/core scoped
# limit: the pipeline double-buffers input/output blocks, and Mosaic's
# stack holds the rolled fp32 copy, its border mask and the bf16 cast LIVE
# simultaneously with inputs and the accumulator — so the per-image
# estimates below charge ~16 bytes/pixel for the rolled operand
# (2 in + 4 cast + 4 roll + 4 mask + 2 re-cast), not its nominal 2, and
# the budget is set to ~half of a conservative target.  bn=1 on the 56x56
# stage still gives >3000 contraction rows per dot — MXU-efficient.


def _inherit_vma(*xs) -> frozenset:
    """Union of the inputs' varying-mesh-axes sets — pallas_call inside
    shard_map requires out_shapes to declare how outputs vary (same helper
    as ops/flash_attention.py)."""
    vma = set()
    for x in xs:
        v = getattr(getattr(x, "aval", None), "vma", None)
        if v:
            vma |= set(v)
    return frozenset(vma)


def _promote_vma(x, vma: frozenset):
    """Promote ``x`` to vary over ``vma`` (no-op outside shard_map).

    Interpret mode executes the kernel body as plain jnp under the
    shard_map trace, where a dot between a batch-sharded dy and a
    replicated w fails VMA agreement — promote the lagging operand first
    (compiled Mosaic never sees vma, so this is interpret-only in
    practice but harmless everywhere)."""
    have = getattr(getattr(x, "aval", None), "vma", frozenset()) or frozenset()
    missing = tuple(sorted(set(vma) - set(have)))
    if not missing:
        return x
    return _pcast_varying(x, missing)


def _same_pad(h: int, k: int, s: int) -> Tuple[int, int]:
    """XLA SAME padding (lo, hi) for one spatial dim."""
    out = -(-h // s)
    total = max((out - 1) * s + k - h, 0)
    return total // 2, total - total // 2


def _pick_bn(n: int, per_image_bytes: int, fixed_bytes: int) -> int:
    """Images per grid step: as many as fit the VMEM budget, dividing n."""
    room = max(_VMEM_BUDGET - fixed_bytes, per_image_bytes)
    bn = max(1, min(n, room // per_image_bytes))
    while n % bn:
        bn -= 1
    return bn


def _pad_rows(v, sp):
    """Zero-pad the flattened-spatial dim (axis 1) up to ``sp`` rows inside
    VMEM.  ``tpu.dynamic_rotate`` and leading-dim reshapes need the
    second-minor dim sublane-aligned (multiple of 8); 14x14 planes (196
    rows) are not.  Zero rows are inert in every dot below, and the border
    masks plus prefix stores keep them out of real outputs."""
    if v.shape[1] == sp:
        return v
    z = jnp.zeros((v.shape[0], sp - v.shape[1], v.shape[2]), v.dtype)
    return jnp.concatenate([v, z], axis=1)


def _rolled(v32, ww, dh, dw, flip):
    """Roll ``v32`` (fp32, flattened spatial) by tap shift (dh, dw).

    ``dh``/``dw`` may be traced scalars: the taps run under a fori_loop so
    only ONE tap's roll temporaries are ever live — a Python-unrolled tap
    loop let Mosaic schedule all 9 rolled copies concurrently and blew the
    16 MB scoped-VMEM stack.  The roll lowers to ``tpu.dynamic_rotate``
    either way, so the traced shift costs nothing.  Border masking is the
    caller's job (``_tap_mask``, applied after the bf16 downcast)."""
    rows = v32.shape[1]  # the PADDED extent — rolls wrap at the array edge
    sh = dh * ww + dw
    if flip:
        sh = -sh
    return pltpu.roll(v32, (rows - sh) % rows, 1)  # out[s] = v[s + sh]


def _make_hw(sp, ww):
    """(h, w) plane coordinates of each flattened row, shaped (1, sp, 1).

    Built ONCE per kernel invocation and shared by every tap: full-shape
    per-tap iotas and fp32 masks were the dominant VMEM transients (three
    (bn, sp, C) i32 iotas + an fp32 mask per tap blew the 16 MB scoped
    stack on the 56x56 stage)."""
    s = jax.lax.broadcasted_iota(jnp.int32, (1, sp, 1), 1)
    return s // ww, s % ww


def _tap_mask(h, w, hh, ww, dh, dw, flip, dtype):
    """(1, sp, 1) border mask for tap shift (dh, dw), in the DOT dtype so
    the multiply runs on the bf16 operand after the downcast."""
    if flip:
        dh, dw = -dh, -dw
    cond = ((h + dh >= 0) & (h + dh < hh)
            & (w + dw >= 0) & (w + dw < ww))
    return cond.astype(dtype)


# ---------------------------------------------------------------------------
# wgrad: dW[kh, kw, ci, co] = sum_{n, oh, ow} X[n, oh+dh, ow+dw, ci]
#                                             * dY[n, oh, ow, co]
# ---------------------------------------------------------------------------


def _wgrad_kernel(x_ref, dy_ref, dw_ref, *scratch, hh, ww, k, pad, ni):
    """Grid is (batch-blocks, k*k): ONE tap per grid cell.

    A fori_loop over taps inside one cell left all 9 rolled fp32 copies
    and masked casts live simultaneously (~16.9 MB scoped stack on the
    56x56 stage, over the 16 MB limit).  Grid cells are sequential by
    construction, so per-tap temporaries now peak at one tap's worth;
    inputs keep constant block indices across the k*k inner cells (fetched
    once per batch block) and dW accumulates in scratch, written to HBM
    exactly once at the final cell."""
    i, t = pl.program_id(0), pl.program_id(1)
    sp = -(-hh * ww // 8) * 8  # sublane-aligned flattened-spatial extent
    dy = _pad_rows(dy_ref[...], sp)
    dyf = dy.reshape(-1, dy.shape[-1])

    if k == 1:  # tapless: one floor-traffic matmul, no roll/mask/cast
        @pl.when(i == 0)
        def _init1():
            dw_ref[...] = jnp.zeros(dw_ref.shape, dw_ref.dtype)
        dw_ref[0] += jax.lax.dot_general(
            _pad_rows(x_ref[...], sp).reshape(-1, x_ref.shape[-1]), dyf,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return

    xbuf, dwacc = scratch

    @pl.when(t == 0)
    def _load():
        xbuf[...] = _pad_rows(x_ref[...].astype(jnp.float32), sp)

    @pl.when(jnp.logical_and(i == 0, t == 0))
    def _zero():
        dwacc[...] = jnp.zeros(dwacc.shape, dwacc.dtype)

    kh, kw = t // k, t % k
    dh, dw = kh - pad, kw - pad
    hs, ws = _make_hw(sp, ww)
    xs = (_rolled(xbuf[...], ww, dh, dw, flip=False).astype(dy.dtype)
          * _tap_mask(hs, ws, hh, ww, dh, dw, False, dy.dtype))
    part = jax.lax.dot_general(
        xs.reshape(-1, xs.shape[-1]), dyf,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dwacc[pl.dslice(t, 1)] += part[None]

    @pl.when(jnp.logical_and(i == ni - 1, t == k * k - 1))
    def _flush():
        dw_ref[...] = dwacc[...]


def conv3x3_wgrad(x, dy, stride: int = 1, *, ksize: int = 3,
                  interpret: bool = False):
    """dW for a kxk (k in {1, 3}) SAME stride-1 conv, NHWC/HWIO, at the
    HBM floor."""
    assert stride == 1, "stride-2 wgrad stays on XLA (see module docstring)"
    n, h, w, ci = x.shape
    co = dy.shape[-1]
    pad = _same_pad(h, ksize, 1)[0]
    # x: 2B in (x2 double-buffer) + fp32 cast/roll/mask/re-cast transients
    # when k>1; dy: 2B in (x2 double-buffer)
    per_img = h * w * (ci * (18 if ksize > 1 else 4) + co * 4)
    bn = _pick_bn(n, per_img, ksize * ksize * ci * co * 4)
    sp = -(-h * w // 8) * 8
    vma = _inherit_vma(x, dy)
    kernel = functools.partial(_wgrad_kernel, hh=h, ww=w, k=ksize, pad=pad,
                               ni=n // bn)
    dw = pl.pallas_call(
        kernel,
        grid=(n // bn, ksize * ksize),
        in_specs=[
            pl.BlockSpec((bn, h * w, ci), lambda i, t: (i, 0, 0)),
            pl.BlockSpec((bn, h * w, co), lambda i, t: (i, 0, 0)),
        ],
        scratch_shapes=([pltpu.VMEM((bn, sp, ci), jnp.float32),
                         pltpu.VMEM((ksize * ksize, ci, co), jnp.float32)]
                        if ksize > 1 else []),
        out_specs=pl.BlockSpec((ksize * ksize, ci, co),
                               lambda i, t: (0, 0, 0)),
        out_shape=_sds((ksize * ksize, ci, co), jnp.float32,
                                       vma=vma),
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(_promote_vma(x.reshape(n, h * w, ci), vma),
      _promote_vma(dy.reshape(n, h * w, co), vma))
    return dw.reshape(ksize, ksize, ci, co).astype(x.dtype)


# ---------------------------------------------------------------------------
# dgrad: dX[n, h, w, ci] = sum_{kh, kw} dY[n, h-dh, w-dw, co] W[kh, kw, ci, co]
# ---------------------------------------------------------------------------


def _dgrad_kernel(dy_ref, w_ref, dx_ref, *scratch, hh, ww, k, pad):
    """Grid is (batch-blocks, k*k): one tap per cell — see _wgrad_kernel
    for why the tap loop lives in the grid and not a fori_loop."""
    if k == 1:  # tapless: one floor-traffic matmul
        dx_ref[...] = jax.lax.dot_general(
            dy_ref[...], w_ref[0], (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dx_ref.dtype)
        return

    t = pl.program_id(1)
    size = hh * ww
    sp = -(-size // 8) * 8  # sublane-aligned; acc is allocated at sp rows
    acc, dybuf = scratch

    @pl.when(t == 0)
    def _load():
        acc[...] = jnp.zeros(acc.shape, acc.dtype)
        dybuf[...] = _pad_rows(dy_ref[...].astype(jnp.float32), sp)

    kh, kw = t // k, t % k
    dh, dw = kh - pad, kw - pad
    wv = w_ref[pl.dslice(t, 1)][0]
    hs, ws = _make_hw(sp, ww)
    dys = (_rolled(dybuf[...], ww, dh, dw, flip=True).astype(wv.dtype)
           * _tap_mask(hs, ws, hh, ww, dh, dw, True, wv.dtype))
    acc[...] += jax.lax.dot_general(
        dys, wv, (((2,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(t == k * k - 1)
    def _flush():
        dx_ref[...] = acc[:, :size, :].astype(dx_ref.dtype)


def conv3x3_dgrad(dy, w, xshape, stride: int = 1, *,
                  interpret: bool = False):
    """dX for a kxk (k in {1, 3}) SAME stride-1 conv, NHWC/HWIO, at the
    HBM floor."""
    assert stride == 1, "stride-2 dgrad stays on XLA (see module docstring)"
    n, h, ww_, ci = xshape
    co = dy.shape[-1]
    k = w.shape[0]
    pad = _same_pad(h, k, 1)[0]
    # dy: 2B in (x2 double-buffer) + fp32 cast/roll/mask/re-cast transients
    # when k>1; out: 2B (x2 double-buffer) + fp32 acc scratch
    per_img = h * ww_ * (co * (18 if k > 1 else 4) + ci * 8)
    bn = _pick_bn(n, per_img, k * k * ci * co * w.dtype.itemsize)
    kernel = functools.partial(_dgrad_kernel, hh=h, ww=ww_, k=k, pad=pad)
    sp = -(-h * ww_ // 8) * 8
    vma = _inherit_vma(dy, w)
    dx = pl.pallas_call(
        kernel,
        grid=(n // bn, k * k),
        in_specs=[
            pl.BlockSpec((bn, h * ww_, co), lambda i, t: (i, 0, 0)),
            pl.BlockSpec((k * k, ci, co), lambda i, t: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h * ww_, ci), lambda i, t: (i, 0, 0)),
        out_shape=_sds((n, h * ww_, ci), dy.dtype,
                                       vma=vma),
        scratch_shapes=([pltpu.VMEM((bn, sp, ci), jnp.float32),
                         pltpu.VMEM((bn, sp, co), jnp.float32)]
                        if k > 1 else []),
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(_promote_vma(dy.reshape(n, h * ww_, co), vma),
      _promote_vma(w.reshape(k * k, ci, co), vma))
    return dx.reshape(xshape)


# ---------------------------------------------------------------------------
# Drop-in conv with the Pallas VJP
# ---------------------------------------------------------------------------


def _xla_conv(x, w, stride):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _eligible(xshape, wshape, stride) -> bool:
    """Shapes where the floor-traffic kernels beat XLA (probe-measured).

    Small planes (7x7) are excluded: their contraction runs are too short
    to load the MXU, the 512-channel fp32 dW accumulator dominates VMEM,
    and XLA is already within 1.7x of floor on tiny absolute bytes there."""
    kh, kw = wshape[:2]
    if (kh, kw) not in ((3, 3), (1, 1)) or stride != 1:
        return False
    h, w = xshape[1], xshape[2]
    return h * w >= 196


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d(x, w, stride: int = 1, interpret: bool = None):
    """SAME-padded NHWC conv; XLA forward, Pallas 3x3/1x1-s1 backward.

    Falls back to XLA's own transpose rule for shapes outside the kernels'
    envelope, so it is safe as a universal replacement.  ``interpret=None``
    auto-selects: compiled Pallas on TPU, the XLA transpose rule elsewhere
    (identical math; interpret-mode Pallas under shard_map trips VMA
    agreement on the kernel's dynamic index scalars, and is far slower
    than XLA on CPU anyway).  ``interpret=True`` forces interpret-mode
    kernels — the parity tests' oracle-vs-kernel mode, outside shard_map.
    """
    return _xla_conv(x, w, stride)


def _conv2d_fwd(x, w, stride, interpret):
    return _xla_conv(x, w, stride), (x, w)


def _conv2d_bwd(stride, interpret, res, dy):
    x, w = res
    if interpret is None and jax.default_backend() != "tpu":
        interpret = "xla"  # auto: off-TPU, the XLA transpose rule
    if interpret == "xla" or not _eligible(x.shape, w.shape, stride):
        _, vjp = jax.vjp(lambda x, w: _xla_conv(x, w, stride), x, w)
        return vjp(dy)
    dx = conv3x3_dgrad(dy, w, x.shape, stride, interpret=bool(interpret))
    dw = conv3x3_wgrad(x, dy, stride, ksize=w.shape[0],
                       interpret=bool(interpret))
    return dx, dw.astype(w.dtype)


conv2d.defvjp(_conv2d_fwd, _conv2d_bwd)
