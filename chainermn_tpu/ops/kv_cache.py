"""In-place KV-cache append for incremental decoding (Pallas scatter).

The decode tick's cache append is ONE row per tensor, but
``lax.dynamic_update_slice`` inside the decode ``lax.scan`` costs a full
extra pass over the cache on TPU: XLA fuses the update into its consumers
(the attention einsums) as a select between old buffer and new row, so
every tick re-materializes the whole (B, S, H, D) cache instead of
writing 2 KB in place.  Measured on v5e (d1024/L8/h16 decode micro,
S=1024): attend-only 0.264 ms/tick, attend+dus appends 0.528 ms/tick —
the appends double cache traffic; reordering at the jnp level makes XLA
copy outright (3.49 ms/tick).

``cache_append`` replaces the two updates with one Pallas call whose
grid maps ONLY the block containing ``pos`` (scalar-prefetch index map)
and aliases input to output (``input_output_aliases``), so the write is
physically one row and the rest of the buffer is untouched memory.
Same micro: 0.343 ms/tick — within ~0.08 ms of the attend-only floor.

Reference relationship: the reference had no incremental decoding at all
(its seq2seq example re-ran the full decoder per token —
examples/seq2seq/seq2seq.py :: translate_one [uv], SURVEY.md §2.9); this
op exists to make the TPU-native KV-cache path run at the HBM floor.

Semantics are exactly ``dynamic_update_slice_in_dim`` at ``pos`` along
``axis``; the XLA fallback (non-TPU backends, multi-row writes such as
prefill, or ``impl='xla'``) IS that op.  The Pallas path itself is
parity-tested off-chip in interpret mode (tests/test_kv_cache.py,
``interpret=True``) and exercised compiled by the TPU decode runs.
"""

from __future__ import annotations

import jax

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import shape_dtype_struct as _sds

__all__ = ["cache_append"]


def _inherit_vma(*xs) -> frozenset:
    """Union of the inputs' varying-mesh-axes sets (same helper as
    ops/flash_attention.py) — pallas_call under shard_map must declare how
    its outputs vary."""
    vma = set()
    for x in xs:
        v = getattr(getattr(x, "aval", None), "vma", None)
        if v:
            vma |= set(v)
    return frozenset(vma)


_ROWS = 8  # sublane tile: the smallest legal second-minor block


def _append_kernel(pos_ref, knew_ref, vnew_ref, kin_ref, vin_ref,
                   kout_ref, vout_ref, *, rows):
    """Rewrite the 8-row sublane block containing ``pos``, replacing only
    rows [pos, pos+rows) (iota-range select — no dynamic stores).

    The new-row operands arrive TILED to the full 8-row block
    (8/rows copies): because ``rows | 8`` and the caller guarantees
    ``pos % rows == 0``, the in-block start ``pos % 8`` is a multiple of
    ``rows``, so ``tiled[j] == new[j - start]`` for every selected row —
    placement needs no dynamic shift at all."""
    start = pos_ref[0] % _ROWS
    idx = jax.lax.broadcasted_iota(jnp.int32, kin_ref.shape,
                                   kin_ref.ndim - 2)
    sel = (idx >= start) & (idx < start + rows)
    kout_ref[...] = jnp.where(sel, knew_ref[...], kin_ref[...])
    vout_ref[...] = jnp.where(sel, vnew_ref[...], vin_ref[...])


def cache_append(kc, vc, k_new, v_new, pos, *, axis: int = 1,
                 impl: str = "auto", pos_aligned: bool = False,
                 interpret: bool = False):
    """Write ``k_new``/``v_new`` into ``kc``/``vc`` at ``pos`` along
    ``axis``; returns the updated ``(kc, vc)``.

    ``impl='auto'`` uses the Pallas scatter on TPU when the write is
    ``rows`` rows with ``rows | 8`` (one row = the decode tick; rows=k =
    the time-major beam tick writing all k slots at once), and the XLA
    ``dynamic_update_slice`` everywhere else (other backends, and slab
    prefill writes where a full-pass update is amortized and XLA's slab
    write is fine).  CONTRACT for rows > 1: ``pos`` must be a multiple
    of ``rows`` (the beam tick's ``(i-1)·k`` positions are) — the
    in-tile placement relies on it.  A concrete misaligned ``pos`` falls
    back to the exact dus (or raises under ``impl='pallas'``); a TRACED
    ``pos`` cannot be checked, so multi-row auto-dispatch additionally
    requires the caller's ``pos_aligned=True`` promise — without it the
    write takes the dus path rather than risk silent corruption.
    ``interpret=True`` (with ``impl='pallas'``) runs the kernel in
    interpret mode for off-chip parity tests.

    **Per-row positions** (the serving cache pool's contract): ``pos``
    may be a RANK-1 vector of length ``kc.shape[0]`` — row ``b`` of the
    new K/V is then written at ``pos[b]`` along ``axis``, independently
    per row (a vmapped ``dynamic_update_slice``).  Every slot in a
    continuous-batching pool sits at its own sequence length, so the
    one-token-per-active-slot tick needs exactly this ragged write.
    Scalar ``pos`` behavior is unchanged; the vector path is XLA-only
    (``impl='pallas'`` with a vector raises — the scatter kernel maps a
    single block per call).
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"impl must be auto|pallas|xla, got {impl!r}")
    if not isinstance(pos, (int, np.integer)) and getattr(pos, "ndim", 0) == 1:
        if impl == "pallas":
            raise ValueError(
                "impl='pallas' supports scalar pos only; a per-row position "
                "vector takes the vmapped dynamic_update_slice path "
                "(impl='auto' or 'xla')")
        if axis < 1:
            raise ValueError(
                f"per-row pos needs the row axis (0) distinct from the "
                f"write axis, got axis={axis}")
        if pos.shape[0] != kc.shape[0]:
            raise ValueError(
                f"per-row pos length {pos.shape[0]} != leading (row) dim "
                f"{kc.shape[0]} of the cache {kc.shape}")

        def _row_write(c, n, p):
            return jax.lax.dynamic_update_slice_in_dim(c, n, p, axis - 1)

        return (jax.vmap(_row_write)(kc, k_new, pos),
                jax.vmap(_row_write)(vc, v_new, pos))
    # Pallas envelope: a single-row write whose position axis is the
    # SECOND-MINOR dim (the attention-native cache layouts put positions
    # there) with an 8-divisible extent — the mapped block is then the
    # (8, minor) sublane tile containing ``pos``, the smallest Mosaic
    # will address.
    rows = k_new.shape[axis]
    concrete = isinstance(pos, (int, np.integer))
    aligned = (rows == 1
               or (concrete and pos % rows == 0)
               or (not concrete and pos_aligned))
    fits = (rows >= 1 and _ROWS % rows == 0 and axis == kc.ndim - 2
            and kc.shape[axis] % _ROWS == 0 and aligned)
    use_pallas = (impl == "pallas"
                  or (impl == "auto" and fits
                      and jax.default_backend() == "tpu"))
    if not use_pallas:
        return (jax.lax.dynamic_update_slice_in_dim(kc, k_new, pos, axis),
                jax.lax.dynamic_update_slice_in_dim(vc, v_new, pos, axis))
    if not fits:
        raise ValueError(
            f"impl='pallas' needs a write of rows dividing {_ROWS} along "
            f"the second-minor axis with an 8-divisible extent, at a "
            f"rows-aligned pos (traced pos needs pos_aligned=True); got "
            f"axis {axis} of shape {kc.shape} writing "
            f"{k_new.shape[axis]} rows at pos {pos!r}")
    if not interpret and jax.default_backend() != "tpu":
        # Forced pallas off-chip: fail at dispatch with an actionable
        # message instead of deep in Mosaic lowering (ADVICE round 5) —
        # compiled Pallas is TPU-only.
        raise ValueError(
            f"impl='pallas' with interpret=False requires a TPU backend "
            f"(current backend: {jax.default_backend()!r}); pass "
            f"interpret=True for off-chip parity runs, or impl='auto'/"
            f"'xla' to take the dynamic_update_slice path")

    block = tuple(_ROWS if d == axis else n for d, n in enumerate(kc.shape))
    new_block = tuple(1 if d == axis else n for d, n in enumerate(kc.shape))
    zero_idx = (0,) * kc.ndim

    def at_pos(i, p):
        # block index map in units of the block shape: the position axis
        # uses 8-row blocks, so the block index is pos // 8
        return tuple(p[0] // _ROWS if d == axis else 0
                     for d in range(kc.ndim))

    vma = _inherit_vma(kc, vc, k_new, v_new)
    # rows == 1 keeps the 1-row new-operand block (the hot greedy tick:
    # the where broadcasts it for free); rows > 1 tiles the new rows to
    # the full 8-row block so in-tile placement is shift-free (see
    # _append_kernel).
    nb = new_block if rows == 1 else block
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec(nb, lambda i, p: zero_idx),
                  pl.BlockSpec(nb, lambda i, p: zero_idx),
                  pl.BlockSpec(block, at_pos),
                  pl.BlockSpec(block, at_pos)],
        out_specs=[pl.BlockSpec(block, at_pos),
                   pl.BlockSpec(block, at_pos)])
    new_shape = kc.shape[:axis] + (rows,) + kc.shape[axis + 1:]
    kn = k_new.reshape(new_shape).astype(kc.dtype)
    vn = v_new.reshape(new_shape).astype(vc.dtype)
    if rows > 1:
        reps = tuple(_ROWS // rows if d == axis else 1
                     for d in range(kc.ndim))
        kn, vn = jnp.tile(kn, reps), jnp.tile(vn, reps)
    import functools as _ft
    return pl.pallas_call(
        _ft.partial(_append_kernel, rows=rows), grid_spec=grid_spec,
        out_shape=[_sds(kc.shape, kc.dtype, vma=vma),
                   _sds(vc.shape, vc.dtype, vma=vma)],
        input_output_aliases={3: 0, 4: 1},  # kc, vc (after the scalar arg)
        interpret=interpret,
    )(jnp.asarray([pos], jnp.int32).astype(jnp.int32), kn, vn, kc, vc)
