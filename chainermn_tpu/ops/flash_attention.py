"""Flash attention as Pallas TPU kernels, forward and backward.

Reference relationship: the reference's only runtime-compiled device code
was CuPy's fused cast/scale CUDA kernels on the allreduce path
(``chainermn/communicators/pure_nccl_communicator.py`` [uv], SURVEY.md
§2.7); attention itself predates it entirely.  This is the TPU-native
analog of "hand-write the hot kernel": the O(S²) score matrix never
touches HBM — Q/K/V stream through VMEM in MXU-sized tiles and the online-
softmax state (m, l, acc) lives in VMEM scratch across the K-block grid
dimension (pallas_guide.md §4/§8 revolving-accumulator pattern).

Forward: one Pallas kernel, grid ``(B·H, S/block_q, S/block_k)``, the last
dimension sequential ("arbitrary") so scratch accumulates across K blocks.
Saves the log-sum-exp alongside the output.

Backward: ONE fused Pallas kernel (round 4; previously a dQ + dKV pair
that recomputed ``qk``/``do·v`` twice and read the operands from HBM
twice).  Grid is K-major with (group, Q) sequential: dk/dv accumulate in
fp32 VMEM scratch (the GQA head-group fold happens in-scratch), while
each cell's dq contribution is written as a per-K-block PARTIAL slab —
input dtype, summed in fp32 by one XLA reduce — because K-major cells
visit a given q block non-consecutively (no scratch residency) and HBM
read-modify-write aliasing would race the block prefetch at diagonal
corners.  Probabilities recompute from the saved LSE (``p = exp(s −
lse)`` is the exact softmax, no renormalisation pass); causal
above-diagonal cells are skipped AND their dead block DMA elided by
index-map clamping.  O(S·block) live memory in VMEM, an O(nk·S·D)
HBM transient for the dq partials.  A
``lax.scan`` XLA fallback (``backward='xla'``) covers Mosaic-hostile
block geometries and serves as the oracle in tests.  On CPU (tests,
debugging) the kernels run in Pallas interpret mode; the math is
identical.

Layout: ``(B, S, H, D)`` — the same convention as ``parallel/``'s ring and
Ulysses attention, which uses this kernel for its local (post-all-to-all)
attention when ``attn_impl='flash'``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import shape_dtype_struct as _sds
from .._compat import tpu_compiler_params as _tpu_compiler_params

NEG_INF = -1e30
_LANES = 128  # TPU vector lane count: scratch vectors are (block_q, 128)
_MIN_BLOCK = 8  # fp32 sublane tile; divisor blocks below this are Mosaic-
                # hostile (prime S degrades to 1), so we pad+mask instead


def resolve_attn_impl(attn_impl: str, seq_len: int) -> str:
    """Resolve ``'auto'`` to a concrete attention implementation.

    ``'flash'`` (this module's Pallas kernels) on a TPU backend for
    non-trivial sequences — measured ≥5× faster than the materializing
    path at S=1024 on v5e and O(block) memory at long S; the materializing
    ``'xla'`` path for tiny sequences (grid overhead dominates) and for
    CPU runs (interpret-mode Pallas is a per-cell Python loop — tests
    force it explicitly when they mean to).  Explicit names pass through
    untouched."""
    if attn_impl != "auto":
        return attn_impl
    if jax.default_backend() == "tpu" and seq_len >= 128:
        return "flash"
    return "xla"


def _pick_block(s: int, want: int) -> int:
    """Largest block ≤ want that divides s (static shapes, no padding)."""
    for b in range(min(want, s), 0, -1):
        if s % b == 0:
            return b
    return 1


def _pick_aligned_block(s: int, want: int) -> int:
    """Largest MOSAIC-LEGAL block ≤ ``want`` dividing ``s``: either the
    full dimension (always legal) or a multiple of the 8-row sublane tile.
    Returns 0 when none exists — the caller must pad ``s``.  (A divisor
    like 100 for S=200 passes the old ≥8 test but is neither full-size nor
    8-aligned, which Mosaic rejects at lowering.)"""
    if s <= want:
        return s
    for b in range(min(want, s), _MIN_BLOCK - 1, -1):
        if s % b == 0 and b % _MIN_BLOCK == 0:
            return b
    return 0


def _pick_lane_block(s: int, want: int) -> int:
    """Largest LANE-multiple (128) divisor of ``s`` ≤ ``want`` — the
    backward's Pallas kernels slice (1, 1, S) LSE/delta rows at lane-dim
    offset iq·block_q, which compiled Mosaic requires 128-aligned, so the
    q-block must be a 128-multiple.  Preferring 128-multiple divisors keeps
    shapes like S=640 (→128) and S=1280 (→256) on the Pallas path where the
    plain 8-aligned pick would return 320 and silently fall back to the XLA
    scan (round-4 advisor finding).  Falls back to the 8-aligned pick when
    no 128-multiple divisor exists (the dispatch check then routes to XLA).
    """
    for b in range(min(want, s) // _LANES * _LANES, 0, -_LANES):
        if s % b == 0:
            return b
    return _pick_aligned_block(s, want)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal,
                block_q, block_k, num_kblocks, seq_len):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # seq_len < the padded S means a masked tail (prime/odd S padded up to
    # the block size); those K positions must contribute nothing.
    tail = seq_len is not None

    # Causal: K blocks entirely above the diagonal contribute nothing —
    # skip their matmuls (≈2× FLOP saving at long S).  Fully-padded K
    # blocks likewise.
    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True
    if tail:
        run = jnp.logical_and(run, ik * block_k < seq_len)

    @pl.when(run)
    def _body():
        q = q_ref[0]                                   # (block_q, D)
        k = k_ref[0]                                   # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)

        mask = None
        if causal or tail:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = (q_pos >= k_pos) if causal else (k_pos == k_pos)
            if tail:
                mask = jnp.logical_and(mask, k_pos < seq_len)
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                          # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        # NEG_INF is finite, so exp(s - m_new) alone would turn fully-masked
        # rows into 1s — multiply by the mask explicitly.
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                # (block_q, 1)
        l_new = l_prev * alpha + p.sum(-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    # For causal, the last contributing K block for this Q block is the one
    # covering the diagonal, not num_kblocks-1.
    if causal:
        last_ik = jnp.minimum(
            (iq * block_q + block_q - 1) // block_k, num_kblocks - 1)
    else:
        last_ik = num_kblocks - 1

    @pl.when(ik == last_ik)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-37)).astype(o_ref.dtype)
        # LSE is lane-replicated (block_q, LANES) — Mosaic needs the last
        # two block dims tileable; callers slice [..., 0].
        lse_ref[0] = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-37))


def _inherit_vma(*xs) -> frozenset:
    """Union of the inputs' varying-mesh-axes sets — pallas_call inside
    shard_map requires out_shapes to declare how outputs vary."""
    vma = set()
    for x in xs:
        aval = getattr(x, "aval", None)
        v = getattr(aval, "vma", None)
        if v:
            vma |= set(v)
    return frozenset(vma)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret, seq_len,
               group: int = 1):
    """``q (B·H, S, D)``, ``k/v (B·H/group, S, D)``: ``group`` consecutive
    q heads share one KV head (GQA/MQA).  The sharing happens in the
    BlockSpec index_map — KV is never materialized at H heads."""
    bh, s, d = q.shape
    bq = _pick_aligned_block(s, block_q)
    bk = _pick_aligned_block(s, block_k)
    assert bq and bk, (s, block_q, block_k)  # wrapper pads unalignable S
    nq, nk = s // bq, s // bk
    vma = _inherit_vma(q, k, v)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, num_kblocks=nk,
        seq_len=None if seq_len == s else seq_len)

    def kv_index(b, i, j):
        # Causal: K blocks past the diagonal are pl.when-skipped — clamp
        # their index to the diagonal block so Pallas's revisit detection
        # elides the (otherwise dead) K/V DMA for the whole skipped tail.
        if causal:
            j = jnp.minimum(j, (i * bq + bq - 1) // bk)
        return (b // group, j, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kv_index),
            pl.BlockSpec((1, bk, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, s, d), q.dtype, vma=vma),
            _sds((bh, s, _LANES), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_blockwise(q, k, v, out, lse, do, causal, scale, block_k, seq_len,
                   dlse=None):
    """Memory-efficient backward: scan over K blocks, recomputing p from
    the saved LSE.  All operands (BH, S, D); returns (dq, dk, dv).

    ``dlse``: cotangent of the LSE output when the caller differentiates
    through it (ring attention's block-merge weights).  Since
    ∂lse_i/∂s_ij = p_ij, it folds into the score cotangent as
    ``ds = p * (dp - delta + dlse)``; v gets no extra term (lse is
    v-independent)."""
    bh, s, d = q.shape
    bk = _pick_block(s, block_k)
    nk = s // bk
    tail = seq_len != s
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                  # (BH, S)
    q_pos = jnp.arange(s)

    def step(dq_acc, ik):
        kb = jax.lax.dynamic_slice_in_dim(k, ik * bk, bk, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(v, ik * bk, bk, axis=1)
        sc = jnp.einsum("bqd,bkd->bqk", q, kb,
                        preferred_element_type=jnp.float32) * scale
        p = jnp.exp(sc - lse[..., None])                      # exact softmax
        if causal or tail:
            k_pos = ik * bk + jnp.arange(bk)
            mask = (q_pos[:, None] >= k_pos[None, :] if causal
                    else jnp.ones((s, bk), bool))
            if tail:
                # Padded q rows have lse ≈ NEG_INF, making exp() overflow to
                # inf; padded k columns must contribute nothing.  Mask both.
                mask = (mask & (k_pos[None, :] < seq_len)
                        & (q_pos[:, None] < seq_len))
            p = jnp.where(mask[None], p, 0.0)
        dv_b = jnp.einsum("bqk,bqd->bkd", p.astype(do.dtype), do,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqd,bkd->bqk", do, vb,
                        preferred_element_type=jnp.float32)
        dsoft = dp - delta[..., None]
        if dlse is not None:
            dsoft = dsoft + dlse[..., None]
        ds = p * dsoft * scale                                # (BH, S, bk)
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds.astype(kb.dtype), kb,
                                     preferred_element_type=jnp.float32)
        dk_b = jnp.einsum("bqk,bqd->bkd", ds.astype(q.dtype), q,
                          preferred_element_type=jnp.float32)
        return dq_acc, (dk_b, dv_b)

    # The accumulator must carry q's varying-axes type (scan demands
    # carry-in/out agree inside shard_map) WITHOUT inheriting q's values —
    # `q * 0` would smear one inf/NaN in q into an all-NaN dq.
    from .collective import zeros_like_vma

    dq, (dks, dvs) = jax.lax.scan(
        step, zeros_like_vma(q, jnp.float32), jnp.arange(nk))
    # (nk, BH, bk, D) → (BH, nk·bk=S, D); blocks were emitted in order.
    dk = dks.transpose(1, 0, 2, 3).reshape(bh, s, d)
    dv = dvs.transpose(1, 0, 2, 3).reshape(bh, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_fused_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                      dqp_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                      causal, block_q, block_k, num_qblocks, group, seq_len):
    """Fused backward: ONE kernel produces dk, dv AND dq.

    Grid ``(B·H_kv, S/block_k, group, S/block_q)`` with the (group, Q)
    dims sequential — one K block's dk/dv accumulate over every q head
    sharing it (the GQA fold happens IN the scratch, in fp32) and every Q
    block, exactly as the old dK/dV kernel did.  The difference: the
    ``ds·k`` product this cell already has in registers ALSO yields this
    (q-block, k-block) cell's dq contribution, so the old separate dQ
    kernel — which re-did the qk and do·v matmuls and re-read q/k/v/do
    from HBM — is gone (2 of 7 backward matmuls and half the backward
    input DMA, measured +21% backward at S=8192, docs/PERF.md round 4).

    dq contributions cannot accumulate in scratch here (the grid is
    K-major; a q block's contributions arrive across non-consecutive
    cells) and HBM read-modify-write via input/output aliasing would race
    Pallas's block prefetch at the diagonal corners, so each K block
    writes its dq PARTIAL to its own ``(B·H, nk, S, D)`` slab slice and
    one XLA sum over nk finishes the job — O(nk·S·D) fp32 transient,
    ~0.7 ms of the ~5 ms the fusion saves at S=8192."""
    jk, g, iq = pl.program_id(1), pl.program_id(2), pl.program_id(3)

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    tail = seq_len is not None
    run = (iq * block_q + block_q - 1 >= jk * block_k) if causal else True
    if tail:
        run = jnp.logical_and(run, iq * block_q < seq_len)

    @pl.when(run)
    def _body():
        k, v, q, do = k_ref[0], v_ref[0], q_ref[0], do_ref[0]
        lse = lse_ref[0, 0, pl.dslice(iq * block_q, block_q)]
        delta = delta_ref[0, 0, pl.dslice(iq * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        p = jnp.exp(s - lse[:, None])
        if causal or tail:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = (q_pos >= k_pos) if causal else (k_pos == k_pos)
            if tail:
                mask = jnp.logical_and(
                    mask, jnp.logical_and(k_pos < seq_len, q_pos < seq_len))
            p = jnp.where(mask, p, 0.0)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dqp_ref[0, 0] = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dqp_ref.dtype)  # bf16 partial: fp32 sum outside

    @pl.when(jnp.logical_not(run))
    def _skip():
        # this cell's partial slice is summed unconditionally outside —
        # unwritten blocks would be uninitialized memory, not zeros
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    @pl.when(jnp.logical_and(g == group - 1, iq == num_qblocks - 1))
    def _fin():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, out, lse, do, causal, scale, block_q, block_k,
                interpret, seq_len, group, dlse=None):
    """Pallas dq/dk/dv via the ONE fused kernel (see
    :func:`_bwd_fused_kernel`), sharing one XLA-precomputed
    ``delta = rowsum(do·out) − dlse`` (the LSE cotangent folds in exactly:
    ``ds = p·(dp − delta + dlse)``).  Same blockwise-LSE math as
    :func:`_bwd_blockwise`, but the (S, block) score recompute never leaves
    VMEM and the GQA head-group fold happens in the fp32 scratch."""
    bh, s, d = q.shape
    bh_kv = k.shape[0]
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    nq, nk = s // bq, s // bk
    vma = _inherit_vma(q, k, v, do)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                    # (BH, S)
    if dlse is not None:
        delta = delta - dlse
    # (BH, 1, S): full-row trailing dims satisfy Mosaic's block alignment
    # for any block_q; kernels slice their q block dynamically.
    lse = lse.astype(jnp.float32)[:, None, :]
    delta = delta[:, None, :]
    sl = None if seq_len == s else seq_len

    def qdo_index(b, j, g, i):
        # Q blocks strictly above the diagonal (i·bq + bq − 1 < j·bk) are
        # pl.when-skipped — clamp them up to the first contributing block
        # so Pallas's revisit detection elides their dead Q/dO DMA
        if causal:
            i = jnp.maximum(i, (j * bk) // bq)
        return (b * group + g, i, 0)

    dq_part, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, scale=scale, causal=causal, block_q=bq,
            block_k=bk, num_qblocks=nq, group=group, seq_len=sl),
        grid=(bh_kv, nk, group, nq),
        in_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), qdo_index),
            pl.BlockSpec((1, bq, d), qdo_index),
            pl.BlockSpec((1, 1, s), lambda b, j, g, i: (b * group + g, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda b, j, g, i: (b * group + g, 0, 0)),
        ],
        out_specs=[
            # dq partials: UNclamped index — dead cells write their own
            # zero slice (the sum below reads every slab slice)
            pl.BlockSpec((1, 1, bq, d),
                         lambda b, j, g, i: (b * group + g, j, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, g, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, g, i: (b, j, 0)),
        ],
        out_shape=[
            # partials in the INPUT dtype: bf16 models halve the slab
            # traffic at the cost of rounding each of the nk per-K-block
            # partials to bf16 BEFORE the fp32 sum (the sum itself adds no
            # further error) — dq error vs the fp32-slab path measured
            # ~0.5% relative, inside bf16 training noise, and pinned by
            # the bf16 gradient parity test; fp32 callers (ring
            # attention's fp32-grade parity) keep a full-precision slab
            _sds((bh, nk, s, d), q.dtype, vma=vma),
            _sds((bh_kv, s, d), k.dtype, vma=vma),
            _sds((bh_kv, s, d), v.dtype, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(k, v, q, do, lse, delta)
    dq = dq_part.astype(jnp.float32).sum(axis=1).astype(q.dtype)
    return dq, dk, dv


def _expand_kv(x, group):
    """(B·Hkv, S, D) → (B·H, S, D) by repeating each KV head ``group``
    times (backward-only; the forward shares via the index_map)."""
    if group == 1:
        return x
    return jnp.repeat(x, group, axis=0)


def _fold_dkv(dx, group):
    """(B·H, S, D) grads → (B·Hkv, S, D): sum the shared-head group in fp32
    (an MQA group can be 32+ heads; a bf16 tree-sum would shed low-order
    gradient mass) — callers cast back to the KV dtype."""
    if group == 1:
        return dx
    bh, s, d = dx.shape
    return dx.reshape(bh // group, group, s, d).astype(jnp.float32).sum(1)


def _bwd_gqa(q, k, v, out, lse, do, causal, scale, block_k, seq_len, group,
             dlse=None):
    """GQA backward: recompute with KV expanded to the full q-head count,
    then fold the shared-head gradient groups back down.  The expansion is
    backward-only and O(S·D·H) — dominated by the (BH, S, block) score
    recompute the blockwise backward already carries."""
    dq, dk, dv = _bwd_blockwise(
        q, _expand_kv(k, group), _expand_kv(v, group), out, lse, do,
        causal, scale, block_k, seq_len, dlse=dlse)
    return dq, _fold_dkv(dk, group).astype(k.dtype), \
        _fold_dkv(dv, group).astype(v.dtype)


_BWD_BLOCK_Q = 512   # backward tiles: the 5-matmul body needs coarse
_BWD_BLOCK_K = 2048  # blocks to amortise grid overhead (v5e-tuned; the
# S=16384 hunt measured bwd 0.374 MFU at 512x2048 vs 0.315 at the
# forward-optimal 1024x1024 — fwd and bwd optima DIFFER, so the backward
# no longer inherits the forward's blocks; scripts/tune_flash_bwd.py)


def _bwd_dispatch(q, k, v, out, lse, do, causal, scale, block_q, block_k,
                  interpret, seq_len, group, backward, dlse=None,
                  bwd_block_q=None, bwd_block_k=None):
    """Route to the Pallas dq/dk/dv kernels (``'pallas'``), the XLA
    blockwise scan (``'xla'``), or pick automatically (``'auto'``: Pallas
    whenever the block geometry is Mosaic-aligned — which on TPU with the
    default blocks is every realistic shape).  Backward tiles are chosen
    independently of the forward's (``bwd_block_q``/``bwd_block_k``,
    default the v5e-tuned ``_BWD_BLOCK_*``): the two optima measurably
    differ, and an explicit value is honored even when finer than the
    default."""
    s = q.shape[1]
    # Backward blocks are INDEPENDENT of the forward's: the optima differ
    # (S=16384: fwd wants 1024x1024, bwd wants 512x2048 — 19% apart), so
    # callers' forward tuning no longer drags the backward with it.
    # Explicit bwd_block_q/bwd_block_k on flash_attention override.
    # Default q block: prefer 128-multiple divisors (lane-aligned LSE
    # slices, see below).  An EXPLICIT bwd_block_q keeps the plain
    # 8-aligned pick so the caller's value is honored verbatim — and a
    # non-lane explicit block still fails loudly on backward='pallas'
    # instead of being silently swapped for a smaller tile.
    bwd_bq = (_pick_block if interpret else
              _pick_aligned_block if bwd_block_q else _pick_lane_block)(
        s, bwd_block_q or _BWD_BLOCK_Q)
    bwd_bk = (_pick_block if interpret else _pick_aligned_block)(
        s, bwd_block_k or _BWD_BLOCK_K)
    # The kernels slice the (1, 1, S) LSE/delta rows at lane-dim offset
    # iq·block_q — compiled Mosaic wants those slices 128-aligned, so the
    # Pallas path needs a 128-multiple q block.  _pick_lane_block prefers
    # 128-multiple divisors of S, so the real condition is: S has a
    # 128-multiple divisor ≤ the q-block budget (every multiple of 128
    # qualifies; e.g. S=640 → block 128).  Anything else — e.g. S=200 —
    # falls back to the XLA scan.
    ok = interpret or (bwd_bq % _LANES == 0)
    if backward == "auto":
        backward = "pallas" if ok else "xla"
    elif backward == "pallas" and not ok:
        raise ValueError(
            f"pallas backward needs a q block that is a multiple of "
            f"{_LANES} after shrinking to divide S={s} (got {bwd_bq}); "
            f"pad S to a multiple of {_LANES} or use backward='xla'")
    if backward == "pallas":
        return _bwd_pallas(q, k, v, out, lse, do, causal, scale, bwd_bq,
                           bwd_bk, interpret, seq_len, group, dlse=dlse)
    if backward != "xla":
        raise ValueError(
            f"backward must be 'auto', 'pallas' or 'xla', got {backward!r}")
    return _bwd_gqa(q, k, v, out, lse, do, causal, scale, bwd_bk,
                    seq_len, group, dlse=dlse)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash_bhsd(q, k, v, causal, block_q, block_k, interpret, seq_len, group,
                backward, bwd_block_q=None, bwd_block_k=None):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                        seq_len, group)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k, interpret, seq_len,
                    group, backward, bwd_block_q=None, bwd_block_k=None):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                          seq_len, group)
    return out, (q, k, v, out, lse)


def _flash_bhsd_bwd(causal, block_q, block_k, interpret, seq_len, group,
                    backward, bwd_block_q, bwd_block_k, res, do):
    q, k, v, out, lse = res
    scale = 1.0 / (q.shape[-1] ** 0.5)
    return _bwd_dispatch(q, k, v, out, lse, do, causal, scale, block_q,
                         block_k, interpret, seq_len, group, backward,
                         bwd_block_q=bwd_block_q, bwd_block_k=bwd_block_k)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash_bhsd_lse(q, k, v, causal, block_q, block_k, interpret, seq_len,
                    group, backward, bwd_block_q=None, bwd_block_k=None):
    """Like :func:`_flash_bhsd` but also returns the LSE as a DIFFERENTIABLE
    output — ring attention merges visiting blocks with LSE-derived weights,
    so gradients must flow through it."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                      seq_len, group)


def _flash_bhsd_lse_fwd(q, k, v, causal, block_q, block_k, interpret,
                        seq_len, group, backward,
                        bwd_block_q=None, bwd_block_k=None):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                          seq_len, group)
    return (out, lse), (q, k, v, out, lse)


def _flash_bhsd_lse_bwd(causal, block_q, block_k, interpret, seq_len,
                        group, backward, bwd_block_q, bwd_block_k, res, cts):
    q, k, v, out, lse = res
    do, dlse = cts
    scale = 1.0 / (q.shape[-1] ** 0.5)
    return _bwd_dispatch(q, k, v, out, lse, do, causal, scale, block_q,
                         block_k, interpret, seq_len, group, backward,
                         dlse=dlse, bwd_block_q=bwd_block_q,
                         bwd_block_k=bwd_block_k)


_flash_bhsd_lse.defvjp(_flash_bhsd_lse_fwd, _flash_bhsd_lse_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    return_lse: bool = False, backward: str = "auto",
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None):
    """Flash attention over ``(B, S, H, D)`` arrays.

    ``interpret=None`` auto-selects: the compiled Pallas kernel on TPU,
    interpret mode elsewhere (CPU tests — same math, no Mosaic).  When ``S``
    is a multiple of a reasonable block, blocks shrink to the largest
    Mosaic-legal divisor (full-size or 8-row aligned); otherwise
    (prime/small-factor S) ``S`` is padded up to the next lane multiple and
    the tail masked inside the kernel.  Differentiable via the blockwise
    LSE backward; O(S·block) live memory both directions.

    Default blocks (``block_q/block_k=None``) are tuned on TPU v5e:
    128×128 leaves the grid too fine (measured ~5× slower at S=1024 —
    per-cell overhead dominates the two (block_q × d × block_k) MXU
    issues).  512×1024 amortises it at short S; from S ≥ 2048 the
    forward measurably prefers 1024×1024 (S=8192: 6.11 → 4.92 ms,
    docs/PERF.md long-context round 4) and the fp32 score tile (4 MB)
    still fits VMEM, so the q block widens automatically.  Explicit
    values are always honored.

    ``backward`` selects the gradient path: ``'pallas'`` — the ONE fused
    dq/dk/dv kernel (blockwise LSE recompute in VMEM, fp32 dk/dv scratch,
    input-dtype dq partials + fp32 XLA sum, causal cells skipped with
    their DMA elided, GQA group-fold in-scratch);
    ``'xla'`` — the lax.scan blockwise recompute; ``'auto'`` — Pallas
    whenever the block geometry is Mosaic-aligned (any S that is a multiple
    of 128 after padding), else XLA.

    ``bwd_block_q``/``bwd_block_k`` (default None → 512x2048, v5e-tuned)
    tile the BACKWARD independently of the forward: the optima differ
    (S=16384 measured: bwd 512x2048 vs the forward-optimal 1024x1024 is
    ~2-5% end to end; S=4096 fwd+bwd improved 0.30 → 0.47 attn-MFU when
    the backward stopped inheriting the forward's 1024-wide q block).

    ``return_lse=True`` additionally returns the per-query log-sum-exp
    ``(B, H, S)`` as a differentiable output (the block-merge currency of
    ring attention).

    GQA/MQA: ``k``/``v`` may carry FEWER heads than ``q`` (``H_kv`` with
    ``H % H_kv == 0``); each group of ``H/H_kv`` consecutive q heads
    attends the shared KV head.  The sharing is done in the kernel's block
    index map — KV never materializes at ``H`` heads in the forward.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(
            f"q heads {h} not a multiple of kv heads {h_kv} (GQA contract)")
    if v.shape[2] != h_kv:
        raise ValueError(f"k has {h_kv} heads but v has {v.shape[2]}")
    group = h // h_kv
    if block_q is None:
        block_q = 1024 if s >= 2048 else 512
    if block_k is None:
        block_k = 1024
    block_q = max(block_q, _MIN_BLOCK)
    block_k = max(block_k, _MIN_BLOCK)
    s_pad = s
    if not (_pick_aligned_block(s, block_q)
            and _pick_aligned_block(s, block_k)):
        # No Mosaic-legal block divides S (prime/small-divisor lengths):
        # pad to the next lane multiple — 128 | s_pad guarantees an aligned
        # block ≥ min(block, 128) exists, and keeps the padding overhead
        # O(128) instead of the old round-up to lcm(block_q, block_k).
        s_pad = -(-s // _LANES) * _LANES
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    def to_bhsd(x):
        nh = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * nh, s_pad, x.shape[-1])

    if return_lse:
        out, lse = _flash_bhsd_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                   causal, block_q, block_k, interpret, s,
                                   group, backward, bwd_block_q, bwd_block_k)
        return (out.reshape(b, h, s_pad, d)[:, :, :s].transpose(0, 2, 1, 3),
                lse.reshape(b, h, s_pad)[:, :, :s])
    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                      causal, block_q, block_k, interpret, s, group,
                      backward, bwd_block_q, bwd_block_k)
    return out.reshape(b, h, s_pad, d)[:, :, :s].transpose(0, 2, 1, 3)
