from .flash_attention import flash_attention  # noqa: F401
from .fused_ce import ce_grads, ce_stats, fused_cross_entropy  # noqa: F401
from .collective import (  # noqa: F401
    all_gather,
    all_to_all,
    axis_index,
    axis_size,
    bcast,
    block_dequantize,
    block_quantize,
    choose_pipeline_depth,
    hierarchical_pmean,
    pmax,
    pmean,
    pmean_if_bound,
    pmin,
    ppermute,
    psum,
    quantized_ring_pmean,
    reduce_scatter,
    shift,
)
