"""Flash-decode attention: one Pallas pass over the KV cache per tick.

The decode tick's attention is bandwidth-bound — read every cached K and
V byte once, at full HBM rate.  XLA's lowering of the per-head einsums
(``bqhgd,bhkd->bhgqk`` with q-length 1) misses that floor ~2.4× in the
compiled decode loop: with M=1 the dots lower to VPU multiply+reduce
fusions over ``(S, head_dim=64)`` tiles whose minor dim fills only half
of each 128-lane vreg (the round-4 HLO dump ranks these fusions top of
the while body; the same chain STANDALONE compiles to MXU dots and hits
1028 GB/s — the miss is a fusion/layout decision inside the big loop,
not op cost).

This kernel sidesteps the shape problem instead of fighting the fusion
heuristics:

* the cache is stored FLAT — ``(B, S, H·head_dim)`` — so every load
  streams dense 128-lane rows (1024 lanes at the bench config);
* per-head score reduction is a SEGMENTED MATMUL: ``scores (S_b, H) =
  (K ⊙ q) @ SEG`` where ``SEG (H·hd, H)`` is the 0/1 head-membership
  matrix — the MXU does the 64-wide segment sums, no reshapes, no
  per-head GEMVs;
* softmax is the standard online (m, l, acc) flash recursion over
  S-blocks, entirely in VMEM/registers;
* the probability-weighted V sum expands ``p (S_b, H)`` back to lanes
  with ``SEGᵀ`` (MXU again) and reduces over the block's sublanes.

Grid: ``(B, S/block_s)`` — per-batch-row state resets at the first
S-block (the grid's minor dim iterates fastest).  The ``pos`` scalar
arrives via scalar prefetch; positions beyond it are masked before the
online max.  ``decode_attend`` covers h_q == h_kv; GQA decode rides the
BEAM kernel (``decode_attend_gqa``: the g query groups of a batch row
share its cache row — exactly the beam row mapping — with the position
mask in the mask operand).

Reference relationship: no analog — the reference decoded by re-running
the full decoder per token (SURVEY.md §2.9 seq2seq).  Parity oracle:
the einsum attend in ``parallel/decode.py`` (``impl='xla'``), tested in
tests/test_decode_attention.py.
"""

from __future__ import annotations

import functools

import jax

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import shape_dtype_struct as _sds

__all__ = ["decode_attend", "decode_attend_gqa",
           "beam_attend_parts", "merge_attend_parts"]

_NEG = -1e30
DEFAULT_BLOCK_S = 512  # single source for the kernel AND dispatch gates


def _inherit_vma(*xs) -> frozenset:
    vma = set()
    for x in xs:
        v = getattr(getattr(x, "aval", None), "vma", None)
        if v:
            vma |= set(v)
    return frozenset(vma)


def _seg(d: int, n_heads: int):
    """The 0/1 head-membership matrix ``(D, H)``: SEG[j, h] = 1 iff lane
    ``j`` belongs to head ``h`` — single source for the kernels and the
    merge (its transpose)."""
    return (jnp.arange(d)[:, None] // (d // n_heads)
            == jnp.arange(n_heads)[None, :]).astype(jnp.float32)


def _pick_block_s(s: int, want: int = DEFAULT_BLOCK_S) -> int:
    """Largest 8-aligned divisor of ``s`` ≤ ``want`` (0 = none)."""
    if s <= want:
        return s if s % 8 == 0 or s == 1 else 0
    for b in range(want, 7, -1):
        if s % b == 0 and b % 8 == 0:
            return b
    return 0


def _kernel(pos_ref, q_ref, k_ref, v_ref, seg_ref, segt_ref, o_ref,
            m_ref, l_ref, acc_ref, *, block_s, n_blocks, scale):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    k = k_ref[0]                                   # (S_b, D)
    # q/o blocks stay whole-(B, D) resident (a (1, D) block would break
    # the (8, 128) tiling rule, and Mosaic rejects unaligned dynamic
    # sublane indexing) — the batch row is selected by iota mask
    bidx = jax.lax.broadcasted_iota(jnp.int32, q_ref.shape, 0)
    q = jnp.where(bidx == i, q_ref[...], 0).astype(jnp.float32).sum(
        axis=0, keepdims=True)                     # (1, D)
    seg = seg_ref[...]                             # (D, H) 0/1 f32
    # segmented per-head dot: (K ⊙ q) @ SEG — MXU does the 64-wide sums
    t = k.astype(jnp.float32) * q                  # (S_b, D)
    s_blk = jax.lax.dot_general(
        t, seg, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # (S_b, H)
    idx = j * block_s + jax.lax.broadcasted_iota(
        jnp.int32, s_blk.shape, 0)
    s_blk = jnp.where(idx <= pos_ref[0], s_blk, _NEG)

    m_prev = m_ref[...]                            # (1, H)
    l_prev = l_ref[...]
    m_new = jnp.maximum(m_prev, s_blk.max(axis=0, keepdims=True))
    corr = jnp.exp(m_prev - m_new)                 # (1, H)
    p = jnp.exp(s_blk - m_new)                     # (S_b, H)
    m_ref[...] = m_new
    l_ref[...] = l_prev * corr + p.sum(axis=0, keepdims=True)
    segt = segt_ref[...]                           # (H, D)
    p_lanes = jax.lax.dot_general(                 # (S_b, D)
        p, segt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    corr_lanes = jax.lax.dot_general(              # (1, D)
        corr, segt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    v = v_ref[0].astype(jnp.float32)               # (S_b, D)
    acc_ref[...] = (acc_ref[...] * corr_lanes
                    + (p_lanes * v).sum(axis=0, keepdims=True))

    @pl.when(j == n_blocks - 1)
    def _finish():
        l_lanes = jax.lax.dot_general(
            l_ref[...], segt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # write row i, preserve the others (the (B, D) block stays VMEM-
        # resident across the whole grid; rows fill in as i advances)
        val = (acc_ref[...] / l_lanes).astype(o_ref.dtype)
        o_ref[...] = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, o_ref.shape, 0) == i,
            val, o_ref[...])


@functools.partial(jax.jit, static_argnames=("n_heads", "head_dim",
                                             "block_s", "interpret"))
def decode_attend(q, kc, vc, pos, *, n_heads: int, head_dim: int,
                  block_s: int = DEFAULT_BLOCK_S, interpret: bool = False):
    """One decode tick's attention over the whole cache.

    ``q (B, H·hd)`` flat queries, ``kc/vc (B, S, H·hd)`` flat caches
    (positions > ``pos`` masked), returns ``ctx (B, H·hd)``.  Requires
    the q-head count to equal the cache's ``n_heads``; GQA decode goes
    through :func:`decode_attend_gqa` (the beam kernel).
    """
    b, s, d = kc.shape
    h = n_heads
    assert d == h * head_dim, (d, h, head_dim)
    bs = _pick_block_s(s, block_s)
    if bs == 0:
        raise ValueError(f"S={s} has no 8-aligned block ≤ {block_s}")
    n_blocks = s // bs
    scale = 1.0 / (head_dim ** 0.5)
    seg = _seg(d, h)
    vma = _inherit_vma(q, kc, vc)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(b, n_blocks),
        in_specs=[
            pl.BlockSpec((b, d), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((1, bs, d), lambda i, j, p_: (i, j, 0)),
            pl.BlockSpec((1, bs, d), lambda i, j, p_: (i, j, 0)),
            pl.BlockSpec((d, h), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((h, d), lambda i, j, p_: (0, 0)),
        ],
        out_specs=pl.BlockSpec((b, d), lambda i, j, p_: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ])
    return pl.pallas_call(
        functools.partial(_kernel, block_s=bs, n_blocks=n_blocks,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=_sds((b, d), q.dtype, vma=vma),
        interpret=interpret,
    )(jnp.asarray([pos], jnp.int32), q, kc, vc, seg, seg.T)


def _beam_kernel(pos_ref, q_ref, k_ref, v_ref, seg_ref, segt_ref, mask_ref,
                 acc_o_ref, m_o_ref, l_o_ref, m_ref, l_ref, acc_ref, *,
                 beams, block_s, n_blocks, scale, masked):
    """Beam variant: q rows [i·beams, (i+1)·beams) share batch row i's
    cache segment; per-row online-softmax state; outputs UNNORMALIZED
    (acc, m, l) so two segments (prompt + generated) merge outside with
    the standard flash combine.  ``masked`` selects the ancestry-mask
    operand (generated segment) vs fully-valid (prompt segment)."""
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kb = k_ref[0].astype(jnp.float32)              # (S_b, D)
    vb = v_ref[0].astype(jnp.float32)
    seg, segt = seg_ref[...], segt_ref[...]
    rows = jax.lax.broadcasted_iota(jnp.int32, q_ref.shape, 0)
    for s in range(beams):
        q = jnp.where(rows == i * beams + s, q_ref[...], 0).astype(
            jnp.float32).sum(axis=0, keepdims=True)           # (1, D)
        s_blk = jax.lax.dot_general(
            kb * q, seg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (S_b, H)
        if masked == "amask":
            # mask operand is f32: Mosaic only supports non-no-op minor-
            # dim insertion ([:, None]) on 32-bit types
            mrow = mask_ref[0, s, :][:, None]                 # (S_b, 1)
            s_blk = jnp.where(mrow > 0.5, s_blk, _NEG)
        elif masked == "pos":
            # position-validity from the prefetch scalar — zero HBM cost
            # (the GQA path's mask; an f32 operand here would stream
            # B·g·S·4 bytes per layer per tick)
            idx = j * block_s + jax.lax.broadcasted_iota(
                jnp.int32, s_blk.shape, 0)
            s_blk = jnp.where(idx <= pos_ref[0], s_blk, _NEG)
        m_prev = m_ref[s:s + 1, :]                            # (1, H)
        l_prev = l_ref[s:s + 1, :]
        m_new = jnp.maximum(m_prev, s_blk.max(axis=0, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_blk - m_new)
        m_ref[s:s + 1, :] = m_new
        l_ref[s:s + 1, :] = l_prev * corr + p.sum(axis=0, keepdims=True)
        p_lanes = jax.lax.dot_general(
            p, segt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        corr_lanes = jax.lax.dot_general(
            corr, segt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[s:s + 1, :] = (acc_ref[s:s + 1, :] * corr_lanes
                               + (p_lanes * vb).sum(axis=0, keepdims=True))

    @pl.when(j == n_blocks - 1)
    def _finish():
        orows = jax.lax.broadcasted_iota(jnp.int32, acc_o_ref.shape, 0)
        hrows = jax.lax.broadcasted_iota(jnp.int32, m_o_ref.shape, 0)
        for s in range(beams):
            r = i * beams + s
            acc_o_ref[...] = jnp.where(orows == r, acc_ref[s:s + 1, :],
                                       acc_o_ref[...])
            m_o_ref[...] = jnp.where(hrows == r, m_ref[s:s + 1, :],
                                     m_o_ref[...])
            l_o_ref[...] = jnp.where(hrows == r, l_ref[s:s + 1, :],
                                     l_o_ref[...])


@functools.partial(jax.jit, static_argnames=(
    "beams", "n_heads", "head_dim", "block_s", "interpret"))
def beam_attend_parts(q, kc, vc, amask=None, pos=None, *, beams: int,
                      n_heads: int, head_dim: int,
                      block_s: int = DEFAULT_BLOCK_S,
                      interpret: bool = False):
    """One cache SEGMENT's worth of beam attention, unnormalized.

    ``q (B·beams, H·hd)`` flat per-beam queries; ``kc/vc (B, S_seg,
    H·hd)`` a cache segment shared by each batch row's ``beams`` rows —
    the shared PROMPT cache (pass ``amask=None``: every position valid)
    or the flat per-slot GENERATED caches ``(B, slots·T, D)`` with
    ``amask (B, beams, S_seg)`` (any 0/1 dtype; carried as f32 in the
    kernel) = ancestry ∧ validity.  Returns
    ``(acc (B·beams, D) f32 unnormalized, m (B·beams, H) f32,
    l (B·beams, H) f32)``; merge segments with the flash combine
    (see ``merge_attend_parts``).

    Masking uses a finite ``-1e30`` sentinel, so a row with NO valid
    position in ``amask`` still yields finite ``(acc, m, l)`` that the
    merge cannot tell from real data — at least one segment per row must
    contain a valid position (``merge_attend_parts`` documents the same
    precondition; the always-present prompt segment satisfies it).
    """
    bk, d = q.shape
    b, s, _ = kc.shape
    assert bk == b * beams, (bk, b, beams)
    h = n_heads
    assert d == h * head_dim, (d, h, head_dim)
    bs = _pick_block_s(s, block_s)
    if bs == 0:
        raise ValueError(f"S={s} has no 8-aligned block ≤ {block_s}")
    n_blocks = s // bs
    scale = 1.0 / (head_dim ** 0.5)
    seg = _seg(d, h)
    masked = "amask" if amask is not None else (
        "pos" if pos is not None else "none")
    if amask is None:
        # tiny constant dummy keeps ONE kernel signature at ~zero DMA
        # (the pos/none modes never read it; an (b, beams, s) dummy
        # would stream B·beams·S·4 bytes per tick for nothing)
        amask = jnp.ones((1, beams, 8), jnp.float32)
        mask_spec = pl.BlockSpec((1, beams, 8), lambda i, j, p_: (0, 0, 0))
    else:
        mask_spec = pl.BlockSpec((1, beams, bs), lambda i, j, p_: (i, 0, j))
    vma = _inherit_vma(q, kc, vc)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(b, n_blocks),
        in_specs=[
            pl.BlockSpec((bk, d), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((1, bs, d), lambda i, j, p_: (i, j, 0)),
            pl.BlockSpec((1, bs, d), lambda i, j, p_: (i, j, 0)),
            pl.BlockSpec((d, h), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((h, d), lambda i, j, p_: (0, 0)),
            mask_spec,
        ],
        out_specs=[
            pl.BlockSpec((bk, d), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((bk, h), lambda i, j, p_: (0, 0)),
            pl.BlockSpec((bk, h), lambda i, j, p_: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((beams, h), jnp.float32),
            pltpu.VMEM((beams, h), jnp.float32),
            pltpu.VMEM((beams, d), jnp.float32),
        ])
    return pl.pallas_call(
        functools.partial(_beam_kernel, beams=beams, block_s=bs,
                          n_blocks=n_blocks, scale=scale, masked=masked),
        grid_spec=grid_spec,
        out_shape=[_sds((bk, d), jnp.float32, vma=vma),
                   _sds((bk, h), jnp.float32, vma=vma),
                   _sds((bk, h), jnp.float32, vma=vma)],
        interpret=interpret,
    )(jnp.asarray([0 if pos is None else pos], jnp.int32), q, kc, vc,
      seg, seg.T, amask.astype(jnp.float32))


def merge_attend_parts(parts, n_heads: int, head_dim: int, dtype):
    """Flash combine of ≥2 ``(acc, m, l)`` segments → normalized context
    ``(B·beams, H·hd)`` in ``dtype``.

    PRECONDITION: every output row must have at least one VALID (unmasked)
    key position across the segments.  A fully-masked row cannot be
    detected here — masking uses a finite ``-1e30`` sentinel, so such a
    row arrives with ``m = -1e30`` and ``l = S`` (every masked score
    contributes ``exp(0)``), which is indistinguishable from real data and
    would merge into silently junk context.  Every in-tree caller
    satisfies this: the prompt segment is always present and position 0 is
    always valid (``decode_attend``/``beam_attend_parts`` mask by
    ``pos``-validity or ancestry, never the whole row).  The ``l > 0``
    guard below only covers the benign exact-zero case (an all-zero
    partial segment from :func:`zeros_like` initialization), returning
    zeros instead of 0/0 NaNs.
    """
    d = n_heads * head_dim
    seg_t = _seg(d, n_heads).T

    def lanes(x):  # (N, H) -> (N, D) per-head broadcast
        return x @ seg_t

    m = functools.reduce(jnp.maximum, [p[1] for p in parts])
    l_tot = 0.0
    acc_tot = 0.0
    for acc, m_i, l_i in parts:
        a = jnp.exp(m_i - m)
        l_tot = l_tot + l_i * a
        acc_tot = acc_tot + acc * lanes(a)
    den = lanes(l_tot)
    ctx = acc_tot / jnp.maximum(den, 1e-30)
    return jnp.where(den > 0, ctx, 0.0).astype(dtype)


def decode_attend_gqa(q, kc, vc, pos, *, n_q_heads: int, n_kv_heads: int,
                      head_dim: int, block_s: int = DEFAULT_BLOCK_S,
                      interpret: bool = False):
    """GQA decode tick: grouped queries against the shared-KV-head cache.

    Structurally the BEAM problem: the ``g = n_q_heads/n_kv_heads`` query
    groups of batch row ``b`` all attend batch row ``b``'s cache — so the
    beam kernel serves GQA verbatim with ``beams=g`` and the position-
    validity mask from the prefetch scalar (``masked='pos'``).  The
    cache still streams ONCE per tick (grid is (B, S-blocks); the g
    groups iterate in-register) — GQA's inference payoff is preserved.

    ``q (B, Hq·hd)`` head-major flat; ``kc/vc (B, S, Hkv·hd)``; returns
    ``ctx (B, Hq·hd)``.  Group convention matches ``parallel/decode.py``:
    q-head h uses KV head ``h // g`` (head-major reshape to
    ``(Hkv, g, hd)``).
    """
    b, s, d_kv = kc.shape
    g = n_q_heads // n_kv_heads
    if n_q_heads % n_kv_heads or g < 1:
        raise ValueError(f"bad head ratio {n_q_heads}/{n_kv_heads}")
    # (B, Hkv, g, hd) -> group-major rows (B·g, Hkv·hd), b-major like the
    # beam kernel's row->cache mapping expects
    q_g = q.reshape(b, n_kv_heads, g, head_dim).transpose(0, 2, 1, 3) \
        .reshape(b * g, n_kv_heads * head_dim)
    # position validity rides the prefetch scalar (masked='pos') — an
    # f32 mask operand would stream B·g·S·4 bytes per layer per tick
    part = beam_attend_parts(q_g, kc, vc, None, pos, beams=g,
                             n_heads=n_kv_heads, head_dim=head_dim,
                             block_s=block_s, interpret=interpret)
    ctx_g = merge_attend_parts([part], n_heads=n_kv_heads,
                               head_dim=head_dim, dtype=q.dtype)
    return ctx_g.reshape(b, g, n_kv_heads, head_dim) \
        .transpose(0, 2, 1, 3).reshape(b, n_q_heads * head_dim)
