"""Multi-node optimizer wrappers.

Reference parity: ``chainermn/optimizers.py`` [uv] (SURVEY.md §2.4):

* ``create_multi_node_optimizer(actual_optimizer, communicator,
  double_buffering=False)`` — wraps any optimizer so that ``update`` first
  averages gradients across ranks, then applies the wrapped optimizer.
* ``_DoubleBufferingOptimizer`` — overlaps the allreduce of step *t*'s
  gradients with step *t+1*'s compute by applying the 1-step-stale averaged
  gradients (SURVEY.md §3.3).

TPU-native: the "optimizer" is an ``optax.GradientTransformation`` and the
wrapper is itself one, so it composes with the whole optax ecosystem.  The
gradient average is ``lax.pmean`` *inside* the jitted SPMD step — XLA fuses
it into the step program and schedules the ICI transfer to overlap with
backprop (the reference needed hand-written CUDA-stream double buffering to
get that overlap; under XLA the async scheduler does it, and the
double-buffering variant below exists to reproduce the reference's *stale
gradient semantics*, which its tests depend on).

Under plain pjit (shardings instead of an explicit axis) the axis is unbound
and ``pmean_if_bound`` is identity: XLA's sharding propagation already
produces globally-averaged gradients from a mean loss over the global batch.

Note on shard_map semantics (JAX ≥0.9 VMA tracking): autodiff w.r.t.
*replicated* params inserts the cross-rank psum of cotangents itself, so
gradients arriving here are already global and replicated — and
``pmean_if_bound`` of a replicated value is identity, so the wrapper is
correct in every regime: real averaging under ``pmap``/per-device params,
no-op under shard_map-with-replicated-params and under pjit.  The train-step
builder (`chainermn_tpu.train`) differentiates ``pmean(loss)`` so the
AD-inserted psum carries the 1/size factor.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from .communicators.base import CommunicatorBase
from .ops.collective import _axis_bound, pmean, pmean_if_bound
from .topology import DEFAULT_AXIS_NAME


def _resolve_axis(communicator: Union[CommunicatorBase, str, None]) -> Optional[str]:
    if communicator is None:
        return DEFAULT_AXIS_NAME
    if isinstance(communicator, (str, tuple, list)):
        return communicator
    return getattr(communicator, "axis_name", DEFAULT_AXIS_NAME)


def compressed_mean(grads, axis_name: Optional[str], allreduce_grad_dtype=None):
    """Cross-rank gradient mean, optionally wire-compressed to a smaller dtype.

    Reference analog: ``PureNcclCommunicator.allreduce_grad_dtype``
    (communicators/pure_nccl_communicator.py [uv]) — fp16 cast fused before
    the NCCL ring, divide+cast-back fused after.  Here the casts bracket the
    ``pmean`` so XLA lowers the ICI all-reduce itself in the reduced dtype
    (half the bytes on the wire for bf16), and XLA fuses the casts into the
    neighboring ops — the CuPy ``_get_converting_kernel`` machinery for free.

    Each leaf is cast back to its original dtype after the reduction, so the
    optimizer update always runs at model precision.
    """
    def already_reduced(g):
        # Provably replication-invariant over the axis (shard_map VMA type):
        # a second reduction would be pure wasted wire — the train-step
        # builders reduce local grads themselves, then hand the result to
        # the optax wrapper, which must not reduce AGAIN.  No vma attribute
        # (pmap, older tracers) proves nothing → reduce.
        vma = getattr(getattr(g, "aval", None), "vma", None)
        if vma is None:
            return False
        names = (axis_name if isinstance(axis_name, (tuple, list))
                 else (axis_name,))
        return not any(n in vma for n in names)

    if allreduce_grad_dtype is None:
        return jax.tree_util.tree_map(
            lambda g: g if already_reduced(g) else pmean_if_bound(g, axis_name),
            grads)
    wire = jnp.dtype(allreduce_grad_dtype)

    if jnp.issubdtype(wire, jnp.integer):
        # int8 path: a hand-scheduled quantized ring all-reduce (~1
        # byte/element on the wire vs the reference's 2-byte fp16 best).
        # Needs a bound axis — the quantized schedule is explicit ppermutes;
        # under plain pjit (unbound axis) the gradients are already globally
        # reduced and there is no wire leg left to compress.
        from .ops.collective import quantized_ring_pmean

        if axis_name is None or not _axis_bound(axis_name):
            return grads
        return jax.tree_util.tree_map(
            lambda g: g if already_reduced(g)
            else quantized_ring_pmean(g, axis_name, wire), grads)

    def one(g):
        if already_reduced(g):
            return g
        return pmean_if_bound(g.astype(wire), axis_name).astype(g.dtype)

    return jax.tree_util.tree_map(one, grads)


def gradient_average(communicator=None, allreduce_grad_dtype=None) -> optax.GradientTransformation:
    """An optax transform that means gradients across the communicator axis.

    Reference analog: ``communicator.multi_node_mean_grad(model)`` called by
    ``_MultiNodeOptimizer.update`` [uv] — but fused into the step program.

    ``allreduce_grad_dtype`` (e.g. ``'bfloat16'``) runs the cross-rank mean
    in that dtype (see :func:`compressed_mean`).  NOTE: this only compresses
    the wire when the gradients arriving here are still *per-rank local*
    (varying over the axis) — the train-step builders arrange that when given
    the same knob.  If gradients are already globally reduced (the default
    pjit/AD-inserted-psum path), the pmean is a trace-time identity and the
    cast merely simulates the precision loss.
    """
    axis_name = _resolve_axis(communicator)

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return compressed_mean(updates, axis_name, allreduce_grad_dtype), state

    return optax.GradientTransformation(init_fn, update_fn)


def hierarchical_gradient_average(chip_axis: str = "chip",
                                  slice_axis: str = "slice",
                                  dcn_dtype=None) -> optax.GradientTransformation:
    """Two-tier gradient mean over a multislice ``('slice','chip')`` mesh.

    Reference analog: ``HierarchicalCommunicator`` [uv] — the fast-fabric-
    first allreduce, rebuilt for ICI×DCN (see
    :func:`chainermn_tpu.ops.collective.hierarchical_pmean`; mesh from
    :func:`chainermn_tpu.topology.make_multislice_mesh`).  ``dcn_dtype``
    compresses only the cross-slice leg.  Feed the train-step builder
    local (varying) gradients — e.g. via ``make_train_step(...,
    grad_reduce=...)`` — so this transform's collectives are the wire ops.
    """
    from .ops.collective import hierarchical_pmean

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        chip, slc = _axis_bound(chip_axis), _axis_bound(slice_axis)
        if chip and slc:
            updates = hierarchical_pmean(updates, chip_axis, slice_axis, dcn_dtype)
        elif chip:
            # Single-slice run (no slice axis in the mesh): the ICI mean is
            # still mandatory — skipping reduction entirely here would
            # silently diverge per-rank params.
            updates = pmean(updates, chip_axis)
        elif slc:
            # Degenerate one-chip-per-slice mesh: only the DCN leg exists.
            updates = compressed_mean(updates, slice_axis, dcn_dtype)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


class DoubleBufferState(NamedTuple):
    inner: optax.OptState
    stale_grads: optax.Updates  # averaged grads of the previous step


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator=None,
    double_buffering: bool = False,
    zero_fill: bool = True,
    allreduce_grad_dtype=None,
) -> optax.GradientTransformation:
    """Wrap ``actual_optimizer`` with cross-rank gradient averaging.

    Reference: ``create_multi_node_optimizer`` [uv].  ``zero_fill`` mirrors
    the reference flag: the double-buffered first step applies zero updates
    (gradient buffers start zero-filled).  ``allreduce_grad_dtype`` is the
    reference's fp16-compressed-allreduce knob
    (``pure_nccl_communicator.py :: allreduce_grad_dtype`` [uv]); pass
    ``'bfloat16'`` to halve gradient bytes on the wire — see
    :func:`gradient_average` for when the compression is physical vs
    simulated.
    """
    if not double_buffering:
        return optax.chain(
            gradient_average(communicator, allreduce_grad_dtype), actual_optimizer)

    axis_name = _resolve_axis(communicator)

    def init_fn(params):
        if not zero_fill:
            raise NotImplementedError(
                "double_buffering requires zero_fill=True (matches reference: "
                "grad buffers start zeroed)")
        zeros = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
        return DoubleBufferState(inner=actual_optimizer.init(params), stale_grads=zeros)

    def update_fn(grads, state, params=None):
        # Average THIS step's grads (XLA overlaps the collective with
        # whatever compute follows), but apply the PREVIOUS step's average —
        # exactly the reference's 1-step staleness.
        fresh = compressed_mean(grads, axis_name, allreduce_grad_dtype)
        updates, inner = actual_optimizer.update(state.stale_grads, state.inner, params)
        return updates, DoubleBufferState(inner=inner, stale_grads=fresh)

    return optax.GradientTransformation(init_fn, update_fn)
