"""Multi-node optimizer wrappers.

Reference parity: ``chainermn/optimizers.py`` [uv] (SURVEY.md §2.4):

* ``create_multi_node_optimizer(actual_optimizer, communicator,
  double_buffering=False)`` — wraps any optimizer so that ``update`` first
  averages gradients across ranks, then applies the wrapped optimizer.
* ``_DoubleBufferingOptimizer`` — overlaps the allreduce of step *t*'s
  gradients with step *t+1*'s compute by applying the 1-step-stale averaged
  gradients (SURVEY.md §3.3).

TPU-native: the "optimizer" is an ``optax.GradientTransformation`` and the
wrapper is itself one, so it composes with the whole optax ecosystem.  The
gradient average is ``lax.pmean`` *inside* the jitted SPMD step — XLA fuses
it into the step program and schedules the ICI transfer to overlap with
backprop (the reference needed hand-written CUDA-stream double buffering to
get that overlap; under XLA the async scheduler does it, and the
double-buffering variant below exists to reproduce the reference's *stale
gradient semantics*, which its tests depend on).

Under plain pjit (shardings instead of an explicit axis) the axis is unbound
and ``pmean_if_bound`` is identity: XLA's sharding propagation already
produces globally-averaged gradients from a mean loss over the global batch.

Note on shard_map semantics (JAX ≥0.9 VMA tracking): autodiff w.r.t.
*replicated* params inserts the cross-rank psum of cotangents itself, so
gradients arriving here are already global and replicated — and
``pmean_if_bound`` of a replicated value is identity, so the wrapper is
correct in every regime: real averaging under ``pmap``/per-device params,
no-op under shard_map-with-replicated-params and under pjit.  The train-step
builder (`chainermn_tpu.train`) differentiates ``pmean(loss)`` so the
AD-inserted psum carries the 1/size factor.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from .communicators.base import CommunicatorBase
from .ops.collective import (DEFAULT_QUANT_BLOCK, _axis_bound, pmean,
                             pmean_if_bound)
from .topology import DEFAULT_AXIS_NAME


class ErrorFeedbackState(NamedTuple):
    """Per-rank quantization residuals of the int8 gradient bucket.

    ``residuals`` is ONE fp32 leaf of GLOBAL shape ``(world, n_total)``
    — row ``r`` is rank ``r``'s unsent error mass, sharded over the data
    axis by :func:`opt_state_partition_specs` so each rank reads/writes
    only its own ``(1, n_total)`` block inside the step (EF-SGD:
    ``v = g + e``, send ``Q(v)``, keep ``e' = v - Q(v)``).  It is
    checkpoint state: :func:`error_feedback_layout` gives the v2
    manifest layout, :func:`fold_error_feedback` the world-size
    re-partition for elastic resume / live shrink.
    """

    residuals: Any


def _resolve_axis(communicator: Union[CommunicatorBase, str, None]) -> Optional[str]:
    if communicator is None:
        return DEFAULT_AXIS_NAME
    if isinstance(communicator, (str, tuple, list)):
        return communicator
    return getattr(communicator, "axis_name", DEFAULT_AXIS_NAME)


def _bucket(grads):
    """Flatten a gradient pytree into ONE fp32 vector (+ the recipe to
    split it back).  The reference's ``_memory_utility`` bucketing,
    jit-side: one ring call per step instead of one per leaf — fewer
    per-hop ops AND one ledger row at the bucket's true byte size."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    sizes = [int(l.size) for l in leaves]
    flat = jnp.concatenate([l.ravel().astype(jnp.float32) for l in leaves])

    def unbucket(vec):
        out, off = [], 0
        for l, s in zip(leaves, sizes):
            out.append(vec[off:off + s].reshape(l.shape).astype(l.dtype))
            off += s
        return jax.tree_util.tree_unflatten(treedef, out)

    return flat, unbucket


def compressed_mean(grads, axis_name: Optional[str], allreduce_grad_dtype=None,
                    quant_block: int = DEFAULT_QUANT_BLOCK,
                    quant_pipeline: int = 1, residuals=None):
    """Cross-rank gradient mean, optionally wire-compressed to a smaller dtype.

    Reference analog: ``PureNcclCommunicator.allreduce_grad_dtype``
    (communicators/pure_nccl_communicator.py [uv]) — fp16 cast fused before
    the NCCL ring, divide+cast-back fused after.  Here the casts bracket the
    ``pmean`` so XLA lowers the ICI all-reduce itself in the reduced dtype
    (half the bytes on the wire for bf16), and XLA fuses the casts into the
    neighboring ops — the CuPy ``_get_converting_kernel`` machinery for free.

    Each leaf is cast back to its original dtype after the reduction, so the
    optimizer update always runs at model precision.

    Integer ``allreduce_grad_dtype`` (int8) runs the BLOCK-SCALED
    quantized ring (:func:`~chainermn_tpu.ops.collective
    .quantized_ring_pmean`): the whole tree is bucketed into one flat
    vector (one ring call, one ledger row), with ``quant_block`` fp32
    scale granularity and ``quant_pipeline`` sub-chunk pipelining.
    ``residuals`` (the ``(1, n_total)`` per-rank block of an
    :class:`ErrorFeedbackState` leaf) switches on error feedback:
    the corrected bucket ``v = g + e`` goes on the wire and the return
    becomes ``(mean_tree, new_residuals)`` with ``e' = v - Dq(Q(v))`` —
    the EF-SGD update that makes the compounding per-hop quantization
    error unbiased across steps.
    """
    def already_reduced(g):
        # Provably replication-invariant over the axis (shard_map VMA type):
        # a second reduction would be pure wasted wire — the train-step
        # builders reduce local grads themselves, then hand the result to
        # the optax wrapper, which must not reduce AGAIN.  No vma attribute
        # (pmap, older tracers) proves nothing → reduce.
        vma = getattr(getattr(g, "aval", None), "vma", None)
        if vma is None:
            return False
        names = (axis_name if isinstance(axis_name, (tuple, list))
                 else (axis_name,))
        return not any(n in vma for n in names)

    if allreduce_grad_dtype is None:
        assert residuals is None, "error feedback requires an int wire dtype"
        return jax.tree_util.tree_map(
            lambda g: g if already_reduced(g) else pmean_if_bound(g, axis_name),
            grads)
    wire = jnp.dtype(allreduce_grad_dtype)

    if jnp.issubdtype(wire, jnp.integer):
        # int8 path: the block-scaled quantized ring (~1 byte/element on
        # the wire vs the reference's 2-byte fp16 best), over ONE flat
        # bucket of the whole tree.  Needs a bound axis — the quantized
        # schedule is explicit ppermutes; under plain pjit (unbound axis)
        # the gradients are already globally reduced and there is no wire
        # leg left to compress.
        from .ops.collective import (block_dequantize, block_quantize,
                                     quantized_ring_pmean)

        if axis_name is None or not _axis_bound(axis_name):
            return grads if residuals is None else (grads, residuals)
        if all(already_reduced(g) for g in jax.tree_util.tree_leaves(grads)):
            # provably-global grads: no wire leg left to compress, and
            # EF would feed back an error that was never incurred
            return grads if residuals is None else (grads, residuals)
        from ._compat import axis_size as _axis_size
        p = _axis_size(axis_name)
        flat, unbucket = _bucket(grads)
        if residuals is None:
            if p == 1:
                return grads
            return unbucket(quantized_ring_pmean(
                flat, axis_name, wire, quant_block, quant_pipeline))
        # Error feedback: residuals arrive as this rank's (1, n) block of
        # the (world, n) sharded state leaf (opt_state_partition_specs).
        # A full-world block here means the state was fed in replicated —
        # each rank would then update a DIFFERENT row of a supposedly
        # replicated array and silently drop every other rank's error.
        if p > 1 and residuals.shape[0] != 1:
            raise ValueError(
                f"error-feedback residual block has leading dim "
                f"{residuals.shape[0]} (expected 1): the residual state "
                f"leaf must be sharded over '{axis_name}' — build the "
                "step with error_feedback=True (make_train_step) or "
                "shard it via opt_state_partition_specs")
        if residuals.shape[-1] != flat.shape[0]:
            raise ValueError(
                f"error-feedback residual holds {residuals.shape[-1]} "
                f"elements but the gradient bucket holds {flat.shape[0]} "
                "— the optimizer was initialized against different params")
        if p == 1:
            return grads, residuals
        v = flat + residuals[0]
        mean = unbucket(quantized_ring_pmean(
            v, axis_name, wire, quant_block, quant_pipeline))
        # e' = v - Dq(Q(v)): the first-quantization residual, computed
        # with the SAME effective block the wire uses.  The ring clamps
        # the block to the per-rank CHUNK (_ring_layout) — quantizing
        # the residual at the raw quant_block instead would use coarser
        # blocks whenever chunk < quant_block and re-inject gradient
        # mass the fine-grained wire already delivered, a systematic
        # training bias.  chunk_len is a multiple of eff_block, so the
        # residual's block grid aligns with the wire's chunk grid.
        from .ops.collective import _ring_layout
        _, eff_block, _, _ = _ring_layout(
            int(v.shape[0]), p, quant_block, quant_pipeline)
        q, scales = block_quantize(v, wire, eff_block)
        new_res = (v - block_dequantize(q, scales, n_elements=v.shape[0]))
        return mean, new_res[None]

    def one(g):
        if already_reduced(g):
            return g
        return pmean_if_bound(g.astype(wire), axis_name).astype(g.dtype)

    return jax.tree_util.tree_map(one, grads)


def _resolve_world(communicator, world: Optional[int]) -> int:
    """World size for EF residual allocation: explicit ``world=`` wins,
    else the communicator's size.  Loud when neither is available —
    silently allocating a 1-row residual for an 8-rank gang would shear
    the state layout at first step."""
    if world is not None:
        return int(world)
    size = getattr(communicator, "size", None)
    if size is None:
        raise ValueError(
            "error_feedback=True needs the world size to allocate the "
            "per-rank residual rows: pass a real communicator (xla/naive) "
            "or world=<axis size> explicitly")
    return int(size)


def _ef_init(params, world: int) -> ErrorFeedbackState:
    """Zero residuals: ONE (world, n_total) fp32 leaf over the bucketed
    gradient size (``zero_fill`` semantics — the first step's wire
    carries the raw gradients)."""
    n_total = sum(int(jnp.size(l))
                  for l in jax.tree_util.tree_leaves(params))
    return ErrorFeedbackState(
        residuals=jnp.zeros((int(world), n_total), jnp.float32))


def gradient_average(communicator=None, allreduce_grad_dtype=None,
                     error_feedback: bool = False,
                     quant_block: int = DEFAULT_QUANT_BLOCK,
                     quant_pipeline: int = 1,
                     world: Optional[int] = None) -> optax.GradientTransformation:
    """An optax transform that means gradients across the communicator axis.

    Reference analog: ``communicator.multi_node_mean_grad(model)`` called by
    ``_MultiNodeOptimizer.update`` [uv] — but fused into the step program.

    ``allreduce_grad_dtype`` (e.g. ``'bfloat16'``) runs the cross-rank mean
    in that dtype (see :func:`compressed_mean`).  NOTE: this only compresses
    the wire when the gradients arriving here are still *per-rank local*
    (varying over the axis) — the train-step builders arrange that when given
    the same knob.  If gradients are already globally reduced (the default
    pjit/AD-inserted-psum path), the pmean is a trace-time identity and the
    cast merely simulates the precision loss.

    ``error_feedback=True`` (int wire dtypes only) keeps the per-rank
    quantization residual in the transform's state
    (:class:`ErrorFeedbackState`) and folds it into the next step's
    bucket — build the step with ``make_train_step(...,
    error_feedback=True)`` so the residual leaf is sharded per rank.
    """
    axis_name = _resolve_axis(communicator)
    if error_feedback:
        if allreduce_grad_dtype is None or not jnp.issubdtype(
                jnp.dtype(allreduce_grad_dtype), jnp.integer):
            raise ValueError(
                "error_feedback=True requires an integer "
                f"allreduce_grad_dtype, got {allreduce_grad_dtype!r}")
        ef_world = _resolve_world(communicator, world)

    def init_fn(params):
        if error_feedback:
            return _ef_init(params, ef_world)
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        if error_feedback:
            mean, new_res = compressed_mean(
                updates, axis_name, allreduce_grad_dtype,
                quant_block=quant_block, quant_pipeline=quant_pipeline,
                residuals=state.residuals)
            return mean, ErrorFeedbackState(residuals=new_res)
        return compressed_mean(
            updates, axis_name, allreduce_grad_dtype,
            quant_block=quant_block, quant_pipeline=quant_pipeline), state

    return optax.GradientTransformation(init_fn, update_fn)


def hierarchical_gradient_average(chip_axis: str = "chip",
                                  slice_axis: str = "slice",
                                  dcn_dtype=None) -> optax.GradientTransformation:
    """Two-tier gradient mean over a multislice ``('slice','chip')`` mesh.

    Reference analog: ``HierarchicalCommunicator`` [uv] — the fast-fabric-
    first allreduce, rebuilt for ICI×DCN (see
    :func:`chainermn_tpu.ops.collective.hierarchical_pmean`; mesh from
    :func:`chainermn_tpu.topology.make_multislice_mesh`).  ``dcn_dtype``
    compresses only the cross-slice leg.  Feed the train-step builder
    local (varying) gradients — e.g. via ``make_train_step(...,
    grad_reduce=...)`` — so this transform's collectives are the wire ops.
    """
    from .ops.collective import hierarchical_pmean

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        chip, slc = _axis_bound(chip_axis), _axis_bound(slice_axis)
        if chip and slc:
            updates = hierarchical_pmean(updates, chip_axis, slice_axis, dcn_dtype)
        elif chip:
            # Single-slice run (no slice axis in the mesh): the ICI mean is
            # still mandatory — skipping reduction entirely here would
            # silently diverge per-rank params.
            updates = pmean(updates, chip_axis)
        elif slc:
            # Degenerate one-chip-per-slice mesh: only the DCN leg exists.
            updates = compressed_mean(updates, slice_axis, dcn_dtype)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


class DoubleBufferState(NamedTuple):
    inner: optax.OptState
    stale_grads: optax.Updates  # averaged grads of the previous step
    #: ErrorFeedbackState in the combined quantized+double-buffered mode
    #: (the int8 ring of step k overlaps step k+1's forward/backward,
    #: residuals ride along); empty tuple otherwise.
    ef: Any = ()


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator=None,
    double_buffering: bool = False,
    zero_fill: bool = True,
    allreduce_grad_dtype=None,
    error_feedback: bool = False,
    quant_block: int = DEFAULT_QUANT_BLOCK,
    quant_pipeline: int = 1,
    world: Optional[int] = None,
) -> optax.GradientTransformation:
    """Wrap ``actual_optimizer`` with cross-rank gradient averaging.

    Reference: ``create_multi_node_optimizer`` [uv].  ``zero_fill`` mirrors
    the reference flag: the double-buffered first step applies zero updates
    (gradient buffers start zero-filled).  ``allreduce_grad_dtype`` is the
    reference's fp16-compressed-allreduce knob
    (``pure_nccl_communicator.py :: allreduce_grad_dtype`` [uv]); pass
    ``'bfloat16'`` to halve gradient bytes on the wire — see
    :func:`gradient_average` for when the compression is physical vs
    simulated.

    ``allreduce_grad_dtype='int8'`` runs the block-scaled quantized ring
    over ONE bucket of the whole gradient tree (``quant_block`` elements
    per fp32 scale, ``quant_pipeline`` sub-chunks per hop);
    ``error_feedback=True`` adds the EF-SGD residual state
    (:class:`ErrorFeedbackState` — build the step with
    ``make_train_step(..., error_feedback=True)``).  Combining
    ``double_buffering=True`` with the int8 wire is the
    quantized+double-buffered mode: the ring of step ``k`` (1/4 the
    bytes) overlaps step ``k+1``'s forward/backward, and the staleness
    semantics are unchanged.
    """
    if not double_buffering:
        return optax.chain(
            gradient_average(communicator, allreduce_grad_dtype,
                             error_feedback=error_feedback,
                             quant_block=quant_block,
                             quant_pipeline=quant_pipeline,
                             world=world),
            actual_optimizer)

    axis_name = _resolve_axis(communicator)
    if error_feedback:
        if allreduce_grad_dtype is None or not jnp.issubdtype(
                jnp.dtype(allreduce_grad_dtype), jnp.integer):
            raise ValueError(
                "error_feedback=True requires an integer "
                f"allreduce_grad_dtype, got {allreduce_grad_dtype!r}")
        ef_world = _resolve_world(communicator, world)

    def init_fn(params):
        if not zero_fill:
            raise NotImplementedError(
                "double_buffering requires zero_fill=True (matches reference: "
                "grad buffers start zeroed)")
        zeros = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
        ef = _ef_init(params, ef_world) if error_feedback else ()
        return DoubleBufferState(inner=actual_optimizer.init(params),
                                 stale_grads=zeros, ef=ef)

    def update_fn(grads, state, params=None):
        # Average THIS step's grads (XLA overlaps the collective with
        # whatever compute follows), but apply the PREVIOUS step's average —
        # exactly the reference's 1-step staleness.
        if error_feedback:
            fresh, new_res = compressed_mean(
                grads, axis_name, allreduce_grad_dtype,
                quant_block=quant_block, quant_pipeline=quant_pipeline,
                residuals=state.ef.residuals)
            ef = ErrorFeedbackState(residuals=new_res)
        else:
            fresh = compressed_mean(
                grads, axis_name, allreduce_grad_dtype,
                quant_block=quant_block, quant_pipeline=quant_pipeline)
            ef = state.ef
        updates, inner = actual_optimizer.update(state.stale_grads, state.inner, params)
        return updates, DoubleBufferState(inner=inner, stale_grads=fresh,
                                          ef=ef)

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# Error-feedback state plumbing: step specs, checkpoint layout, elastic fold
# ---------------------------------------------------------------------------

def _is_ef(node) -> bool:
    return isinstance(node, ErrorFeedbackState)


def opt_state_partition_specs(opt_state, axis_name: str = DEFAULT_AXIS_NAME):
    """Per-leaf ``PartitionSpec`` tree for an optimizer state holding
    :class:`ErrorFeedbackState` nodes: residual leaves shard their
    leading (rank) axis over ``axis_name``, everything else replicates.

    This is what ``make_train_step(..., error_feedback=True)`` feeds
    shard_map's ``in_specs``/``out_specs`` for the opt-state argument —
    a plain ``P()`` would make every rank write its own row into a
    "replicated" buffer and silently drop all but one rank's residuals.
    """
    from jax.sharding import PartitionSpec as P

    def one(node):
        if _is_ef(node):
            return ErrorFeedbackState(residuals=jax.tree_util.tree_map(
                lambda _: P(axis_name), node.residuals))
        return jax.tree_util.tree_map(lambda _: P(), node)

    return jax.tree_util.tree_map(one, opt_state, is_leaf=_is_ef)


def error_feedback_layout(opt_state, prefix: str = "") -> dict:
    """v2-manifest checkpoint ``layout`` entries for the EF residual
    leaves: dotted leaf path → ``["sharded", 0]`` (rows partition by
    rank), merged into ``create_multi_node_checkpointer(layout=...)`` so
    a multi-controller gang's shards carry the rank rows and
    ``reshard_host`` reassembles them on elastic resume.  ``prefix``
    prepends the opt state's own path inside the saved state tree."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            opt_state, is_leaf=_is_ef)[0]:
        if _is_ef(leaf):
            for sub, _ in jax.tree_util.tree_flatten_with_path(leaf)[0]:
                out[prefix + jax.tree_util.keystr(tuple(path) + tuple(sub))
                    ] = ["sharded", 0]
    return out


def fold_error_feedback(residuals, new_world: int):
    """Re-partition an EF residual array ``(old_world, n)`` for a new
    world size, preserving the EF invariant: the applied correction mass
    per step is ``(1/p)·Σ_r e_r``, so

    * shrink (``new | old``): new rank ``r`` SUMS its inherited rows,
      scaled by ``new/old`` — ``(1/p')·Σ e' == (1/p)·Σ e`` exactly (the
      PR 13 live-shrink hook: call this in the ``heal()`` repartition
      alongside the momentum blocks);
    * growth (``old | new``): rows repeat onto the new ranks (each new
      rank re-derives from its ancestor; the invariant again holds
      exactly).

    Non-divisible world changes raise — a fractional row split has no
    exact invariant."""
    import numpy as np

    res = np.asarray(residuals)
    old = res.shape[0]
    new_world = int(new_world)
    if new_world < 1:
        raise ValueError(f"new_world must be >= 1, got {new_world}")
    if old == new_world:
        return res
    if old % new_world == 0:
        fold = old // new_world
        return (res.reshape(new_world, fold, -1).sum(axis=1)
                * (new_world / old)).astype(res.dtype)
    if new_world % old == 0:
        return np.repeat(res, new_world // old, axis=0)
    raise ValueError(
        f"cannot fold EF residuals {old} -> {new_world}: world sizes "
        "must divide one another (shrink sums inherited rows, growth "
        "repeats them)")
