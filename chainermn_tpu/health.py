"""Transport-agnostic health plane: leases, epochs, breakers, consensus.

ISSUE 13 promotes the supervision primitives ISSUE 10 built for the
serving fleet out of ``serving/health.py`` into a core every side of the
system shares — the serving fleet keeps importing them through the old
path (``serving/health.py`` re-exports), and the TRAINING gang now runs
the same plane per rank (``extensions/gang.py``).  Everything here is
jax-free and fuzzable standalone:

* **Leases** (:class:`HeartbeatPublisher` / :class:`LeaseTable`) — each
  member publishes a heartbeat lease (role, epoch, seq, free-form
  state) under its OWN lane tag, overwritten every beat.  That is the
  ``allgather_obj_eventual`` pattern applied to liveness: a bounded
  per-publisher side channel, deliberately NOT a gang collective — a
  dead member is simply ABSENT (its lease stops refreshing), it can
  never wedge the readers.
* **Detection-window math** (:func:`detection_window_s`) — the reader
  clocks a lease by when IT saw a new sequence number (receiver-side
  monotonic time, so publisher clock skew is irrelevant).  A member
  beating every ``beat_interval_s`` that misses ``miss_beats``
  consecutive beats is declared dead after at most ``beat_interval_s *
  (miss_beats + 1)`` seconds — the ``+1`` covers the worst-case phase
  offset between the last accepted beat and the first missed one
  (docs/ROBUSTNESS.md "Serving failure domains" / "Training failure
  domains").
* **Epoch fencing** (:class:`EpochFence`) — every admission mints a
  monotonic epoch; marking a member dead FENCES its epoch, and every
  lease, token, result, or slab stamped with a fenced epoch is refused
  and counted.  A paused-then-resumed zombie can therefore never land
  anything: its writes carry the old epoch, and re-admission always
  mints a new one.
* **Circuit breaker** (:class:`CircuitBreaker`) — re-admission of a
  flapping member is governed by a retry budget + exponential backoff;
  past the budget the circuit opens permanently.
* **Membership consensus** (:class:`MembershipConsensus`) — the
  training gang's checkpoint-free live-shrink agreement: a pure,
  message-driven state machine (no clocks, no sleeps) every survivor
  drives over the lease side channel.  Either all survivors land on the
  IDENTICAL new gang, or the disagreeing member raises loudly
  (:class:`GangFencedError` / :class:`GangConsensusError`) — never a
  silent hang, never a split brain.  Fuzzed over thousands of
  delayed/duplicated/stale message schedules in tests/test_gang.py.
* **Collective watchdog** (:class:`CollectiveGuard`) — a bounded-timeout
  guard threaded through the accounted collective face
  (``observability/comm.py`` wraps every eager communicator collective
  and ``ops.collective`` call): when a collective exceeds the window,
  the guard consults the lease table (``lost_ranks_fn``), dumps a
  ``rank_lost`` flight bundle NAMING the missing rank(s), and aborts
  loudly — today's alternative is an anonymous lane timeout minutes
  later.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

#: Wire schema of one published lease.
LEASE_SCHEMA = "chainermn_tpu.lease.v1"

#: Wire schema of one membership-consensus proposal.
CONSENSUS_SCHEMA = "chainermn_tpu.gang_consensus.v1"


def detection_window_s(beat_interval_s: float, miss_beats: int) -> float:
    """Worst-case seconds from death to detection: ``miss_beats``
    missed beats plus one interval of phase offset (the member may die
    immediately after a beat the reader just accepted)."""
    return float(beat_interval_s) * (int(miss_beats) + 1)


def make_lease(worker: str, role: str, epoch: int, seq: int,
               **state) -> Dict[str, Any]:
    """One heartbeat lease payload (plain dict: the wire shape)."""
    lease = {
        "schema": LEASE_SCHEMA,
        "worker": str(worker),
        "role": str(role),
        "epoch": int(epoch),
        "seq": int(seq),
        "pid": os.getpid(),
        "t_wall": time.time(),
    }
    lease.update(state)
    return lease


class HeartbeatPublisher:
    """Publisher half: publish this member's lease on the lane store
    every ``beat_interval_s`` (callers invoke :meth:`maybe_beat` from
    their loop — a wedged loop then misses leases, which is exactly the
    liveness semantics the reader wants to observe).

    Thread-safe: a member may beat from both its step loop and a side
    heartbeat thread, so seq minting + the put serialize under a lock
    (concurrent unlocked beats could publish duplicate/out-of-order
    seqs and regress lease contents).  :meth:`release` latches the
    publisher closed under the same lock, so a racing beat can never
    resurrect the lease of a member that just drained.  ``epoch`` is a
    plain attribute read at beat time: a gang reconfiguration re-mints
    it in place and the next beat carries the new stamp."""

    def __init__(self, store, worker: str, role: str, epoch: int,
                 beat_interval_s: float = 0.05, lane_config=None):
        self.store = store
        self.worker = str(worker)
        self.role = str(role)
        self.epoch = int(epoch)
        self.beat_interval_s = float(beat_interval_s)
        self.lane_config = lane_config
        self.seq = 0
        self._last_beat = 0.0
        self._lock = threading.Lock()
        self._released = False

    def beat(self, **state) -> Optional[Dict[str, Any]]:
        """Publish one lease; returns it (None once released)."""
        from .communicators.base import lane_call
        from .observability import journal as _journal

        with self._lock:
            if self._released:
                return None
            self.seq += 1
            lease = make_lease(self.worker, self.role, self.epoch,
                               self.seq, **state)
            if _journal.enabled():
                # the HLC rides in the lease payload so the reader's
                # judgment merges the publisher's clock: every beat
                # happens-before the supervision decision it feeds
                lease["hlc"] = _journal.wire_emit(
                    "beat", worker=self.worker, epoch=self.epoch,
                    lseq=self.seq)
            payload = pickle.dumps(lease,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            lane_call(f"health/{self.worker}/beat",
                      lambda: self.store.put(f"lease/{self.worker}",
                                             payload),
                      self.lane_config)
            self._last_beat = time.monotonic()
            return lease

    def maybe_beat(self, **state) -> Optional[Dict[str, Any]]:
        """Publish iff a beat interval elapsed since the last one."""
        if time.monotonic() - self._last_beat >= self.beat_interval_s:
            return self.beat(**state)
        return None

    def release(self) -> None:
        """Graceful exit (drain): delete this member's lease so the
        reader sees an explicit departure, not a missed window.
        Latches the publisher: later beats are refused."""
        from .communicators.base import lane_call
        from .observability import journal as _journal

        with self._lock:
            self._released = True
            lane_call(f"health/{self.worker}/release",
                      lambda: self.store.delete(f"lease/{self.worker}"),
                      self.lane_config)
            _journal.emit("lease_release", worker=self.worker,
                          epoch=self.epoch)


class LeaseTable:
    """Reader half: read leases and clock them by RECEIVER monotonic
    time — ``age_s`` is seconds since this process last saw a NEW
    sequence number, immune to cross-process clock skew."""

    def __init__(self, store, lane_config=None):
        self.store = store
        self.lane_config = lane_config
        # worker -> (last seen lease dict, t_seen of last NEW seq)
        self._seen: Dict[str, Any] = {}

    def read(self, worker: str) -> Optional[Dict[str, Any]]:
        """Latest lease for ``worker`` (schema-checked), or None when
        the worker never published / released its lease."""
        from .serving.lanes import lane_try_get

        payload = lane_try_get(self.store, f"health/{worker}/read",
                               f"lease/{worker}", self.lane_config)
        if payload is None:
            return None
        lease = pickle.loads(payload)
        if lease.get("schema") != LEASE_SCHEMA:
            raise ValueError(
                f"refusing lease with schema {lease.get('schema')!r} "
                f"for worker {worker!r} (this reader speaks "
                f"{LEASE_SCHEMA})")
        prev = self._seen.get(worker)
        if prev is None or lease["seq"] != prev[0]["seq"]:
            self._seen[worker] = (lease, time.monotonic())
        return self._seen[worker][0]

    def age_s(self, worker: str) -> Optional[float]:
        """Seconds since the last NEW lease seq from ``worker`` was
        observed, or None before any lease arrived."""
        self.read(worker)
        return self.age_of_seen(worker)

    def age_of_seen(self, worker: str) -> Optional[float]:
        """The age from the ALREADY-OBSERVED state (no store read) —
        for callers that just called :meth:`read` and must not pay a
        second lane round trip per poll."""
        prev = self._seen.get(worker)
        if prev is None:
            return None
        return time.monotonic() - prev[1]

    def last_seq(self, worker: str) -> Optional[int]:
        """The last lease seq observed from ``worker`` (no store read),
        or None — the fence's baseline so only writes AFTER a member was
        fenced count as zombie refusals."""
        prev = self._seen.get(worker)
        return None if prev is None else int(prev[0]["seq"])

    def forget(self, worker: str) -> None:
        self._seen.pop(worker, None)


class EpochFence:
    """Monotonic per-member epochs + the fence refusing stale writes.

    The supervisor mints ``new_epoch(worker)`` at every (re-)admission
    and ``fence(worker)`` on death.  Receivers gate every inbound
    artifact with :meth:`admit` — a stale-epoch lease/token/result/slab
    is refused AND counted per kind, which is the zombie-fencing
    acceptance evidence (ISSUEs 10 and 13)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._epoch: Dict[str, int] = {}     # worker -> current epoch
        self._fenced: Dict[str, bool] = {}
        self.refusals: Dict[str, int] = {}   # kind -> refused count

    def new_epoch(self, worker: str) -> int:
        from .observability import journal as _journal

        with self._lock:
            e = self._epoch.get(worker, 0) + 1
            self._epoch[worker] = e
            self._fenced[worker] = False
        _journal.emit("epoch_minted", worker=worker, epoch=e)
        return e

    def set_epoch(self, worker: str, epoch: int) -> int:
        """Install an externally agreed epoch (the gang's consensus mints
        ONE epoch for the whole membership rather than per-member
        counters); refuses to move backwards."""
        with self._lock:
            cur = self._epoch.get(worker, 0)
            if int(epoch) < cur:
                raise ValueError(
                    f"epoch for {worker!r} may not regress "
                    f"({cur} -> {epoch})")
            self._epoch[worker] = int(epoch)
            self._fenced[worker] = False
            return int(epoch)

    def fence(self, worker: str) -> None:
        from .observability import journal as _journal

        with self._lock:
            self._fenced[worker] = True
            epoch = self._epoch.get(worker)
        _journal.emit("fence", worker=worker, epoch=epoch)

    def current(self, worker: str) -> Optional[int]:
        with self._lock:
            return self._epoch.get(worker)

    def is_fenced(self, worker: str) -> bool:
        with self._lock:
            return bool(self._fenced.get(worker, False))

    def admit(self, worker: str, epoch, kind: str) -> bool:
        """Whether an artifact stamped ``epoch`` from ``worker`` may
        land.  Refusals (stale epoch, or the worker's current epoch is
        fenced) are counted under ``kind``."""
        with self._lock:
            cur = self._epoch.get(worker)
            ok = (cur is not None and int(epoch) == cur
                  and not self._fenced.get(worker, False))
            if not ok:
                self.refusals[kind] = self.refusals.get(kind, 0) + 1
            return ok

    def refusal_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.refusals)


class CircuitBreaker:
    """Per-member re-admission governor: retry budget + exponential
    backoff.  ``record_failure`` opens the circuit for ``backoff_base_s
    * 2^(failures-1)`` (capped at ``backoff_max_s``); :meth:`allow`
    half-opens it after the hold-off; ``record_success`` closes it and
    refunds the budget.  Past ``max_failures`` consecutive failures the
    circuit opens PERMANENTLY — a serial flapper is removed rather than
    re-admitted forever."""

    def __init__(self, max_failures: int = 4, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 clock=time.monotonic):
        self.max_failures = int(max_failures)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self.failures = 0
        self._open_until: Optional[float] = None
        self.permanently_open = False

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures >= self.max_failures:
            self.permanently_open = True
            self._open_until = None
            return
        delay = min(self.backoff_base_s * (2 ** (self.failures - 1)),
                    self.backoff_max_s)
        self._open_until = self._clock() + delay

    def record_success(self) -> None:
        self.failures = 0
        self._open_until = None
        self.permanently_open = False

    def allow(self) -> bool:
        """May the member be re-admitted now?"""
        if self.permanently_open:
            return False
        if self._open_until is None:
            return True
        return self._clock() >= self._open_until

    def state(self) -> Dict[str, Any]:
        return {
            "failures": self.failures,
            "permanently_open": self.permanently_open,
            "open_for_s": (None if self._open_until is None
                           else max(self._open_until - self._clock(), 0.0)),
        }


# ---------------------------------------------------------------------------
# training-gang failure vocabulary (ISSUE 13)
# ---------------------------------------------------------------------------

class RankLostError(RuntimeError):
    """A collective could not complete because named rank(s) fell out of
    their lease window mid-operation.  Raised by the gang's watchdog-
    guarded collectives instead of the anonymous lane timeout the same
    death used to surface as — the message, the ``rank_lost`` flight
    bundle, and the attributes all NAME the missing ranks, so the
    survivor can run the live-shrink protocol (``SelfHealingGang
    .heal``) or die with an actionable postmortem."""

    def __init__(self, ranks: Sequence[int], op: Optional[str] = None,
                 lease_age_s: Optional[Dict[int, Optional[float]]] = None,
                 window_s: Optional[float] = None,
                 epoch: Optional[int] = None):
        self.ranks = sorted(int(r) for r in ranks)
        self.op = op
        self.lease_age_s = lease_age_s or {}
        self.window_s = window_s
        self.epoch = epoch
        ages = {r: (None if a is None else round(a, 3))
                for r, a in self.lease_age_s.items()}
        super().__init__(
            f"rank(s) {self.ranks} lost during collective "
            f"{op!r} (epoch {epoch}): lease age(s) {ages} exceeded the "
            f"{window_s}s detection window")


class GangFencedError(RuntimeError):
    """THIS member was fenced out of the gang: a live peer's lease or
    proposal carries a newer epoch, or a consensus proposal excludes us.
    The only correct move is a loud death — continuing would split the
    brain (the survivors already agreed on a gang without us)."""


class GangConsensusError(RuntimeError):
    """Membership consensus could not complete inside its deadline (or
    proposals permanently disagree).  Loud death; the scheduler
    restarts the job from the last checkpoint — degraded to the PR 8
    story, never a silent hang."""


class GangStateLossError(RuntimeError):
    """The side-channel state redundancy is incomplete: a surviving OLD
    member's shard lease is missing (a rank died before its first
    publish, or the lane write was lost) or the shard iterations
    diverge beyond the documented one-step skew — a live shrink would
    silently corrupt the re-partitioned state, so it is refused loudly
    and the caller falls back to the checkpoint restart."""


class GangBelowFloorError(RuntimeError):
    """The surviving membership fell below the configured minimum world
    size — live shrink is refused and the caller must fall back to the
    PR 8 checkpoint restart (the shrink-vs-restart decision table in
    docs/ROBUSTNESS.md)."""

    def __init__(self, survivors: Sequence[int], min_world: int):
        self.survivors = sorted(int(r) for r in survivors)
        self.min_world = int(min_world)
        super().__init__(
            f"only {len(self.survivors)} survivor(s) {self.survivors} "
            f"remain, below the min-world floor {min_world}: refusing "
            f"live shrink — fall back to checkpoint restart")


class MembershipConsensus:
    """Deterministic membership agreement for checkpoint-free shrink.

    A pure message-driven state machine (no clocks, no I/O — fuzzable):
    each survivor feeds its lease-table view in via :meth:`observe`,
    publishes :meth:`proposal` messages over the side channel, delivers
    peers' proposals via :meth:`deliver` (stale-epoch messages refused
    and counted, duplicates deduped by ``seq`` — latest wins), and polls
    :meth:`decide`:

    * ``decide()`` returns the agreed membership exactly when every
      member of MY observed-alive set has a live proposal whose alive
      set EQUALS mine — unanimity over the candidate set.  Until then
      it returns None (keep re-observing/re-publishing).
    * A proposal from a member of my alive set that EXCLUDES me raises
      :class:`GangFencedError`: a live peer considers me dead, so I may
      be the zombie — dying loudly beats splitting the gang.
    * Messages from members outside my alive set (a zombie proposing
      its stale world) are ignored and counted, never adopted.

    The driver (``SelfHealingGang._run_consensus``) bounds the loop
    with a deadline and raises :class:`GangConsensusError` on expiry —
    disagreement degrades to a loud death, never a hang.  Convergence
    under delayed/duplicated/stale schedules is fuzzed over thousands
    of trials in tests/test_gang.py."""

    def __init__(self, member: int, members: Sequence[int], epoch: int):
        self.member = int(member)
        self.members = sorted(int(m) for m in members)
        if self.member not in self.members:
            raise ValueError(
                f"member {member} not in gang {self.members}")
        self.epoch = int(epoch)
        self._alive = {self.member}
        self._seq = 0
        self._proposals: Dict[int, Any] = {}  # member -> (seq, alive tuple)
        self.stale_refused = 0
        self.duplicate_dropped = 0
        self.foreign_ignored = 0

    def observe(self, alive: Sequence[int]) -> None:
        """Install my current lease-table view (I am always alive)."""
        self._alive = {int(r) for r in alive} | {self.member}

    def proposal(self) -> Dict[str, Any]:
        """Mint my next proposal message (seq-stamped, epoch-scoped)."""
        self._seq += 1
        return {"schema": CONSENSUS_SCHEMA, "kind": "gang_propose",
                "epoch": self.epoch, "member": self.member,
                "seq": self._seq, "alive": sorted(self._alive)}

    def deliver(self, msg: Any) -> bool:
        """Feed one (possibly delayed/duplicated/stale) message; returns
        True when it updated the proposal table.  A malformed message or
        a same-epoch proposal from OUTSIDE my alive set (a zombie voting
        for its stale world) is dropped and counted under
        ``foreign_ignored`` — a refused vote can never resurrect its
        sender; the driver re-reads peers every iteration, so a
        proposal that arrives before its sender is observed alive is
        simply re-delivered later."""
        if (not isinstance(msg, dict)
                or msg.get("schema") != CONSENSUS_SCHEMA
                or msg.get("kind") != "gang_propose"):
            self.foreign_ignored += 1
            return False
        if int(msg.get("epoch", -1)) != self.epoch:
            self.stale_refused += 1
            return False
        try:
            m = int(msg["member"])
            seq = int(msg["seq"])
            alive = tuple(int(r) for r in msg["alive"])
        except (KeyError, TypeError, ValueError):
            # schema-stamped but truncated/corrupt: malformed, per the
            # contract — counted and dropped, never a raise out of the
            # consensus driver
            self.foreign_ignored += 1
            return False
        if m == self.member:
            return False  # my own echo off the store
        if m not in self._alive:
            self.foreign_ignored += 1
            return False
        prev = self._proposals.get(m)
        if prev is not None and seq <= prev[0]:
            self.duplicate_dropped += 1
            return False
        self._proposals[m] = (seq, alive)
        return True

    def decide(self) -> Optional[List[int]]:
        """The agreed new membership, None while pending; raises
        :class:`GangFencedError` when a live peer has voted me out."""
        want = tuple(sorted(self._alive))
        for m in want:
            if m == self.member:
                continue
            p = self._proposals.get(m)
            if p is None:
                return None
            if self.member not in p[1]:
                raise GangFencedError(
                    f"member {m} proposes gang {sorted(p[1])} at epoch "
                    f"{self.epoch}, excluding member {self.member}: this "
                    f"member was presumed dead — dying loudly instead of "
                    f"splitting the gang")
            if p[1] != want:
                return None
        return list(want)

    def stats(self) -> Dict[str, int]:
        return {"stale_refused": self.stale_refused,
                "duplicate_dropped": self.duplicate_dropped,
                "foreign_ignored": self.foreign_ignored,
                "proposals_seen": len(self._proposals),
                "seq": self._seq}


# ---------------------------------------------------------------------------
# the collective watchdog (threaded through the accounted collective face)
# ---------------------------------------------------------------------------

def _default_guard_action(op: str, gap_s: float, missing) -> None:
    import sys
    print(f"[chainermn_tpu health] collective '{op}' exceeded its "
          f"{gap_s:.1f}s guard window"
          + (f"; lease table names rank(s) {missing} as lost"
             if missing else "; lease table names no missing rank")
          + " — aborting the gang loudly (exit 44)",
          file=sys.stderr, flush=True)
    try:
        import jax
        jax.distributed.shutdown()
    except Exception:
        pass
    os._exit(44)


class CollectiveGuard:
    """Bounded-timeout watchdog over eager collective calls.

    ``observability/comm.py`` brackets every eager accounted collective
    (the communicator methods auto-wrapped by ``CommunicatorBase
    .__init_subclass__`` AND eager calls through ``ops.collective``'s
    face) with :meth:`enter`/:meth:`exit` when a guard is installed via
    :func:`set_collective_guard`.  A watcher thread fires when any
    active call outlives ``timeout_s``:

    1. ``lost_ranks_fn()`` (typically ``SelfHealingGang.stale_members``)
       is consulted so the abort NAMES the missing rank(s) instead of
       surfacing as an anonymous stall;
    2. a ``rank_lost`` flight bundle is dumped (when ``dump_dir`` set);
    3. ``action(op, gap_s, missing)`` runs — default: print + coordinator
       shutdown + ``os._exit(44)`` (exit 43 is the step watchdog; 44 is
       the collective guard), because a thread cannot raise into a
       caller blocked inside an XLA collective.

    The guard fires at most once per active call and disarms cleanly on
    :meth:`stop`.  With no guard installed the accounted face pays one
    module-global read per call.
    """

    def __init__(self, timeout_s: float,
                 lost_ranks_fn: Optional[Callable[[], Sequence[int]]] = None,
                 action: Optional[Callable] = None,
                 poll_s: Optional[float] = None,
                 dump_dir: Optional[str] = None,
                 rank: Optional[int] = None,
                 clock=time.monotonic):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.lost_ranks_fn = lost_ranks_fn
        self.action = action or _default_guard_action
        self.poll_s = poll_s or max(self.timeout_s / 4, 0.02)
        self.dump_dir = dump_dir
        self.rank = rank
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Dict[int, Any] = {}   # token -> (op, t0, fired)
        self._next_token = 0
        self.fired = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the accounted face's hooks --
    def enter(self, op: str) -> int:
        with self._lock:
            self._next_token += 1
            tok = self._next_token
            self._active[tok] = [str(op), self._clock(), False]
        return tok

    def exit(self, token: int) -> None:
        with self._lock:
            self._active.pop(token, None)

    def active_ops(self) -> List[str]:
        with self._lock:
            return [op for op, _, _ in self._active.values()]

    # -- lifecycle --
    def start(self) -> "CollectiveGuard":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="chainermn-tpu-collective-guard",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def check(self) -> int:
        """One synchronous sweep (the watcher's body; also the test
        seam): fires expiry for every overdue active call, returns how
        many fired."""
        now = self._clock()
        expired = []
        with self._lock:
            for tok, rec in self._active.items():
                op, t0, fired = rec
                if not fired and now - t0 > self.timeout_s:
                    rec[2] = True
                    expired.append((op, now - t0))
        for op, gap in expired:
            self._expire(op, gap)
        return len(expired)

    def _expire(self, op: str, gap_s: float) -> None:
        self.fired += 1
        missing: Optional[List[int]] = None
        if self.lost_ranks_fn is not None:
            try:
                missing = sorted(int(r) for r in self.lost_ranks_fn())
            except Exception:
                missing = None
        from .observability import flight as _flight
        _flight.note("rank_lost", op=op, gap_s=round(gap_s, 3),
                     timeout_s=self.timeout_s, missing=missing,
                     source="collective_guard")
        if self.dump_dir:
            _flight.dump_bundle(
                self.dump_dir, "rank_lost", rank=self.rank,
                extra={"rank_lost": {
                    "missing": missing, "op": op,
                    "gap_s": round(gap_s, 3),
                    "detection_window_s": self.timeout_s,
                    "source": "collective_guard"}})
        self.action(op, gap_s, missing)

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()


#: The process-wide guard the accounted collective face consults.  None
#: (the default) costs one module-global read per eager collective.
_COLLECTIVE_GUARD: Optional[CollectiveGuard] = None


def set_collective_guard(guard: Optional[CollectiveGuard]
                         ) -> Optional[CollectiveGuard]:
    """Install (or clear, with None) the process-wide collective guard."""
    global _COLLECTIVE_GUARD
    _COLLECTIVE_GUARD = guard
    return guard


def collective_guard() -> Optional[CollectiveGuard]:
    return _COLLECTIVE_GUARD


# ---------------------------------------------------------------------------
# store adapter: the communicator KV side channel as a lease store
# ---------------------------------------------------------------------------

class KvLeaseStore:
    """Adapt a communicator's ``kv_lane_transport()`` (tag-addressed
    put/get/delete over the jax.distributed KV store, or the in-process
    loopback) into the store face the health plane polls.

    The one impedance mismatch: the health plane's non-blocking reads
    (``lane_try_get``) expect an ABSENT tag to surface as
    ``TimeoutError``/``KeyError`` (the ``FileLaneStore`` contract), but
    the jax.distributed client raises a backend-specific error whose
    text would classify as a retryable lane fault — turning every
    empty-lease poll into a full retry storm.  This adapter maps
    absence back onto ``TimeoutError`` (text matching the transient
    fingerprints, like every other store) and lets real faults
    propagate for ``lane_call`` to classify."""

    _ABSENT_FINGERPRINTS = ("deadline", "timed out", "not found",
                            "does not exist")

    def __init__(self, transport):
        self.transport = transport

    def put(self, tag: str, payload: bytes) -> None:
        self.transport.put(tag, payload)

    def get(self, tag: str, timeout_s: float = 10.0) -> bytes:
        try:
            return self.transport.get(tag, timeout_s)
        except (TimeoutError, KeyError):
            raise
        except Exception as e:
            msg = str(e).lower()
            if any(p in msg for p in self._ABSENT_FINGERPRINTS):
                raise TimeoutError(
                    f"lane tag {tag!r} not published within {timeout_s}s "
                    f"(deadline exceeded)") from e
            raise

    def delete(self, tag: str) -> None:
        try:
            self.transport.delete(tag)
        except KeyError:
            pass
        except Exception as e:
            # absent-tag deletes are a no-op everywhere else; real
            # faults propagate for lane_call to classify
            if not any(p in str(e).lower()
                       for p in self._ABSENT_FINGERPRINTS):
                raise
