"""Expert parallelism: mixture-of-experts layer with all-to-all dispatch.

Reference relationship: SURVEY.md §2.8 lists EP as absent from the
reference — "``alltoall`` primitive exists, which is the EP substrate"
(``chainermn/functions/collective_communication.py`` [uv]).  This module is
the layer the substrate was pointing at, built the TPU way (the
Switch-Transformer / Mesh-TF dispatch formulation, which XLA maps well):

* routing is a dense argmax + cumsum over a ``(tokens, experts)`` one-hot —
  static shapes, no sorting, no dynamic gather — so the whole layer stays
  inside one jitted SPMD program;
* experts are sharded along a named mesh axis (``E_local = E / P`` experts
  per device) and tokens travel to their expert and back with exactly TWO
  ``jax.lax.all_to_all`` collectives riding ICI;
* capacity is fixed (``ceil(T/E * capacity_factor)``): overflow tokens are
  dropped (contribute zero, standard Switch behavior), keeping every shape
  static for XLA;
* the load-balancing auxiliary loss (Switch eq. 4) comes back alongside the
  output; gradients flow through dispatch/combine einsums and the
  all_to_alls automatically (shard_map transposes them).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..topology import DEFAULT_AXIS_NAME


def moe_mlp(x, params, *, axis_name: str, num_experts: int,
            capacity_factor: float = 1.25, activation=jax.nn.gelu,
            router_topk: int = 1):
    """Top-1 (Switch) or top-2 (GShard) MoE MLP over expert-sharded weights.

    Call INSIDE ``shard_map``.  ``x``: local token shard ``(T, D)`` (token/
    batch axis sharded over ``axis_name``).  ``params``:

    * ``router``: replicated ``(D, E)``;
    * ``wi (E_local, D, F)``, ``bi (E_local, F)``, ``wo (E_local, F, D)``,
      ``bo (E_local, D)``: this device's expert shards (``in_spec
      P(axis_name)`` over globally expert-stacked weights).

    ``router_topk=2`` routes each token to its two best experts with
    normalized gates (GShard): second choices queue BEHIND all first
    choices at their expert, so under capacity pressure first choices win —
    the standard priority rule.  Capacity scales with ``router_topk``.

    Returns ``(y, aux_loss)``: ``y (T, D)`` with dropped tokens zero,
    ``aux_loss`` the load-balancing scalar (already globally averaged).
    """
    if router_topk not in (1, 2):
        raise ValueError(f"router_topk must be 1 or 2, got {router_topk}")
    p_size = jax.lax.axis_size(axis_name)
    e = num_experts
    if e % p_size != 0:
        raise ValueError(f"num_experts {e} not divisible by axis size {p_size}")
    e_local = e // p_size
    t, d = x.shape
    capacity = int(math.ceil(router_topk * t / e * capacity_factor))

    # --- route: fp32 softmax for stable gating ---
    logits = jnp.matmul(x, params["router"],
                        preferred_element_type=jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # (T,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)  # (T, E)
    gate1 = jnp.sum(probs * onehot, axis=-1)                 # (T,)

    # Load-balancing aux (Switch eq. 4) over GLOBAL first-choice statistics:
    # fraction_e and mean_prob_e are each pmean'd across devices BEFORE the
    # product (mean-of-products ≠ product-of-means when routing is skewed
    # across devices), so the scalar equals the single-device computation on
    # the gathered batch.
    fraction = jax.lax.pmean(jnp.mean(onehot, axis=0), axis_name)
    mean_prob = jax.lax.pmean(jnp.mean(probs, axis=0), axis_name)
    aux = e * jnp.sum(fraction * mean_prob)

    # --- dispatch tensors: position of each token within its expert ---
    # (cumsum-1)*onehot is zero at non-assigned entries, so the row sum is
    # exactly the token's arrival index at its expert.
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # (T, E)
    pos_idx = jnp.sum(position, axis=-1).astype(jnp.int32)   # (T,)
    keep = pos_idx < capacity
    pos_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=x.dtype)  # (T, C)
    dispatch = (onehot.astype(x.dtype)[:, :, None] * pos_onehot[:, None, :]
                * keep[:, None, None])                       # (T, E, C)

    if router_topk == 2:
        probs2 = probs * (1.0 - onehot)  # mask the first choice
        idx2 = jnp.argmax(probs2, axis=-1)
        onehot2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
        gate2 = jnp.sum(probs * onehot2, axis=-1)
        # Second choices queue behind ALL first choices at their expert.
        first_counts = jnp.sum(onehot, axis=0)               # (E,)
        position2 = (jnp.cumsum(onehot2, axis=0) - 1.0) * onehot2
        pos2_idx = (jnp.sum(position2 + first_counts[None] * onehot2,
                            axis=-1)).astype(jnp.int32)
        keep2 = pos2_idx < capacity
        pos2_onehot = jax.nn.one_hot(pos2_idx, capacity, dtype=x.dtype)
        dispatch2 = (onehot2.astype(x.dtype)[:, :, None]
                     * pos2_onehot[:, None, :] * keep2[:, None, None])
        # Normalized gates over the two choices (standard GShard combine).
        denom = jnp.maximum(gate1 + gate2, 1e-9)
        combine = (dispatch * (gate1 / denom).astype(x.dtype)[:, None, None]
                   + dispatch2
                   * (gate2 / denom).astype(x.dtype)[:, None, None])
        dispatch = dispatch + dispatch2
    else:
        combine = dispatch * gate1.astype(x.dtype)[:, None, None]  # (T, E, C)

    # --- to experts: (T,E,C)×(T,D) → (E,C,D), then all_to_all over ICI ---
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    # Split the expert dim across devices; receive every device's tokens
    # for MY local experts: (E, C, D) → (P·E_local, C, D) blocks.
    recv = jax.lax.all_to_all(expert_in, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    # Block p holds device p's tokens for my experts; group per expert.
    recv = recv.reshape(p_size, e_local, capacity, d)
    recv = recv.transpose(1, 0, 2, 3).reshape(e_local, p_size * capacity, d)

    # --- expert compute: batched matmuls, MXU-friendly ---
    h = jnp.einsum("egd,edf->egf", recv, params["wi"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    h = activation(h + params["bi"][:, None, :])
    out = jnp.einsum("egf,efd->egd", h, params["wo"],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    out = out + params["bo"][:, None, :]

    # --- back to token owners: inverse reshuffle + second all_to_all ---
    out = out.reshape(e_local, p_size, capacity, d).transpose(1, 0, 2, 3)
    out = out.reshape(e, capacity, d)
    back = jax.lax.all_to_all(out, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)     # (E, C, D)
    y = jnp.einsum("tec,ecd->td", combine, back)
    return y.astype(x.dtype), aux.astype(x.dtype)


def init_moe_mlp_params(rng, d_model: int, d_hidden: int, num_experts: int,
                        dtype=jnp.float32) -> dict:
    """GLOBAL params for :func:`moe_mlp` (expert-stacked leaves, leading dim
    ``E``); shard per :func:`moe_mlp_specs`."""
    kr, k1, k2 = jax.random.split(rng, 3)
    e = num_experts
    si = (2.0 / d_model) ** 0.5
    so = (2.0 / d_hidden) ** 0.5
    return {
        "router": (jax.random.normal(kr, (d_model, e)) * 0.02).astype(dtype),
        "wi": (jax.random.normal(k1, (e, d_model, d_hidden)) * si).astype(dtype),
        "bi": jnp.zeros((e, d_hidden), dtype),
        "wo": (jax.random.normal(k2, (e, d_hidden, d_model)) * so).astype(dtype),
        "bo": jnp.zeros((e, d_model), dtype),
    }


def moe_mlp_specs(axis_name: str = DEFAULT_AXIS_NAME) -> dict:
    """PartitionSpecs: router replicated, expert-stacked weights sharded on
    the expert-stack (leading) dim."""
    return {
        "router": P(),
        "wi": P(axis_name),
        "bi": P(axis_name),
        "wo": P(axis_name),
        "bo": P(axis_name),
    }


def make_moe_mlp(num_experts: int, mesh: Optional[Mesh] = None,
                 axis_name: Optional[str] = None,
                 capacity_factor: float = 1.25, activation=jax.nn.gelu,
                 router_topk: int = 1):
    """Eager/jit face: ``fn(x, global_params) -> (y, aux)`` over global
    arrays, tokens sharded over the mesh axis; compiles once per shape."""
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    specs = moe_mlp_specs(ax)
    return make_global_apply(
        partial(moe_mlp, axis_name=ax, num_experts=num_experts,
                capacity_factor=capacity_factor, activation=activation,
                router_topk=router_topk),
        mesh, (P(ax), specs), (P(ax), P()))
