"""Ring attention: exact attention over sequence shards on the ICI ring.

Technique: blockwise attention with online softmax (Liu et al., "Ring
Attention with Blockwise Transformers"; the reference has no analog —
SURVEY.md §5 long-context entry).  Each device holds a sequence shard of
Q/K/V; K/V blocks rotate around the mesh axis via ``jax.lax.ppermute``
(nearest-neighbor on the TPU torus, so every hop is one ICI link, cost
independent of world size) while each device folds the visiting block into
its online-softmax accumulators.  Communication overlaps compute: XLA
schedules step ``t``'s ppermute concurrently with step ``t``'s matmuls
since they have no data dependence.

Numerics: accumulation in fp32 regardless of input dtype (bf16 inputs stay
bf16 through the matmuls — MXU-native — but m/l/o run fp32), the standard
stabilized-softmax recurrence.  Exactness: results match full attention to
dtype tolerance because online softmax is algebraically exact, not an
approximation.

Autodiff: the whole ring is a differentiable ``lax.scan`` whose transpose
reverses the permutes (ppermute's transpose is the inverse permutation), so
``jax.grad`` through ``ring_attention`` yields the exact backward ring —
the autograd-crosses-ranks property the reference engineered by hand with
Send/Recv FunctionNodes (SURVEY.md §3.5) falls out of XLA here.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ._factory import NEG_INF as _NEG_INF, make_sp_attention


def _block_scores(q, k, scale):
    # (B, Sq, H, D) x (B, Sk, H, D) -> (B, H, Sq, Sk); fp32 accumulation on
    # the MXU via preferred_element_type so bf16 inputs don't lose the
    # softmax numerics.
    return jnp.einsum(
        "bqhd,bkhd->bhqk", q, k,
        preferred_element_type=jnp.float32) * scale


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False) -> jnp.ndarray:
    """Exact multi-head attention over a sequence-sharded axis.

    Call INSIDE ``shard_map``: ``q,k,v`` are the local shards, shape
    ``(batch, seq_local, heads, head_dim)``; the global sequence is
    ``seq_local * axis_size`` in rank order along ``axis_name``.  Returns
    the local output shard, same shape/dtype as ``q``.
    """
    p_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    q_pos = my * s_q + jnp.arange(s_q)  # global query positions

    def step(carry, t):
        k_blk, v_blk, m, l, o = carry
        src = (my - t) % p_size  # who this block originally belonged to
        s = _block_scores(q, k_blk, scale)  # (B, H, Sq, Sk) fp32
        if causal:
            k_pos = src * s_k + jnp.arange(s_k)
            mask = q_pos[:, None] >= k_pos[None, :]  # (Sq, Sk)
            s = jnp.where(mask[None, None], s, _NEG_INF)
            pmask = mask[None, None].astype(s.dtype)
        else:
            pmask = 1.0
        m_new = jnp.maximum(m, s.max(-1))                     # (B, H, Sq)
        p = jnp.exp(s - m_new[..., None]) * pmask             # masked exact 0
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        # PV matmul in the input dtype with fp32 accumulation: bf16 MXU
        # rate, fp32 sums (p is fp32 already; cast to v's dtype for the
        # multiply, accumulate via preferred_element_type).
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        # Rotate K/V one hop around the ring (nearest ICI neighbor).
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, o), None

    # Accumulators derived from q (not jnp.zeros) so they carry q's
    # varying-axis type — lax.scan inside shard_map requires carry-in and
    # carry-out types to agree.
    o0 = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * 0   # (B, H, Sq, D)
    l0 = o0[..., 0]                                      # (B, H, Sq)
    m0 = l0 + _NEG_INF
    (_, _, _, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(p_size))
    out = o / jnp.maximum(l[..., None], 1e-37)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(mesh: Optional[Mesh] = None,
                        axis_name: Optional[str] = None,
                        causal: bool = False):
    """Eager/jit face over GLOBAL sequence-sharded arrays (see
    ``_factory.make_sp_attention``)."""
    return make_sp_attention(ring_attention, mesh, axis_name, causal)
