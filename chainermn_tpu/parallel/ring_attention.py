"""Ring attention: exact attention over sequence shards on the ICI ring.

Technique: blockwise attention with online softmax (Liu et al., "Ring
Attention with Blockwise Transformers"; the reference has no analog —
SURVEY.md §5 long-context entry).  Each device holds a sequence shard of
Q/K/V; K/V blocks rotate around the mesh axis via ``jax.lax.ppermute``
(nearest-neighbor on the TPU torus, so every hop is one ICI link, cost
independent of world size) while each device folds the visiting block into
its online-softmax accumulators.  Communication overlaps compute: XLA
schedules step ``t``'s ppermute concurrently with step ``t``'s matmuls
since they have no data dependence.

Numerics: accumulation in fp32 regardless of input dtype (bf16 inputs stay
bf16 through the matmuls — MXU-native — but m/l/o run fp32), the standard
stabilized-softmax recurrence.  Exactness: results match full attention to
dtype tolerance because online softmax is algebraically exact, not an
approximation.

Autodiff: the whole ring is a differentiable ``lax.scan`` whose transpose
reverses the permutes (ppermute's transpose is the inverse permutation), so
``jax.grad`` through ``ring_attention`` yields the exact backward ring —
the autograd-crosses-ranks property the reference engineered by hand with
Send/Recv FunctionNodes (SURVEY.md §3.5) falls out of XLA here.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ._factory import NEG_INF as _NEG_INF, make_sp_attention


def _block_scores(q, k, scale):
    # (B, Sq, H, D) x (B, Sk, H, D) -> (B, H, Sq, Sk); fp32 accumulation on
    # the MXU via preferred_element_type so bf16 inputs don't lose the
    # softmax numerics.
    return jnp.einsum(
        "bqhd,bkhd->bhqk", q, k,
        preferred_element_type=jnp.float32) * scale


def _ring_flash(q, k, v, axis_name, causal):
    """Ring attention with the Pallas flash kernel as the local block
    compute: the O(Sq·Sk) per-block score matrix never materializes — the
    kernel streams MXU tiles through VMEM and hands back ``(out, lse)``,
    and visiting blocks merge through the numerically-exact log-sum-exp
    recurrence.  Gradients flow through the merge weights via the kernel's
    differentiable LSE output."""
    from ..ops.flash_attention import flash_attention

    p_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)

    def local(k_blk, v_blk, blk_causal):
        return flash_attention(q, k_blk, v_blk, causal=blk_causal,
                               return_lse=True)

    def step(carry, t):
        k_blk, v_blk, o, lse = carry
        src = (my - t) % p_size  # who this block originally belonged to
        if causal:
            # src < my: every key precedes every query (full block);
            # src == my: the diagonal (causal within the block);
            # src > my: entirely in the future (contributes nothing).
            def full(_):
                return local(k_blk, v_blk, False)

            def diag(_):
                return local(k_blk, v_blk, True)

            def skip(_):
                # zeros/NEG_INF with the same varying-axes type as the
                # flash branches (lax.switch demands matching branch types)
                from ..ops.collective import zeros_like_vma
                return (zeros_like_vma(o, q.dtype),
                        zeros_like_vma(lse, jnp.float32) + _NEG_INF)

            idx = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            out_t, lse_t = jax.lax.switch(idx, [full, diag, skip], None)
        else:
            out_t, lse_t = local(k_blk, v_blk, False)

        # LSE-weighted merge; _NEG_INF is finite so empty accumulators and
        # fully-masked blocks contribute exact zeros, never NaNs.
        lse_new = jnp.logaddexp(lse, lse_t)                  # (B, H, Sq)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lse_t - lse_new).transpose(0, 2, 1)[..., None]
        o = o * w_old + out_t.astype(jnp.float32) * w_new    # (B, Sq, H, D)

        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o, lse_new), None

    from ..ops.collective import zeros_like_vma

    b, s_q, h, d = q.shape
    o0 = zeros_like_vma(q, jnp.float32)                      # (B, Sq, H, D)
    lse0 = zeros_like_vma(q, jnp.float32, (b, h, s_q)) + _NEG_INF
    (_, _, o, _), _ = jax.lax.scan(step, (k, v, o0, lse0), jnp.arange(p_size))
    return o.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   attn_impl: str = "auto") -> jnp.ndarray:
    """Exact multi-head attention over a sequence-sharded axis.

    Call INSIDE ``shard_map``: ``q,k,v`` are the local shards, shape
    ``(batch, seq_local, heads, head_dim)``; the global sequence is
    ``seq_local * axis_size`` in rank order along ``axis_name``.  Returns
    the local output shard, same shape/dtype as ``q``.

    ``attn_impl``: ``'xla'`` materializes each visiting block's
    ``(B, H, Sq, Sk)`` score matrix (fine at short S); ``'flash'`` runs the
    Pallas kernel per block — O(block) live memory, the long-context
    configuration; ``'auto'`` (default) picks flash on TPU whenever the
    local block is big enough to fill kernel tiles — the long-context
    module must not default to the path that defeats long context.
    """
    from ..ops.flash_attention import resolve_attn_impl

    attn_impl = resolve_attn_impl(attn_impl, q.shape[1])
    if attn_impl == "flash":
        # GQA (fewer KV heads than Q heads) passes straight through: the
        # flash kernel shares KV heads in its block index map.
        return _ring_flash(q, k, v, axis_name, causal)
    if attn_impl != "xla":
        raise ValueError(
            f"attn_impl must be 'auto', 'xla' or 'flash', got {attn_impl!r}")
    if k.shape[2] != q.shape[2]:
        # GQA on the materializing path: expand KV to the q head count (the
        # O(S²) scores already dominate memory here; the flash path is the
        # one that keeps KV unexpanded).
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"q heads {q.shape[2]} not a multiple of kv heads {k.shape[2]}")
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    p_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    q_pos = my * s_q + jnp.arange(s_q)  # global query positions

    def step(carry, t):
        k_blk, v_blk, m, l, o = carry
        src = (my - t) % p_size  # who this block originally belonged to
        s = _block_scores(q, k_blk, scale)  # (B, H, Sq, Sk) fp32
        if causal:
            k_pos = src * s_k + jnp.arange(s_k)
            mask = q_pos[:, None] >= k_pos[None, :]  # (Sq, Sk)
            s = jnp.where(mask[None, None], s, _NEG_INF)
            pmask = mask[None, None].astype(s.dtype)
        else:
            pmask = 1.0
        m_new = jnp.maximum(m, s.max(-1))                     # (B, H, Sq)
        p = jnp.exp(s - m_new[..., None]) * pmask             # masked exact 0
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        # PV matmul in the input dtype with fp32 accumulation: bf16 MXU
        # rate, fp32 sums (p is fp32 already; cast to v's dtype for the
        # multiply, accumulate via preferred_element_type).
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        # Rotate K/V one hop around the ring (nearest ICI neighbor).
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, o), None

    # Accumulators must carry q's varying-axis type (lax.scan inside
    # shard_map requires carry-in and carry-out types to agree) but NOT its
    # values — `q * 0` would turn one inf/NaN in q into all-NaN output.
    from ..ops.collective import zeros_like_vma

    o0 = zeros_like_vma(q, jnp.float32, (b, h, s_q, d))  # (B, H, Sq, D)
    l0 = o0[..., 0]                                      # (B, H, Sq)
    m0 = l0 + _NEG_INF
    (_, _, _, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(p_size))
    out = o / jnp.maximum(l[..., None], 1e-37)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(mesh: Optional[Mesh] = None,
                        axis_name: Optional[str] = None,
                        causal: bool = False, attn_impl: str = "auto"):
    """Eager/jit face over GLOBAL sequence-sharded arrays (see
    ``_factory.make_sp_attention``)."""
    from functools import partial

    # Same caveat as make_ulysses_attention: interpreted (CPU) pallas can't
    # propagate varying-axes; the compiled TPU path keeps the check.
    # ('auto' never resolves to flash off-TPU, so only an explicit 'flash'
    # request trips this.)
    interpreted_flash = (attn_impl == "flash"
                         and jax.default_backend() != "tpu")
    return make_sp_attention(
        partial(ring_attention, attn_impl=attn_impl),
        mesh, axis_name, causal, check_vma=not interpreted_flash)
