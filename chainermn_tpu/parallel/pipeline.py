"""Pipeline parallelism with a real microbatch schedule (GPipe-style).

Reference relationship: the reference's only inter-layer parallelism is
``MultiNodeChainList`` (``chainermn/links/multi_node_chain_list.py`` [uv]) —
strictly sequential, one rank active at a time, "no microbatching, no 1F1B
schedule" (SURVEY.md §2.3, §2.8 "PP: absent").  Our
``links/multi_node_chain_list.py`` keeps that parity surface; THIS module is
the scheduler the reference never had, built the TPU way:

* stages live on devices along a named mesh axis — stage ``i``'s weights are
  the ``i``-th slice of a stage-stacked pytree (sharded by ``shard_map``);
* the schedule is a ``lax.scan`` over ``M + P - 1`` ticks.  Every tick, all
  ``P`` devices run the SAME stage function on their in-flight microbatch
  (SPMD — XLA sees one program, no data-dependent control flow) and a single
  ``ppermute`` hands activations to the next stage over the ICI ring;
* backward needs no hand-written schedule: ``lax.scan`` reverses the ticks
  and the transpose of ``ppermute(+1)`` is ``ppermute(-1)``, so autodiff
  yields the reverse pipeline automatically — the property the reference
  hand-built with Send/Recv FunctionNodes (SURVEY.md §3.5).

Bubble fraction is ``(P-1)/(M+P-1)`` (GPipe): pick ``num_microbatches >> P``.
Memory is O(M) stashed activations; wrap ``stage_fn`` in ``jax.checkpoint``
to trade FLOPs for HBM (rematerialised backward).

Constraints (the homogeneous-pipeline contract, same as e.g. praxis):
``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape`` and
``y.dtype == x.dtype`` (the activation rides the ring through every stage),
and ``num_microbatches`` divides the global batch.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stage_params, x, *, axis_name: str,
                   num_microbatches: int, squeeze_stage_axis: bool = True,
                   remat: bool = False):
    """Run ``x`` through ``P`` pipeline stages with GPipe microbatching.

    Call INSIDE ``shard_map``.  ``stage_params``: this device's stage slice.
    With ``squeeze_stage_axis=True`` (the default, matching an ``in_spec``
    of ``P(axis_name)`` over stage-stacked params) every leaf must carry a
    leading stage axis of length 1, which is stripped before ``stage_fn``
    sees it; pass ``False`` when handing in an already-squeezed pytree.
    ``x``: the full local batch ``(B, ...)``, replicated across the axis.
    Returns ``stage_P-1 ∘ ... ∘ stage_0`` applied to every microbatch, i.e.
    the same value on every device (merged with one psum at the end).
    """
    p_size = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    if remat:
        # Rematerialized backward: the scan stashes only the tick carries,
        # stage activations are recomputed — O(M) ride-along activations
        # become O(1) per stage, the HBM/FLOP trade SURVEY's §2.8 PP note
        # and the module docstring advertise.
        stage_fn = jax.checkpoint(stage_fn)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches {m}")

    if squeeze_stage_axis:
        bad = [a.shape for a in jax.tree_util.tree_leaves(stage_params)
               if a.ndim == 0 or a.shape[0] != 1]
        if bad:
            raise ValueError(
                f"stage_params leaves must carry a leading stage axis of "
                f"length 1 per device (got shapes {bad}); the stacked stage "
                f"count must equal the '{axis_name}' mesh axis size "
                f"({p_size}), or pass squeeze_stage_axis=False for "
                f"already-squeezed params")
        stage_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    # Pad the injection stream with P-1 zero microbatches so one scan body
    # covers fill, steady state and drain without data-dependent branches.
    pad = jnp.zeros((p_size - 1,) + mb.shape[1:], mb.dtype)
    inject = jnp.concatenate([mb, pad], axis=0)

    def tick(carry, inp):
        state, out_buf, t = carry
        # Stage 0 picks up the next microbatch; everyone else keeps the
        # activation ppermute delivered last tick.
        state = jnp.where(stage == 0, inp, state)
        y = stage_fn(stage_params, state)
        # The last stage emits microbatch t-(P-1) once the pipe is full;
        # masked writes of zeros during fill are overwritten later.
        emit = (stage == p_size - 1) & (t >= p_size - 1)
        slot = jnp.maximum(t - (p_size - 1), 0)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(emit, y, jnp.zeros_like(y)), slot, axis=0)
        # Hand the activation to the next stage over the ICI ring.
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        state = jax.lax.ppermute(y, axis_name, perm=perm)
        return (state, out_buf, t + 1), None

    # The carry becomes device-varying inside the loop (ppermute /
    # stage-dependent writes), so the initial carry must carry that type too.
    def varying_zeros(shape, dtype):
        z = jnp.zeros(shape, dtype)
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            return pcast(z, axis_name, to="varying")
        return jax.lax.pvary(z, axis_name)

    state0 = varying_zeros(mb.shape[1:], mb.dtype)
    out0 = varying_zeros(mb.shape, mb.dtype)
    (_, out_buf, _), _ = jax.lax.scan(
        tick, (state0, out0, jnp.int32(0)), inject)

    # Only the last stage holds real outputs (others all-zero): one psum
    # replicates the result — the in-jit form of "bcast from the last rank".
    out = jax.lax.psum(out_buf, axis_name)
    return out.reshape(x.shape)


def stack_stage_params(per_stage_params) -> object:
    """Stack a list of per-stage pytrees (one per stage, same structure)
    into the stage-stacked pytree ``make_pipeline`` shards: every leaf gains
    a leading axis of length ``P``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline(stage_fn: Callable, mesh: Optional[Mesh] = None,
                  axis_name: Optional[str] = None,
                  num_microbatches: int = 8, remat: bool = False):
    """Eager/jit face: ``fn(stage_stacked_params, x) -> y`` over globals.

    ``stage_stacked_params``: pytree whose leaves have leading dim ``P``
    (see :func:`stack_stage_params`); it is sharded one-stage-per-device
    along the mesh axis, ``x`` replicated; compiles once per shape.
    Differentiable: param grads come back stage-stacked.
    """
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    n_stages = mesh.shape[ax]
    inner = make_global_apply(
        partial(pipeline_apply, stage_fn, axis_name=ax,
                num_microbatches=num_microbatches, remat=remat),
        mesh, (P(ax), P()), P())

    def apply(stage_stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stage_stacked_params):
            if leaf.ndim == 0 or leaf.shape[0] != n_stages:
                raise ValueError(
                    f"stage-stacked leaf has leading dim "
                    f"{leaf.shape[0] if leaf.ndim else None}, but the "
                    f"'{ax}' mesh axis has {n_stages} stages")
        return inner(stage_stacked_params, x)

    return apply
