"""Pipeline parallelism with a real microbatch schedule (GPipe-style).

Reference relationship: the reference's only inter-layer parallelism is
``MultiNodeChainList`` (``chainermn/links/multi_node_chain_list.py`` [uv]) —
strictly sequential, one rank active at a time, "no microbatching, no 1F1B
schedule" (SURVEY.md §2.3, §2.8 "PP: absent").  Our
``links/multi_node_chain_list.py`` keeps that parity surface; THIS module is
the scheduler the reference never had, built the TPU way:

* stages live on devices along a named mesh axis — stage ``i``'s weights are
  the ``i``-th slice of a stage-stacked pytree (sharded by ``shard_map``);
* the schedule is a ``lax.scan`` over ``M + P - 1`` ticks.  Every tick, all
  ``P`` devices run the SAME stage function on their in-flight microbatch
  (SPMD — XLA sees one program, no data-dependent control flow) and a single
  ``ppermute`` hands activations to the next stage over the ICI ring;
* backward needs no hand-written schedule: ``lax.scan`` reverses the ticks
  and the transpose of ``ppermute(+1)`` is ``ppermute(-1)``, so autodiff
  yields the reverse pipeline automatically — the property the reference
  hand-built with Send/Recv FunctionNodes (SURVEY.md §3.5).

Bubble fraction is ``(P-1)/(M+P-1)`` (GPipe): pick ``num_microbatches >> P``.
Memory is O(M) stashed activations; wrap ``stage_fn`` in ``jax.checkpoint``
to trade FLOPs for HBM (rematerialised backward).

Constraints (the homogeneous-pipeline contract, same as e.g. praxis):
``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape`` and
``y.dtype == x.dtype`` (the activation rides the ring through every stage),
and ``num_microbatches`` divides the global batch.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .._compat import pcast_varying


def pipeline_apply(stage_fn: Callable, stage_params, x, *, axis_name: str,
                   num_microbatches: int, squeeze_stage_axis: bool = True,
                   remat: bool = False):
    """Run ``x`` through ``P`` pipeline stages with GPipe microbatching.

    Call INSIDE ``shard_map``.  ``stage_params``: this device's stage slice.
    With ``squeeze_stage_axis=True`` (the default, matching an ``in_spec``
    of ``P(axis_name)`` over stage-stacked params) every leaf must carry a
    leading stage axis of length 1, which is stripped before ``stage_fn``
    sees it; pass ``False`` when handing in an already-squeezed pytree.
    ``x``: the full local batch ``(B, ...)``, replicated across the axis.
    Returns ``stage_P-1 ∘ ... ∘ stage_0`` applied to every microbatch, i.e.
    the same value on every device (merged with one psum at the end).
    """
    p_size = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    if remat:
        # Rematerialized backward: the scan stashes only the tick carries,
        # stage activations are recomputed — O(M) ride-along activations
        # become O(1) per stage, the HBM/FLOP trade SURVEY's §2.8 PP note
        # and the module docstring advertise.
        stage_fn = jax.checkpoint(stage_fn)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches {m}")

    if squeeze_stage_axis:
        bad = [a.shape for a in jax.tree_util.tree_leaves(stage_params)
               if a.ndim == 0 or a.shape[0] != 1]
        if bad:
            raise ValueError(
                f"stage_params leaves must carry a leading stage axis of "
                f"length 1 per device (got shapes {bad}); the stacked stage "
                f"count must equal the '{axis_name}' mesh axis size "
                f"({p_size}), or pass squeeze_stage_axis=False for "
                f"already-squeezed params")
        stage_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    # Pad the injection stream with P-1 zero microbatches so one scan body
    # covers fill, steady state and drain without data-dependent branches.
    pad = jnp.zeros((p_size - 1,) + mb.shape[1:], mb.dtype)
    inject = jnp.concatenate([mb, pad], axis=0)

    def tick(carry, inp):
        state, out_buf, t = carry
        # Stage 0 picks up the next microbatch; everyone else keeps the
        # activation ppermute delivered last tick.
        state = jnp.where(stage == 0, inp, state)
        y = stage_fn(stage_params, state)
        # The last stage emits microbatch t-(P-1) once the pipe is full;
        # masked writes of zeros during fill are overwritten later.
        emit = (stage == p_size - 1) & (t >= p_size - 1)
        slot = jnp.maximum(t - (p_size - 1), 0)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(emit, y, jnp.zeros_like(y)), slot, axis=0)
        # Hand the activation to the next stage over the ICI ring.
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        state = jax.lax.ppermute(y, axis_name, perm=perm)
        return (state, out_buf, t + 1), None

    # The carry becomes device-varying inside the loop (ppermute /
    # stage-dependent writes), so the initial carry must carry that type too.
    def varying_zeros(shape, dtype):
        z = jnp.zeros(shape, dtype)
        return pcast_varying(z, axis_name)

    state0 = varying_zeros(mb.shape[1:], mb.dtype)
    out0 = varying_zeros(mb.shape, mb.dtype)
    (_, out_buf, _), _ = jax.lax.scan(
        tick, (state0, out0, jnp.int32(0)), inject)

    # Only the last stage holds real outputs (others all-zero): one psum
    # replicates the result — the in-jit form of "bcast from the last rank".
    out = jax.lax.psum(out_buf, axis_name)
    return out.reshape(x.shape)


def pipeline_1f1b_grads(stage_fn: Callable, loss_fn: Callable, stage_params,
                        x, targets, *, axis_name: str, num_microbatches: int,
                        squeeze_stage_axis: bool = True):
    """1F1B pipeline schedule: returns ``(loss, param_grads)`` directly.

    Beyond-reference AND beyond :func:`pipeline_apply` (GPipe): the backward
    is part of the schedule, not a scan reversal.  Every tick each stage
    runs ONE forward microbatch and ONE backward microbatch (lockstep 1F1B):

    * forward: stage ``s`` processes microbatch ``f = t - s``; activations
      ride the ``+1`` ICI ring exactly as in GPipe;
    * backward: stage ``s`` processes microbatch ``b = t - 2(P-1) + s`` —
      the last stage seeds the cotangent from ``loss_fn`` the same tick its
      forward finishes, and cotangents ride the ``-1`` ring;
    * each stage keeps only a ``2P-1``-slot circular buffer of its INPUTS
      (the vjp is recomputed at backward time), so stashed-activation memory
      is **O(P), independent of num_microbatches** — GPipe's scan stashes
      O(M) even under remat.  That is what lets ``M`` grow to amortise the
      bubble (``2(P-1)/(M+2P-2)``) without HBM growing with it.

    Call INSIDE ``shard_map``.  ``stage_fn(params, x) -> y`` with
    ``y.shape == x.shape`` (the homogeneous-pipeline contract);
    ``loss_fn(y_mb, target_mb) -> scalar`` (a mean over the microbatch).
    Returns the mean loss over microbatches and gradients w.r.t. this
    device's stage params (leading stage axis of 1, matching an
    ``out_spec`` of ``P(axis_name)``).
    """
    p_size = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches {m}")

    if squeeze_stage_axis:
        bad = [a.shape for a in jax.tree_util.tree_leaves(stage_params)
               if a.ndim == 0 or a.shape[0] != 1]
        if bad:
            raise ValueError(
                f"stage_params leaves must carry a leading stage axis of "
                f"length 1 per device (got shapes {bad})")
        stage_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    tgt = targets.reshape((m, targets.shape[0] // m) + targets.shape[1:])
    n_ticks = m + 2 * (p_size - 1)
    buf_len = 2 * p_size - 1  # proof of safety: see _1F1B buffer note below

    def varying(z):
        # Idempotent: zeros_like(sharded input) is already axis-varying and
        # pcast/pvary reject a varying→varying cast.
        try:
            return pcast_varying(z, axis_name)
        except ValueError:
            return z

    # Circular input buffer: slot f % buf_len.  Unconditional writes are
    # safe: at stage s the entry for microbatch f is consumed 2(P-1-s)
    # ticks after its write, and the next write to the same slot (f +
    # buf_len) happens buf_len = 2P-1 > 2(P-1) ticks later; out-of-range
    # f (fill/drain) only ever lands in slots whose occupant is already
    # consumed or never valid.
    buf0 = varying(jnp.zeros((buf_len,) + mb.shape[1:], mb.dtype))
    fwd0 = varying(jnp.zeros(mb.shape[1:], mb.dtype))
    cot0 = varying(jnp.zeros(mb.shape[1:], mb.dtype))
    # Accumulate grads in fp32 regardless of param dtype: with bf16 params
    # and large M (the regime 1F1B exists for) per-microbatch contributions
    # would drown in a growing bf16 accumulator (same rationale as
    # train._accumulated_local_grads).
    g0 = jax.tree_util.tree_map(
        lambda a: varying(jnp.zeros(a.shape, jnp.float32)), stage_params)

    fwd_perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    bwd_perm = [(i, (i - 1) % p_size) for i in range(p_size)]

    def tick(carry, t):
        fwd_state, cot_in, buf, grads, loss_acc = carry
        f = t - stage                      # forward microbatch index
        b = t - 2 * (p_size - 1) + stage   # backward microbatch index
        valid_f = (f >= 0) & (f < m)
        valid_b = (b >= 0) & (b < m)
        is_last = stage == p_size - 1

        # ---- forward half-tick -------------------------------------------
        inj = jax.lax.dynamic_index_in_dim(
            mb, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        x_in = jnp.where(stage == 0, inj, fwd_state)
        y = stage_fn(stage_params, x_in)
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, x_in, jnp.mod(f, buf_len), axis=0)

        # ---- loss + cotangent seed at the last stage ---------------------
        t_mb = jax.lax.dynamic_index_in_dim(
            tgt, jnp.clip(f, 0, m - 1), 0, keepdims=False)
        l_f, seed = jax.value_and_grad(loss_fn)(y, t_mb)
        loss_acc = loss_acc + jnp.where(is_last & valid_f, l_f, 0.0)

        # ---- backward half-tick ------------------------------------------
        # The last stage back-propagates the microbatch it JUST forwarded
        # (f == b there); everyone else uses the cotangent ppermute
        # delivered last tick, against the input stashed at forward time.
        cot = jnp.where(is_last, jnp.where(valid_f, seed, 0.0), cot_in)
        x_saved = jax.lax.dynamic_index_in_dim(
            buf, jnp.mod(b, buf_len), 0, keepdims=False)
        x_bwd = jnp.where(is_last, x_in, x_saved)
        _, vjp = jax.vjp(stage_fn, stage_params, x_bwd)
        dparams, dx = vjp(cot.astype(y.dtype))
        grads = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(valid_b, d.astype(jnp.float32), 0.0),
            grads, dparams)

        # Activations to the next stage, cotangents to the previous one.
        fwd_state = jax.lax.ppermute(y, axis_name, perm=fwd_perm)
        cot_in = jax.lax.ppermute(dx, axis_name, perm=bwd_perm)
        return (fwd_state, cot_in, buf, grads, loss_acc), None

    (_, _, _, grads, loss_acc), _ = jax.lax.scan(
        tick, (fwd0, cot0, buf0, g0, varying(jnp.float32(0.0))),
        jnp.arange(n_ticks))

    # Only the last stage accumulated loss; grads/loss are means over M.
    # Grads come back in the param dtype (fp32 accumulator cast at the end).
    loss = jax.lax.psum(loss_acc, axis_name) / m
    grads = jax.tree_util.tree_map(
        lambda g, a: (g[None] / m).astype(a.dtype), grads, stage_params)
    return loss, grads


def make_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable,
                       mesh: Optional[Mesh] = None,
                       axis_name: Optional[str] = None,
                       num_microbatches: int = 8):
    """Eager/jit face of :func:`pipeline_1f1b_grads`:
    ``fn(stage_stacked_params, x, targets) -> (loss, stage_stacked_grads)``.

    Use the returned grads with any optax optimizer (state stacked like the
    params); compose with DP by running this inside an outer data axis and
    pmean-ing the grads.
    """
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    n_stages = mesh.shape[ax]
    inner = make_global_apply(
        partial(pipeline_1f1b_grads, stage_fn, loss_fn, axis_name=ax,
                num_microbatches=num_microbatches),
        mesh, (P(ax), P(), P()), (P(), P(ax)))

    def apply(stage_stacked_params, x, targets):
        for leaf in jax.tree_util.tree_leaves(stage_stacked_params):
            if leaf.ndim == 0 or leaf.shape[0] != n_stages:
                raise ValueError(
                    f"stage-stacked leaf has leading dim "
                    f"{leaf.shape[0] if leaf.ndim else None}, but the "
                    f"'{ax}' mesh axis has {n_stages} stages")
        return inner(stage_stacked_params, x, targets)

    return apply


def stack_stage_params(per_stage_params) -> object:
    """Stack a list of per-stage pytrees (one per stage, same structure)
    into the stage-stacked pytree ``make_pipeline`` shards: every leaf gains
    a leading axis of length ``P``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline(stage_fn: Callable, mesh: Optional[Mesh] = None,
                  axis_name: Optional[str] = None,
                  num_microbatches: int = 8, remat: bool = False):
    """Eager/jit face: ``fn(stage_stacked_params, x) -> y`` over globals.

    ``stage_stacked_params``: pytree whose leaves have leading dim ``P``
    (see :func:`stack_stage_params`); it is sharded one-stage-per-device
    along the mesh axis, ``x`` replicated; compiles once per shape.
    Differentiable: param grads come back stage-stacked.
    """
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    n_stages = mesh.shape[ax]
    inner = make_global_apply(
        partial(pipeline_apply, stage_fn, axis_name=ax,
                num_microbatches=num_microbatches, remat=remat),
        mesh, (P(ax), P()), P())

    def apply(stage_stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stage_stacked_params):
            if leaf.ndim == 0 or leaf.shape[0] != n_stages:
                raise ValueError(
                    f"stage-stacked leaf has leading dim "
                    f"{leaf.shape[0] if leaf.ndim else None}, but the "
                    f"'{ax}' mesh axis has {n_stages} stages")
        return inner(stage_stacked_params, x)

    return apply
