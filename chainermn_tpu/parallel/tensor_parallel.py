"""Intra-layer tensor parallelism: column/row-sharded dense, vocab-sharded embedding.

Reference relationship: the reference has NO tensor-parallel library — its
parity bar is "expressible manually via ``functions.allgather/alltoall`` +
split weights" (``chainermn/functions/collective_communication.py`` [uv],
SURVEY.md §2.8 "TP").  This module is the library the reference left as an
exercise, built the TPU way: weights are sharded along a named mesh axis,
the forward is ordinary ``jnp`` matmuls on local shards (MXU-sized, bf16-
friendly), and the only cross-chip traffic is a single ``psum`` (or
``all_gather``) that XLA lowers onto ICI.  Gradients need no hand-written
backward: ``shard_map`` transposes ``psum``/``all_gather`` automatically,
which is exactly the collective-transpose duality the reference implemented
by hand in its autograd FunctionNodes (SURVEY.md §2.2).

Layout (Megatron-LM pairing, one collective per MLP block):

* **column-parallel** — kernel sharded on the OUTPUT dim; each chip computes
  its slice of the features.  No communication unless ``gather_output``.
* **row-parallel** — kernel sharded on the INPUT dim; chips hold partial
  sums, one ``psum`` completes the contraction.  Pairing column→row lets a
  whole MLP (up-projection, nonlinearity, down-projection) run with exactly
  one all-reduce.
* **vocab-parallel embedding** — table sharded on the vocab dim; each chip
  looks up the ids it owns (out-of-range masked to zero), one ``psum``
  merges.

Two faces, like everything here (SURVEY.md §7 "two faces"): the bare
functions run INSIDE ``shard_map`` (compose with ring/Ulysses attention,
pipeline stages, the DP optimizer); ``make_tensor_parallel_mlp`` is the
eager/jit face over global arrays for tests and small jobs.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import collective as _col
from ..topology import DEFAULT_AXIS_NAME

# The TP wire legs below route through the ACCOUNTED collective face
# (`ops.collective`) instead of raw `jax.lax`: numerically identical (the
# wrapper is one attribute read before dispatching to jax.lax), but every
# psum/all_gather a serving tick or TP forward performs now lands in the
# PR 1 comm ledger — which is what lets the shard-flow analyzer
# (analysis/shardflow.py) reconcile the static cost model against runtime
# bytes for the serving entry points.


def column_parallel_dense(x, kernel, bias=None, *, axis_name: str,
                          gather_output: bool = False):
    """``x @ kernel + bias`` with ``kernel`` sharded on the output dim.

    Call inside ``shard_map``.  ``x``: replicated local ``(..., D_in)``;
    ``kernel``: local shard ``(D_in, D_out/P)``; ``bias``: local
    ``(D_out/P,)``.  Returns the local feature slice ``(..., D_out/P)``, or
    the gathered ``(..., D_out)`` when ``gather_output`` (one all_gather).
    """
    y = jnp.matmul(x, kernel, preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias
    if gather_output:
        y = _col.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_dense(x, kernel, bias=None, *, axis_name: str,
                       input_is_parallel: bool = True):
    """``psum(x_local @ kernel_local) + bias`` — kernel sharded on the input dim.

    Call inside ``shard_map``.  ``x``: local ``(..., D_in/P)`` (the natural
    output of a column-parallel layer); ``kernel``: local ``(D_in/P,
    D_out)``; ``bias``: replicated ``(D_out,)``, added AFTER the psum so it
    is applied once, not P times.  When ``input_is_parallel=False``, ``x``
    is replicated ``(..., D_in)`` and each chip first slices its own block.
    """
    if not input_is_parallel:
        p = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        block = x.shape[-1] // p
        x = jax.lax.dynamic_slice_in_dim(x, idx * block, block, axis=x.ndim - 1)
    y = jnp.matmul(x, kernel, preferred_element_type=jnp.float32)
    # Reduce in fp32: casting the partials to bf16 BEFORE the psum would
    # accumulate the cross-chip sum at bf16, losing precision with axis size.
    y = _col.psum(y, axis_name)
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


def vocab_parallel_embedding(ids, table, *, axis_name: str):
    """Embedding lookup with the table sharded on the vocab dim.

    Call inside ``shard_map``.  ``ids``: replicated int ``(...,)``;
    ``table``: local shard ``(V/P, D)``.  Each chip resolves the ids in its
    vocab range (others contribute zeros) and one ``psum`` merges — the
    TPU-native form of a sharded gather.
    """
    vocab_per = table.shape[0]
    start = jax.lax.axis_index(axis_name) * vocab_per
    local = ids - start
    in_range = (local >= 0) & (local < vocab_per)
    rows = jnp.take(table, jnp.clip(local, 0, vocab_per - 1), axis=0)
    rows = jnp.where(in_range[..., None], rows, 0)
    return _col.psum(rows, axis_name)


def tp_mlp(x, params, *, axis_name: str,
           activation: Callable = jax.nn.gelu):
    """Column→activation→row MLP block — ONE psum of cross-chip traffic.

    ``params``: dict with local shards ``wi (D, F/P)``, ``bi (F/P,)``,
    ``wo (F/P, D)`` and replicated ``bo (D,)``.
    """
    h = column_parallel_dense(x, params["wi"], params["bi"],
                              axis_name=axis_name)
    h = activation(h)
    return row_parallel_dense(h, params["wo"], params["bo"],
                              axis_name=axis_name)


def gather_seq_matmul(x, w, bias=None, *, axis_name: str):
    """Megatron-SP entry: ``x (B, S/P, D)`` SEQUENCE-sharded →
    ``(B, S, F_loc)`` via :func:`collective_matmul.all_gather_matmul`, so
    the sequence all-gather rides the ring overlapped with the projection
    instead of serializing before it.  ``w``: column shard ``(D, F/P)``."""
    from .collective_matmul import all_gather_matmul

    b, s_loc, d = x.shape
    p = jax.lax.axis_size(axis_name)
    y = all_gather_matmul(x.reshape(b * s_loc, d), w, axis_name=axis_name)
    y = y.reshape(p, b, s_loc, -1).transpose(1, 0, 2, 3).reshape(
        b, p * s_loc, -1).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def matmul_scatter_seq(x, w, bias=None, *, axis_name: str):
    """Megatron-SP exit: ``x (B, S, F/P)`` (contraction-sharded features) →
    ``(B, S/P, D)`` sequence-sharded, via
    :func:`collective_matmul.matmul_reduce_scatter` — the reduce-scatter
    replaces ``row_parallel_dense``'s psum AND returns only this rank's
    sequence rows, with each ring hop overlapping the next chunk's matmul.
    ``bias``: replicated ``(D,)``, added after the reduction (once)."""
    from .collective_matmul import matmul_reduce_scatter

    b, s, f = x.shape
    p = jax.lax.axis_size(axis_name)
    if s % p:
        raise ValueError(f"sequence {s} not divisible by axis size {p}")
    s_loc = s // p
    x2 = x.reshape(b, p, s_loc, f).transpose(1, 0, 2, 3).reshape(
        p * b * s_loc, f)
    y = matmul_reduce_scatter(x2, w, axis_name=axis_name)
    y = y.reshape(b, s_loc, -1).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp_sp(x, params, *, axis_name: str,
              activation: Callable = jax.nn.gelu):
    """Megatron-SP MLP over SEQUENCE-sharded activations ``(B, S/P, D)``.

    Same params as :func:`tp_mlp`; differs in the activation contract and
    the collectives: entry all-gather and exit reduce-scatter both ride
    the ppermute ring overlapped with their adjacent matmuls
    (`collective_matmul`).  Per-chip activation memory between blocks
    drops by P and the replicated-activation psum disappears.  Exactly
    equals ``tp_mlp`` on the gathered sequence up to reassociation —
    pinned by tests.
    """
    h = gather_seq_matmul(x, params["wi"], params["bi"], axis_name=axis_name)
    h = activation(h)
    return matmul_scatter_seq(h, params["wo"], params["bo"],
                              axis_name=axis_name)


def init_tp_mlp_params(rng, d_model: int, d_hidden: int,
                       dtype=jnp.float32) -> dict:
    """GLOBAL (unsharded) params for :func:`tp_mlp`; shard with
    :func:`tp_mlp_specs` or feed through ``make_tensor_parallel_mlp``."""
    k1, k2 = jax.random.split(rng)
    scale_i = (2.0 / d_model) ** 0.5
    scale_o = (2.0 / d_hidden) ** 0.5
    return {
        "wi": (jax.random.normal(k1, (d_model, d_hidden)) * scale_i).astype(dtype),
        "bi": jnp.zeros((d_hidden,), dtype),
        "wo": (jax.random.normal(k2, (d_hidden, d_model)) * scale_o).astype(dtype),
        "bo": jnp.zeros((d_model,), dtype),
    }


def tp_mlp_specs(axis_name: str = DEFAULT_AXIS_NAME) -> dict:
    """PartitionSpecs mapping :func:`init_tp_mlp_params` globals onto the
    local shards :func:`tp_mlp` expects."""
    return {
        "wi": P(None, axis_name),
        "bi": P(axis_name),
        "wo": P(axis_name, None),
        "bo": P(),
    }


def make_tensor_parallel_mlp(mesh: Optional[Mesh] = None,
                             axis_name: Optional[str] = None,
                             activation: Callable = jax.nn.gelu):
    """Eager/jit face: ``fn(x, global_params) -> y`` over global arrays.

    Shards the params per :func:`tp_mlp_specs`, replicates ``x`` across the
    tensor axis, and runs :func:`tp_mlp` under ``shard_map``; compiles once
    per shape.  Differentiable end-to-end (shard_map transposes the psum).
    """
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    specs = tp_mlp_specs(ax)
    return make_global_apply(
        partial(tp_mlp, axis_name=ax, activation=activation),
        mesh, (P(), specs), P())
