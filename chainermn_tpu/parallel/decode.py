"""Autoregressive decoding with a KV cache for the TP transformer LM.

Beyond-reference (the reference's only generation was seq2seq greedy
translate): incremental decoding the TPU way —

* ONE jitted program: prefill (full-prompt forward that also writes the
  per-layer KV cache) + a ``lax.scan`` over the new tokens (static trip
  count, static cache shapes — no dynamic shapes anywhere);
* the cache holds the **KV heads** (GQA models cache ``n_kv_heads``, the
  whole point of GQA at inference);
* tensor parallelism composes: projections are column-parallel so each
  chip caches only its local heads, the output projection's psum is the
  only per-token cross-chip traffic, and the vocab-parallel logits are
  argmax'd via a (max, index) pmax/psum pair — the full ``(B, V)`` logits
  never materialize on one chip;
* positions come from the model's ``pos_impl`` (learned table or RoPE —
  RoPE rotates each new token at its absolute position).

Layout matches :func:`transformer.init_tp_transformer_lm`; works for both
fused-``wqkv`` and GQA (``wq``/``wkv``) attention params.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tensor_parallel import row_parallel_dense
from .transformer import _layer_norm, _project_qkv, apply_rope


def lm_generate(params, prompt, rng: Optional[jax.Array] = None, *,
                head_dim: int, axis_name: str,
                max_new_tokens: int, temperature: float = 0.0):
    """Generate ``max_new_tokens`` greedily (or sampled when
    ``temperature > 0``) from ``prompt (B, S_p) int32``.

    Call INSIDE ``shard_map`` with the model axis bound (use
    :func:`make_lm_generator` for the jit face).  Returns ``(B,
    max_new_tokens) int32``.
    """
    b, s_p = prompt.shape
    d_model = params["embed"].shape[1]
    rope = "pos_embed" not in params
    total = s_p + max_new_tokens
    if not rope and total > params["pos_embed"].shape[0]:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds the learned "
            f"pos_embed max_len {params['pos_embed'].shape[0]}; shorten the "
            f"generation or init the model with pos_impl='rope'")
    blocks = params["blocks"]

    def embed(tokens, positions):
        from .tensor_parallel import vocab_parallel_embedding

        # The table is VOCAB-SHARDED over the model axis — a plain take
        # would index local rows with global ids.
        x = vocab_parallel_embedding(tokens, params["embed"],
                                     axis_name=axis_name)
        x = x * (d_model ** 0.5)
        if not rope:
            x = x + jnp.take(params["pos_embed"], positions, axis=0)[None]
        return x

    def attn_block(x, blk, k_cache, v_cache, positions, write_at, q_valid):
        """x (B,S,D) → block output; caches written at ``write_at + i`` for
        the i-th input position; query i attends cache [:q_valid + i + 1).
        """
        h = _layer_norm(x, blk["ln1_scale"], blk["ln1_bias"])
        q, k, v = _project_qkv(h, blk["attn"], head_dim, axis_name)
        if rope:
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, write_at, 1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, write_at, 1)
        # Per-query valid lengths make one formula serve prefill (causal)
        # and decode (full prefix): query i sees q_valid + i + 1 entries.
        s_q = q.shape[1]
        valid = (q_valid + jnp.arange(s_q) + 1)[None, None, None, :, None]
        hl, hkv = q.shape[2], k_cache.shape[2]
        # Grouped attention against the UN-expanded cache (GQA's inference
        # payoff): q heads regrouped onto their KV head — no per-tick
        # n_heads-sized cache copy.
        g = hl // hkv
        q5 = q.reshape(b, s_q, hkv, g, head_dim)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k_cache,
                       preferred_element_type=jnp.float32) / (head_dim ** 0.5)
        mask = jnp.arange(k_cache.shape[1])[None, None, None, None, :] < valid
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        ctx = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v_cache.dtype),
                         v_cache,
                         preferred_element_type=jnp.float32).astype(x.dtype)
        ctx = ctx.reshape(b, s_q, -1)
        attn_out = row_parallel_dense(ctx, blk["attn"]["wo"],
                                      blk["attn"]["bo"], axis_name=axis_name)
        x = x + attn_out
        h = _layer_norm(x, blk["ln2_scale"], blk["ln2_bias"])
        from .tensor_parallel import tp_mlp
        return x + tp_mlp(h, blk["mlp"], axis_name=axis_name), k_cache, v_cache

    def logits_next(h_last, step_pos):
        """Vocab-parallel next-token choice from ``h_last (B, D)``;
        ``step_pos`` (the position being generated) salts the sampling key
        so every step draws FRESH Gumbel noise."""
        table = params["embed"]
        vocab_per = table.shape[0]
        start = jax.lax.axis_index(axis_name) * vocab_per
        logits = jnp.einsum("bd,vd->bv", h_last, table,
                            preferred_element_type=jnp.float32)
        if temperature > 0.0:
            # Gumbel trick on the SHARDED logits: per-shard argmax of
            # (logit/T + gumbel) then a global (value, index) max — exact
            # categorical sampling without materializing (B, V) anywhere.
            key = jax.random.fold_in(
                jax.random.fold_in(rng, step_pos),
                jax.lax.axis_index(axis_name))
            gumbel = -jnp.log(-jnp.log(
                jax.random.uniform(key, logits.shape, minval=1e-20)))
            scored = logits / temperature + gumbel
        else:
            scored = logits
        local_best = scored.max(-1)
        local_idx = start + scored.argmax(-1)
        gbest = jax.lax.pmax(local_best, axis_name)
        # Global argmax; an exact-fp tie across shards resolves to the
        # LOWEST winning index (argmax convention), via pmin over winners.
        winner = (local_best == gbest)
        return jax.lax.pmin(
            jnp.where(winner, local_idx, jnp.int32(2 ** 30)), axis_name)

    # ---- prefill: full prompt through the stack, caches written ----
    n_kv = (blocks[0]["attn"]["wkv"].shape[1] // (2 * head_dim)
            if "wkv" in blocks[0]["attn"]
            else blocks[0]["attn"]["bqkv"].shape[0] // (3 * head_dim))
    positions = jnp.arange(s_p)
    x = embed(prompt, positions)
    caches = []
    for blk in blocks:
        k0 = jnp.zeros((b, total, n_kv, head_dim), x.dtype)
        v0 = jnp.zeros((b, total, n_kv, head_dim), x.dtype)
        x, kc, vc = attn_block(x, blk, k0, v0, positions, 0, 0)
        caches.append((kc, vc))
    h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    first = logits_next(h[:, -1], jnp.int32(s_p))

    # ---- decode: one token per scan tick ----
    def tick(carry, i):
        token, caches = carry
        pos = s_p + i - 1  # tick i consumes the (i-1)-th generated token
        x = embed(token[:, None], pos[None])
        new_caches = []
        for blk, (kc, vc) in zip(blocks, caches):
            x, kc, vc = attn_block(x, blk, kc, vc, pos[None], pos, pos)
            new_caches.append((kc, vc))
        h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
        nxt = logits_next(h[:, -1], s_p + i)
        return (nxt, new_caches), token

    (last, _), toks = jax.lax.scan(
        tick, (first, caches), jnp.arange(1, max_new_tokens))
    # toks carries tokens 0..max_new-2 (each tick emits its INPUT token);
    # append the final one.
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)
    return out.astype(jnp.int32)


def make_lm_generator(mesh: Optional[Mesh] = None, axis_name: str = "model",
                      *, head_dim: int, max_new_tokens: int,
                      temperature: float = 0.0):
    """Eager/jit face: ``fn(params, prompt[, rng]) -> (B, max_new) tokens``
    over TP-sharded global params (``transformer_lm_specs`` layout)."""
    from jax import shard_map

    from .transformer import transformer_lm_specs

    if mesh is None:
        from ..topology import make_mesh
        mesh = make_mesh(axis_name=axis_name)

    cache = {}  # one compiled program per param STRUCTURE (spec pytree)

    def apply(params, prompt, rng=None):
        specs = transformer_lm_specs(params, axis_name)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        key = jax.tree_util.tree_structure(specs)
        if key not in cache:
            cache[key] = jax.jit(shard_map(
                partial(lm_generate, head_dim=head_dim, axis_name=axis_name,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature),
                mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=P(),
            ))
        sharded = jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            params, specs)
        return cache[key](sharded, prompt, rng)

    return apply
