"""Autoregressive decoding with a KV cache for the TP transformer LM.

Beyond-reference (the reference's only generation was seq2seq greedy
translate): incremental decoding the TPU way —

* ONE jitted program: prefill (full-prompt forward that also writes the
  per-layer KV cache) + a ``lax.scan`` over the new tokens (static trip
  count, static cache shapes — no dynamic shapes anywhere);
* the cache holds the **KV heads** (GQA models cache ``n_kv_heads``, the
  whole point of GQA at inference);
* tensor parallelism composes: projections are column-parallel so each
  chip caches only its local heads, the output projection's psum is the
  only per-token cross-chip traffic, and the vocab-parallel logits are
  argmax'd via a (max, index) pmax/psum pair — the full ``(B, V)`` logits
  never materialize on one chip;
* positions come from the model's ``pos_impl`` (learned table or RoPE —
  RoPE rotates each new token at its absolute position).

Layout matches :func:`transformer.init_tp_transformer_lm`; works for both
fused-``wqkv`` and GQA (``wq``/``wkv``) attention params.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import pcast_varying
from .tensor_parallel import row_parallel_dense
from .transformer import _layer_norm, _project_qkv, apply_rope


def _decoder_core(params, head_dim: int, axis_name: str):
    """Shared incremental-decoding machinery:
    ``(embed, attn_block, block_with, rope)``.

    ``attn_block`` derives its batch from ``x`` so the same core serves the
    greedy path (batch B) and beam search (batch B·K); ``block_with`` is
    the underlying scaffolding with a pluggable attend stage (the lazy
    beam swaps in its ancestry-masked attention there).
    """
    d_model = params["embed"].shape[1]
    rope = "pos_embed" not in params

    def embed(tokens, positions):
        from .tensor_parallel import vocab_parallel_embedding

        # The table is VOCAB-SHARDED over the model axis — a plain take
        # would index local rows with global ids.
        x = vocab_parallel_embedding(tokens, params["embed"],
                                     axis_name=axis_name)
        x = x * (d_model ** 0.5)
        if not rope:
            pe = jnp.take(params["pos_embed"], positions, axis=0)
            # (S,) positions broadcast over the batch; (N, S) positions
            # (the serving tick: every slot at its own length) index
            # per row.
            x = x + (pe if positions.ndim == 2 else pe[None])
        return x

    def block_with(x, blk, positions, attend):
        """Shared block scaffolding: ln1 → qkv projection (+rope) →
        pluggable ``attend(q, k, v) -> (ctx, extras)`` → wo row-parallel →
        residual → ln2 → tp_mlp.  ONE copy of the model structure serves
        the physical-cache path and the lazy-beam path; only the
        score/context stage differs."""
        n, s_q = x.shape[0], x.shape[1]
        h = _layer_norm(x, blk["ln1_scale"], blk["ln1_bias"])
        q, k, v = _project_qkv(h, blk["attn"], head_dim, axis_name)
        if rope:
            q = apply_rope(q, positions)
            k = apply_rope(k, positions)
        ctx, extras = attend(q, k, v)
        ctx = ctx.reshape(n, s_q, -1)
        attn_out = row_parallel_dense(ctx, blk["attn"]["wo"],
                                      blk["attn"]["bo"], axis_name=axis_name)
        x = x + attn_out
        h = _layer_norm(x, blk["ln2_scale"], blk["ln2_bias"])
        from .tensor_parallel import tp_mlp
        return (x + tp_mlp(h, blk["mlp"], axis_name=axis_name),) + extras

    def attn_block(x, blk, k_cache, v_cache, positions, write_at, q_valid):
        """x (N,S,D) → block output; caches written at ``write_at + i`` for
        the i-th input position; query i attends cache [:q_valid + i + 1).

        ``write_at``/``q_valid`` may be RANK-1 vectors of length N (the
        serving tick): row ``b`` then writes at ``write_at[b]`` and
        attends its own prefix ``[:q_valid[b] + i + 1)`` — the ragged
        iteration-level batch, on the einsum path (the flash-decode
        kernel maps one scalar position per call).

        Cache layout is FLAT — ``(B, total, H_kv·head_dim)`` — so every
        cache load streams dense 128-lane rows; per-head structure is
        recovered by view reshapes (einsum fallback) or the segmented
        matmuls inside the flash-decode kernel.  The 4-D layouts measured
        0.7-0.9 µs/position against a ~0.3 µs bandwidth floor in the
        compiled decode loop because XLA lowered the q-length-1 dots to
        VPU multiply+reduce fusions over half-empty 64-lane vregs
        (scripts/profile_decode.py + the round-5 HLO dump).
        """
        n = x.shape[0]
        per_row = getattr(write_at, "ndim", 0) == 1

        def attend(q, k, v):
            from ..ops.kv_cache import cache_append
            s_q = q.shape[1]
            hl, hkv = q.shape[2], k.shape[2]
            # one-row decode appends go through the Pallas in-place
            # scatter (ops/kv_cache.py): the XLA dus costs a full extra
            # pass over the cache per tick; prefill's slab write (s_q >
            # 1) falls back to dus inside cache_append
            kc, vc = cache_append(
                k_cache, v_cache, k.reshape(n, s_q, hkv * head_dim),
                v.reshape(n, s_q, hkv * head_dim), write_at, axis=1)
            if s_q > 1 and isinstance(write_at, int) and write_at == 0 \
                    and isinstance(q_valid, int) and q_valid == 0:
                # PREFILL: pure causal self-attention over the prompt —
                # the flash kernels, not the naive einsum, which would
                # materialize an (n, h, s_q, total) fp32 score tensor
                # (268 MB/layer at the bench config; the HLO cost model
                # ranked its softmax reductions above every decode op,
                # and its cost GREW with the cache length, polluting the
                # measured per-token decode rate).
                from ..ops.flash_attention import flash_attention
                ctx = flash_attention(q, k, v, causal=True)
                return ctx.astype(x.dtype), (kc, vc)
            from ..ops.decode_attention import (_pick_block_s,
                                                 decode_attend,
                                                 decode_attend_gqa)
            if s_q == 1 and not per_row and jax.default_backend() == "tpu" \
                    and _pick_block_s(kc.shape[1]) > 0:
                # DECODE on TPU: one flash-decode Pallas pass — cache
                # read once at full lane density (ops/decode_attention).
                # GQA groups ride the beam kernel (g query groups share
                # one cache row, exactly the beam row mapping).  Odd
                # totals with no 8-aligned S-block (e.g. a max_new=1
                # probe's 513) stay on the einsum fallback below.
                if hl == hkv:
                    ctx = decode_attend(
                        q.reshape(n, hl * head_dim), kc, vc, write_at,
                        n_heads=hkv, head_dim=head_dim)
                else:
                    ctx = decode_attend_gqa(
                        q.reshape(n, hl * head_dim), kc, vc, write_at,
                        n_q_heads=hl, n_kv_heads=hkv, head_dim=head_dim)
                return ctx.reshape(n, 1, hl, head_dim), (kc, vc)
            # Fallback (GQA groups, non-TPU backends): grouped einsum
            # attention against head-view reshapes of the flat cache.
            # Per-query valid lengths make one formula serve chunked
            # fills (causal) and decode (full prefix): query i sees
            # q_valid + i + 1 entries.
            total = kc.shape[1]
            kc4 = kc.reshape(n, total, hkv, head_dim)
            vc4 = vc.reshape(n, total, hkv, head_dim)
            if per_row:
                # (n, 1, 1, s_q, 1): each row's own valid prefix
                valid = (q_valid[:, None] + jnp.arange(s_q)[None] + 1
                         )[:, None, None, :, None]
            else:
                valid = (q_valid + jnp.arange(s_q) + 1
                         )[None, None, None, :, None]
            # Grouped attention against the UN-expanded cache (GQA's
            # inference payoff): q heads regrouped onto their KV head — no
            # per-tick n_heads-sized cache copy.
            g = hl // hkv
            q5 = q.reshape(n, s_q, hkv, g, head_dim)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, kc4,
                           preferred_element_type=jnp.float32) \
                / (head_dim ** 0.5)
            mask = (jnp.arange(total)[None, None, None, None, :]
                    < valid)
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(vc4.dtype), vc4,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype)
            return ctx, (kc, vc)

        return block_with(x, blk, positions, attend)

    return embed, attn_block, block_with, rope


def _check_length(params, total: int, rope: bool) -> None:
    if not rope and total > params["pos_embed"].shape[0]:
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds the learned "
            f"pos_embed max_len {params['pos_embed'].shape[0]}; shorten the "
            f"generation or init the model with pos_impl='rope'")


def _kv_heads(params, head_dim: int) -> int:
    a = params["blocks"][0]["attn"]
    return (a["wkv"].shape[1] // (2 * head_dim) if "wkv" in a
            else a["bqkv"].shape[0] // (3 * head_dim))


def _prefill(params, embed, attn_block, prompt, total: int, head_dim: int):
    """Run the full prompt through the stack, returning ``(h_final,
    caches)`` with per-layer KV caches of length ``total`` (prompt written,
    tail zeros) in the flat ``(B, total, H_kv·head_dim)`` layout (see
    ``attn_block``)."""
    b, s_p = prompt.shape
    n_kv = _kv_heads(params, head_dim)
    positions = jnp.arange(s_p)
    x = embed(prompt, positions)
    caches = []
    for blk in params["blocks"]:
        k0 = jnp.zeros((b, total, n_kv * head_dim), x.dtype)
        v0 = jnp.zeros((b, total, n_kv * head_dim), x.dtype)
        x, kc, vc = attn_block(x, blk, k0, v0, positions, 0, 0)
        caches.append((kc, vc))
    return _layer_norm(x, params["lnf_scale"], params["lnf_bias"]), caches


def _greedy_token(table, h_last, axis_name: str):
    """Vocab-parallel greedy next token from ``h_last (N, D)`` against the
    VOCAB-SHARDED embedding ``table (V/P, D)``: per-shard (max, argmax)
    then a global (pmax, pmin-over-winners) pair — the full ``(N, V)``
    logits never materialize on one chip.  An exact-fp tie across shards
    resolves to the LOWEST winning index (argmax convention).  Shared by
    :func:`lm_generate` (``temperature=0``) and the serving engine's
    per-tick step, so batched-slot decode is token-exact against the
    closed-batch generator."""
    from ..ops import collective as _col

    vocab_per = table.shape[0]
    start = jax.lax.axis_index(axis_name) * vocab_per
    logits = jnp.einsum("bd,vd->bv", h_last, table,
                        preferred_element_type=jnp.float32)
    local_best = logits.max(-1)
    local_idx = start + logits.argmax(-1)
    # accounted face: the serving tick's argmax pair must be ledger-
    # visible for the shard-flow static↔dynamic reconciliation
    gbest = _col.pmax(local_best, axis_name)
    winner = (local_best == gbest)
    return _col.pmin(
        jnp.where(winner, local_idx, jnp.int32(2 ** 30)), axis_name)


def _next_token(table, h_last, axis_name, keys, temps, step_pos):
    """Per-row greedy-OR-sampled next token from ``h_last (N, D)`` —
    the serving tick's selection step (ISSUE 9 sampling plumbing).

    ``keys (N, 2) uint32`` is each row's REQUEST rng key, ``temps (N,)``
    its temperature (``<= 0`` → greedy), ``step_pos (N,) int32`` the
    position being generated.  Rows with ``temps > 0`` draw the exact
    Gumbel trick of :func:`lm_generate`'s sampled path — same key
    folding ``fold_in(fold_in(rng, step_pos), axis_index)``, same
    ``(1, V/P)`` uniform draw per row — so a request sampled through
    the shared serving pool is TOKEN-EXACT vs ``lm_generate(rng=...)``
    alone at the same key (the tests/test_serving_disagg.py oracle).
    Rows with ``temps <= 0`` reproduce :func:`_greedy_token` bit-for-
    bit (the selection happens BEFORE the shared pmax/pmin pair, which
    is rowwise).  ONE (pmax, pmin) pair either way: the full ``(N, V)``
    logits never materialize on one chip."""
    from ..ops import collective as _col

    vocab_per = table.shape[0]
    start = jax.lax.axis_index(axis_name) * vocab_per
    logits = jnp.einsum("bd,vd->bv", h_last, table,
                        preferred_element_type=jnp.float32)
    g_best = logits.max(-1)
    g_idx = start + logits.argmax(-1)

    def row_gumbel(key, sp):
        # mirror lm_generate's logits_next exactly: step-pos salt, then
        # axis salt, then a (1, V/P) uniform (the B=1 oracle's shape —
        # threefry bits depend on the flat draw count, asserted by the
        # token-exactness test)
        k = jax.random.fold_in(jax.random.fold_in(key, sp),
                               jax.lax.axis_index(axis_name))
        return -jnp.log(-jnp.log(
            jax.random.uniform(k, (1, vocab_per), minval=1e-20)))[0]

    sample = temps > 0.0

    def sampled_branch():
        gumbel = jax.vmap(row_gumbel)(keys, step_pos)
        safe_t = jnp.where(sample, temps, 1.0)
        scored = logits / safe_t[:, None] + gumbel
        s_best = scored.max(-1)
        s_idx = start + scored.argmax(-1)
        return (jnp.where(sample, s_best, g_best),
                jnp.where(sample, s_idx, g_idx))

    # an all-greedy batch (the serving default) skips the N×(V/P)
    # threefry draw entirely — cond, not where, so the hot decode tick
    # pays for sampling only when some row actually samples; no
    # collectives inside either branch (the shared pmax/pmin pair
    # below runs unconditionally, so every rank takes the same path
    # through the accounted face)
    local_best, local_idx = jax.lax.cond(
        jnp.any(sample), sampled_branch, lambda: (g_best, g_idx))
    # accounted face, like _greedy_token: the serving tick's argmax pair
    # stays ledger-visible for the shard-flow reconciliation
    gbest = _col.pmax(local_best, axis_name)
    winner = (local_best == gbest)
    return _col.pmin(
        jnp.where(winner, local_idx, jnp.int32(2 ** 30)), axis_name)


def lm_prefill(params, prompt, total: int, *, head_dim: int, axis_name: str):
    """Iteration-level PREFILL step: run the full ``prompt (B, S_p)``
    through the stack, returning ``(h, caches)`` — ``h (B, S_p, D)`` is
    the post-final-layer-norm hidden state (greedy-select the first
    generated token from ``h[:, s_real - 1]``), and ``caches`` is the
    per-layer list of flat ``(B, total, H_kv·head_dim)`` K/V pairs with
    the prompt written at rows ``[0, S_p)``.

    Call INSIDE ``shard_map`` with the model axis bound.  This is the
    "prefill(prompt) → slot" half of the serving engine's per-tick API
    (``chainermn_tpu/serving/engine.py``): the caches slot straight into
    a pool row, and generation continues via :func:`lm_decode_tick` —
    no closed ``lax.scan`` batch required.
    """
    embed, attn_block, _, rope = _decoder_core(params, head_dim, axis_name)
    _check_length(params, total, rope)
    return _prefill(params, embed, attn_block, prompt, total, head_dim)


def lm_decode_tick(params, tokens, caches, pos, *, head_dim: int,
                   axis_name: str):
    """ONE iteration-level decode tick: consume ``tokens (N,)`` (the last
    emitted token per row), write each row's K/V at ``pos`` and attend
    its own cache prefix ``[0, pos]``, returning ``(h_last (N, D),
    new_caches)`` — feed ``h_last`` to :func:`_greedy_token` (or a
    sampler) for the next token.

    ``pos`` is a scalar (all rows at the same position — the closed
    ``lm_generate`` batch) or an ``(N,)`` int32 vector (every row at its
    OWN position — the serving engine's slot pool, where sequences are
    inserted and evicted between ticks).  Call INSIDE ``shard_map`` with
    the model axis bound.
    """
    embed, attn_block, _, _ = _decoder_core(params, head_dim, axis_name)
    per_row = getattr(pos, "ndim", 0) == 1
    positions = pos[:, None] if per_row else pos[None]
    x = embed(tokens[:, None], positions)
    new_caches = []
    for blk, (kc, vc) in zip(params["blocks"], caches):
        x, kc, vc = attn_block(x, blk, kc, vc, positions, pos, pos)
        new_caches.append((kc, vc))
    h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    return h[:, -1], new_caches


def _make_face(mesh: Optional[Mesh], axis_name: str, inner, has_rng: bool,
               requires_rng: bool = False):
    """Shared jit face for the generators: resolve the mesh, cache one
    compiled shard_map program per param STRUCTURE, device_put per spec."""
    from .._compat import shard_map
    from .transformer import transformer_lm_specs

    if mesh is None:
        from ..topology import make_mesh
        mesh = make_mesh(axis_name=axis_name)

    cache = {}

    def apply(params, prompt, rng=None):
        specs = transformer_lm_specs(params, axis_name)
        key = jax.tree_util.tree_structure(specs)
        if key not in cache:
            in_specs = (specs, P(), P()) if has_rng else (specs, P())
            cache[key] = jax.jit(shard_map(
                inner, mesh=mesh, in_specs=in_specs, out_specs=P()))
        sharded = jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            params, specs)
        if has_rng:
            if rng is None:
                if requires_rng:
                    raise ValueError(
                        "temperature > 0 samples tokens and needs an "
                        "explicit rng: pass jax.random.PRNGKey(...) as the "
                        "third argument (the old silent PRNGKey(0) fallback "
                        "made every default-rng call draw IDENTICAL token "
                        "sequences)")
                # unused at temperature == 0: greedy decode never consumes
                # it, a constant is exactly right (keeps the jit signature)
                rng = jax.random.PRNGKey(0)  # spmd-lint: disable=prng-constant-key
            return cache[key](sharded, prompt, rng)
        return cache[key](sharded, prompt)

    return apply


def lm_generate(params, prompt, rng: Optional[jax.Array] = None, *,
                head_dim: int, axis_name: str,
                max_new_tokens: int, temperature: float = 0.0):
    """Generate ``max_new_tokens`` greedily (or sampled when
    ``temperature > 0``) from ``prompt (B, S_p) int32``.

    Call INSIDE ``shard_map`` with the model axis bound (use
    :func:`make_lm_generator` for the jit face).  Returns ``(B,
    max_new_tokens) int32``.

    RNG CONTRACT: ``temperature > 0`` requires an explicit ``rng`` —
    sampling with a process-constant default key would draw the SAME
    Gumbel noise on every call, so every "random" generation from the
    same prompt would emit identical tokens.  The jit face
    (:func:`make_lm_generator`) enforces this with a ``ValueError``;
    ``temperature == 0`` ignores ``rng`` entirely.
    """
    b, s_p = prompt.shape
    total = s_p + max_new_tokens

    def logits_next(h_last, step_pos):
        """Vocab-parallel next-token choice from ``h_last (B, D)``;
        ``step_pos`` (the position being generated) salts the sampling key
        so every step draws FRESH Gumbel noise."""
        table = params["embed"]
        if temperature <= 0.0:
            return _greedy_token(table, h_last, axis_name)
        vocab_per = table.shape[0]
        start = jax.lax.axis_index(axis_name) * vocab_per
        logits = jnp.einsum("bd,vd->bv", h_last, table,
                            preferred_element_type=jnp.float32)
        # Gumbel trick on the SHARDED logits: per-shard argmax of
        # (logit/T + gumbel) then a global (value, index) max — exact
        # categorical sampling without materializing (B, V) anywhere.
        key = jax.random.fold_in(
            jax.random.fold_in(rng, step_pos),
            jax.lax.axis_index(axis_name))
        gumbel = -jnp.log(-jnp.log(
            jax.random.uniform(key, logits.shape, minval=1e-20)))
        scored = logits / temperature + gumbel
        local_best = scored.max(-1)
        local_idx = start + scored.argmax(-1)
        gbest = jax.lax.pmax(local_best, axis_name)
        # Global argmax; an exact-fp tie across shards resolves to the
        # LOWEST winning index (argmax convention), via pmin over winners.
        winner = (local_best == gbest)
        return jax.lax.pmin(
            jnp.where(winner, local_idx, jnp.int32(2 ** 30)), axis_name)

    # ---- prefill: full prompt through the stack, caches written ----
    h, caches = lm_prefill(params, prompt, total, head_dim=head_dim,
                           axis_name=axis_name)
    first = logits_next(h[:, -1], jnp.int32(s_p))

    # ---- decode: one iteration-level tick per scan step (the SAME
    # per-tick step the serving engine drives between insert/evict) ----
    def tick(carry, i):
        token, caches = carry
        pos = s_p + i - 1  # tick i consumes the (i-1)-th generated token
        h_last, new_caches = lm_decode_tick(
            params, token, caches, pos, head_dim=head_dim,
            axis_name=axis_name)
        nxt = logits_next(h_last, s_p + i)
        return (nxt, new_caches), token

    (last, _), toks = jax.lax.scan(
        tick, (first, caches), jnp.arange(1, max_new_tokens))
    # toks carries tokens 0..max_new-2 (each tick emits its INPUT token);
    # append the final one.
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)
    return out.astype(jnp.int32)


def lm_generate_beam(params, prompt, *, head_dim: int, axis_name: str,
                     max_new_tokens: int, beam_size: int,
                     lazy_reorder: bool = True, attend_impl: str = "auto"):
    """Beam search with the KV cache: the highest-cumulative-log-prob
    continuation of each prompt among ``beam_size`` beams.

    Fixed-length beams (the toy LMs here have no EOS semantics); exact
    under the cumulative-log-prob objective because each beam contributes
    its top-``beam_size`` tokens and the global top-``beam_size`` of
    ``K·K`` candidates can never need a token outside a beam's own top-K.
    TP-composed: per-shard top-K of the vocab-sharded log-probs, one small
    all_gather of ``K`` candidates per shard, replicated merge.  Returns
    ``(B, max_new_tokens) int32`` — the best beam.

    ``lazy_reorder=True`` (default) kills the per-tick cache-reorder
    bandwidth tax that made beam-4 cost 9× greedy per token (round-3
    BENCH): instead of physically gathering the (B·K, total, h, d) caches
    by parent each step (read+write of the whole cache, on top of the
    read attention itself needs), the caches are never moved —

    * prompt K/V is computed once at batch B and SHARED by all beams
      (read once per tick, not K times, and not stored K times);
    * each beam SLOT owns an append-only generated-token cache; a tiny
      ``(B, K, max_new)`` int32 ancestry table says which slot held this
      beam's token at each past position, and only the table is
      reordered by parent (kilobytes, not the gigabyte cache);
    * attention scores are computed against ALL K slots and the ancestry
      mask selects the one true writer per position — K× more score
      FLOPs on a (head_dim)-deep dot, nothing on the bandwidth that
      actually bounds decode.  Softmax runs over the joint
      prompt+generated axis, so the result is numerically the standard
      beam attention.

    ``lazy_reorder=False`` keeps the physical-gather path (the parity
    oracle for tests).
    """
    b, s_p = prompt.shape
    k = beam_size
    total = s_p + max_new_tokens
    embed, attn_block, block_with, rope = _decoder_core(
        params, head_dim, axis_name)
    _check_length(params, total, rope)
    blocks = params["blocks"]

    def shard_logprobs(h_last):
        """(N, D) → local log-probs (N, V/P) + this shard's vocab offset.
        Normalized GLOBALLY (pmax/psum logsumexp across shards)."""
        table = params["embed"]
        logits = jnp.einsum("bd,vd->bv", h_last, table,
                            preferred_element_type=jnp.float32)
        m = jax.lax.pmax(logits.max(-1), axis_name)              # (N,)
        z = jax.lax.psum(jnp.exp(logits - m[:, None]).sum(-1), axis_name)
        logz = m + jnp.log(z)
        start = jax.lax.axis_index(axis_name) * table.shape[0]
        return logits - logz[:, None], start

    def global_topk(h_last):
        """(N, D) → (values (N, K), token_ids (N, K)) — global top-K over
        the sharded vocab; invariant outputs (pmax over value-identical
        gathers fixes the VMA type at zero numeric cost)."""
        logp, start = shard_logprobs(h_last)
        v_loc, i_loc = jax.lax.top_k(logp, k)                    # (N, K)
        i_loc = i_loc + start
        gv = jax.lax.all_gather(v_loc, axis_name, axis=1, tiled=True)
        gi = jax.lax.all_gather(i_loc, axis_name, axis=1, tiled=True)
        gv = jax.lax.pmax(gv, axis_name)   # identical values; type → invariant
        gi = jax.lax.pmax(gi, axis_name)
        v, pos = jax.lax.top_k(gv, k)                            # (N, K)
        ids = jnp.take_along_axis(gi, pos, axis=1)
        return v, ids

    if attend_impl not in ("auto", "kernel", "einsum"):
        raise ValueError(f"attend_impl must be auto|kernel|einsum, "
                         f"got {attend_impl!r}")
    if lazy_reorder:
        return _beam_lazy(params, prompt, embed, attn_block, block_with,
                          global_topk, head_dim=head_dim,
                          axis_name=axis_name,
                          max_new_tokens=max_new_tokens, beam_size=k,
                          attend_impl=attend_impl)

    # ---- prefill once at batch B, then tile caches to B·K ----
    h, caches = _prefill(params, embed, attn_block, prompt, total, head_dim)
    caches = [(jnp.repeat(kc, k, axis=0), jnp.repeat(vc, k, axis=0))
              for kc, vc in caches]
    v0k, i0k = global_topk(h[:, -1])                             # (B, K)
    scores = v0k                                                 # (B, K)
    tokens = i0k.astype(jnp.int32)                               # live beams
    toks_buf = jnp.zeros((b, k, max_new_tokens), jnp.int32)
    toks_buf = toks_buf.at[:, :, 0].set(tokens)

    def tick(carry, i):
        tokens, scores, toks_buf, caches = carry
        pos = s_p + i - 1
        x = embed(tokens.reshape(b * k)[:, None], pos[None])     # (B·K, 1, D)
        new_caches = []
        for blk, (kc, vc) in zip(blocks, caches):
            x, kc, vc = attn_block(x, blk, kc, vc, pos[None], pos, pos)
            new_caches.append((kc, vc))
        h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
        tokens, scores, toks_buf, parent = _merge_candidates(
            global_topk, h, scores, toks_buf, i, b, k)
        # Reindex the full caches by the winning parents (the bandwidth
        # tax the lazy path avoids).
        reind = []
        for kc, vc in new_caches:
            shp = kc.shape  # (B·K, total, hkv·hd) flat
            kc = jnp.take_along_axis(
                kc.reshape((b, k) + shp[1:]),
                parent[:, :, None, None], axis=1).reshape(shp)
            vc = jnp.take_along_axis(
                vc.reshape((b, k) + shp[1:]),
                parent[:, :, None, None], axis=1).reshape(shp)
            reind.append((kc, vc))
        return (tokens, scores, toks_buf, reind), None

    if max_new_tokens > 1:
        (tokens, scores, toks_buf, _), _ = jax.lax.scan(
            tick, (tokens, scores, toks_buf, caches),
            jnp.arange(1, max_new_tokens))
    # top_k keeps beams score-sorted, so beam 0 is the winner by invariant.
    return toks_buf[:, 0].astype(jnp.int32)


def _merge_candidates(global_topk, h, scores, toks_buf, i, b, k):
    """Shared beam bookkeeping for BOTH cache strategies: global top-K of
    the K·K candidate continuations, then reorder the token history by the
    winning parents.  Returns ``(tokens, scores, toks_buf, parent)`` —
    the caller decides what ELSE the parents reindex (physical caches vs
    the ancestry table)."""
    v_k, i_k = global_topk(h[:, -1])                             # (B·K, K)
    cand = scores[:, :, None] + v_k.reshape(b, k, k)             # (B, K, K)
    flat = cand.reshape(b, k * k)
    scores, pos_flat = jax.lax.top_k(flat, k)                    # (B, K)
    parent = pos_flat // k                                       # (B, K)
    tokens = jnp.take_along_axis(
        i_k.reshape(b, k, k).reshape(b, k * k), pos_flat, axis=1
    ).astype(jnp.int32)
    toks_buf = jnp.take_along_axis(toks_buf, parent[:, :, None], axis=1)
    toks_buf = toks_buf.at[:, :, i].set(tokens)
    return tokens, scores, toks_buf, parent


def _beam_lazy(params, prompt, embed, attn_block, block_with, global_topk, *,
               head_dim: int, axis_name: str, max_new_tokens: int,
               beam_size: int, attend_impl: str = "auto"):
    """Ancestry-indexed beam decode body (see ``lm_generate_beam``
    docstring): shared prompt cache + per-slot append-only generated
    caches + a reordered index table instead of reordered caches."""
    b, s_p = prompt.shape
    k = beam_size
    blocks = params["blocks"]
    n_kv = _kv_heads(params, head_dim)

    # prefill at batch B; caches sized to the PROMPT only (they are never
    # extended — generated tokens live in the per-slot caches)
    h, pcaches = _prefill(params, embed, attn_block, prompt, s_p, head_dim)
    v0k, i0k = global_topk(h[:, -1])                             # (B, K)
    scores = v0k
    tokens = i0k.astype(jnp.int32)
    toks_buf = jnp.zeros((b, k, max_new_tokens), jnp.int32)
    toks_buf = toks_buf.at[:, :, 0].set(tokens)
    def varying_zeros(shape, dtype):
        # the scan writes device-VARYING K/V (they come from sharded
        # params) into these buffers, so the initial carry must already
        # carry the varying-manual-axes type
        z = jnp.zeros(shape, dtype)
        return pcast_varying(z, axis_name)

    # TIME-MAJOR flat generated caches: row t·k + slot.  Valid rows are a
    # contiguous PREFIX [0, i·k) — and a leading-prefix slice into a
    # Pallas operand is measured copy-free on v5e — so the staged scan
    # below shrinks the streamed segment to the live prefix per stage
    # instead of always reading all k·max_new rows (docs/PERF.md).
    gen = [(varying_zeros((b, max_new_tokens * k, n_kv * head_dim), pk.dtype),
            varying_zeros((b, max_new_tokens * k, n_kv * head_dim), pv.dtype))
           for pk, pv in pcaches]
    anc = jnp.zeros((b, k, max_new_tokens), jnp.int32)
    gen_pos = jnp.arange(max_new_tokens)
    slot_ids = jnp.arange(k)

    def lazy_attn(x, blk, pk, pv, gk, gv, amask_tl, pos, i, t_hi):
        """One block for the (B·K, 1, D) tick input, via the SHARED
        ``block_with`` scaffolding — only the attend stage differs from
        the physical path.

        ``amask_tl (B, K, max_new, K_slots) bool`` — TIME-MAJOR
        (b, beam s, position t, slot l) to match the generated-cache row
        order t·k + l: ancestry ∧ validity — True where slot ``l``'s
        generated row at position ``t`` belongs to beam ``s``'s history.
        Exactly one slot is True per valid t.  ``t_hi`` (static, per
        scan stage) bounds the live prefix window that is read."""

        def attend(q, kk, vv):
            # append this tick's K/V — ALL k slots' rows [(i-1)k, ik) in
            # ONE Pallas range scatter (ops/kv_cache.py, rows=k).
            # Layouts: the shared PROMPT cache is FLAT (b, s_p, hkv·hd);
            # the generated caches are TIME-MAJOR flat
            # (b, max_new·k, hkv·hd), row t·k + slot, read through the
            # static live-prefix window [:t_hi·k] (copy-free slice).
            from ..ops.decode_attention import (_pick_block_s,
                                                beam_attend_parts,
                                                merge_attend_parts)
            from ..ops.kv_cache import cache_append
            gk2, gv2 = cache_append(
                gk, gv, kk.reshape(b, k, n_kv * head_dim),
                vv.reshape(b, k, n_kv * head_dim), (i - 1) * k, axis=1,
                pos_aligned=True)  # (i-1)·k is k-aligned by construction
            hl = q.shape[2]
            g = hl // n_kv
            scale = head_dim ** 0.5
            gk_w = gk2[:, :t_hi * k]
            gv_w = gv2[:, :t_hi * k]
            kernel_ok = (g == 1 and _pick_block_s(s_p) > 0
                         and _pick_block_s(k * t_hi) > 0)
            # ``attend_impl='einsum'`` forces the fallback (the on-chip
            # parity oracle for the kernel path); 'kernel' forces the
            # Pallas path (interpret off-TPU — note interpret-Pallas
            # under shard_map trips VMA checks, so off-chip coverage of
            # the flatten/mask convention lives in tests/test_decode.py
            # :: test_beam_kernel_slot_flattening_convention instead).
            if kernel_ok and (attend_impl == "kernel"
                              or (attend_impl == "auto"
                                  and jax.default_backend() == "tpu")):
                # flash-decode beam path: one Pallas pass per segment
                # (shared prompt, ancestry-masked slots), merged with the
                # standard (m, l, acc) flash combine — the einsum path
                # below pays the same VPU half-lane tax greedy decode did.
                interp = jax.default_backend() != "tpu"
                qf = q.reshape(b * k, hl * head_dim)
                part_p = beam_attend_parts(
                    qf, pk, pv, beams=k, n_heads=n_kv, head_dim=head_dim,
                    interpret=interp)
                part_g = beam_attend_parts(
                    qf, gk_w, gv_w,
                    amask_tl[:, :, :t_hi, :].reshape(b, k, t_hi * k)
                    .astype(jnp.int8),
                    beams=k, n_heads=n_kv, head_dim=head_dim,
                    interpret=interp)
                ctx = merge_attend_parts(
                    [part_p, part_g], n_heads=n_kv, head_dim=head_dim,
                    dtype=x.dtype)
                return ctx.reshape(b * k, 1, hl, head_dim), (gk2, gv2)
            q6 = q.reshape(b, k, n_kv, g, head_dim)
            # prompt scores: shared cache, read ONCE for all K beams
            # (flat caches viewed per-head for the einsum fallback)
            pk4 = pk.reshape(b, s_p, n_kv, head_dim)
            pv4 = pv.reshape(b, s_p, n_kv, head_dim)
            gk5 = gk_w.reshape(b, t_hi, k, n_kv, head_dim)
            gv5 = gv_w.reshape(b, t_hi, k, n_kv, head_dim)
            sp = jnp.einsum("bshgd,bthd->bshgt", q6, pk4,
                            preferred_element_type=jnp.float32) / scale
            # generated scores against ALL slots; the ancestry mask
            # selects the one true writer per position
            sg = jnp.einsum("bshgd,btlhd->bshgtl", q6, gk5,
                            preferred_element_type=jnp.float32) / scale
            sg = jnp.where(amask_tl[:, :, None, None, :t_hi, :], sg, -1e30)
            joint = jnp.concatenate(
                [sp, sg.reshape(b, k, n_kv, g, t_hi * k)], axis=-1)
            p = jax.nn.softmax(joint, axis=-1)
            p_p = p[..., :s_p].astype(pv.dtype)
            p_g = p[..., s_p:].reshape(sg.shape).astype(gv2.dtype)
            ctx = (jnp.einsum("bshgt,bthd->bshgd", p_p, pv4,
                              preferred_element_type=jnp.float32)
                   + jnp.einsum("bshgtl,btlhd->bshgd", p_g, gv5,
                                preferred_element_type=jnp.float32))
            return ctx.astype(x.dtype).reshape(b * k, 1, hl, head_dim), \
                (gk2, gv2)

        return block_with(x, blk, pos[None], attend)

    def make_tick(t_hi):
        def tick(carry, i):
            tokens, scores, toks_buf, anc, gen = carry
            pos = s_p + i - 1
            # position i-1 was written by each slot itself
            anc = jax.lax.dynamic_update_slice_in_dim(
                anc, jnp.broadcast_to(slot_ids[None, :, None], (b, k, 1)),
                i - 1, axis=2)
            # ancestry ∧ validity (only positions < i exist), in
            # (b, s, t, l) order to match the time-major row = t·k + l
            amask_tl = ((anc[:, :, None, :] == slot_ids[None, None, :, None])
                        & (gen_pos[None, None, None, :] < i)
                        ).transpose(0, 1, 3, 2)
            x = embed(tokens.reshape(b * k)[:, None], pos[None])
            new_gen = []
            for blk, (pk, pv), (gk, gv) in zip(blocks, pcaches, gen):
                x, gk, gv = lazy_attn(x, blk, pk, pv, gk, gv, amask_tl,
                                      pos, i, t_hi)
                new_gen.append((gk, gv))
            h = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
            tokens, scores, toks_buf, parent = _merge_candidates(
                global_topk, h, scores, toks_buf, i, b, k)
            # the parents reorder only the ancestry table (kilobytes) —
            # never the caches; that is the whole point of the lazy path
            anc = jnp.take_along_axis(anc, parent[:, :, None], axis=1)
            return (tokens, scores, toks_buf, anc, new_gen), None
        return tick

    if max_new_tokens > 1:
        # STAGED scans: stage ticks [lo, hi) read only the live-prefix
        # window [:hi·k] of the generated caches (always-full reads were
        # ~half dead; the prefix slice is copy-free).  The chunk
        # heuristic below yields max_new/128 stages for 128-multiples
        # (e.g. 4 stages at 512 → ~5/8 of full-segment traffic), exactly
        # 2 stages for other even counts ≥ 8 (~3/4 of the traffic), and
        # a single full-window scan otherwise.  One tick body compiles
        # per stage, so finer chunking trades compile time for traffic.
        if max_new_tokens % 128 == 0:
            chunk = 128
        elif max_new_tokens % 2 == 0 and max_new_tokens >= 8:
            chunk = max_new_tokens // 2
        else:
            chunk = max_new_tokens
        carry = (tokens, scores, toks_buf, anc, gen)
        lo = 1
        for hi in range(chunk, max_new_tokens + 1, chunk):
            carry, _ = jax.lax.scan(make_tick(hi), carry,
                                    jnp.arange(lo, hi))
            lo = hi
        (tokens, scores, toks_buf, anc, gen) = carry
    return toks_buf[:, 0].astype(jnp.int32)


def make_lm_beam_generator(mesh: Optional[Mesh] = None,
                           axis_name: str = "model", *, head_dim: int,
                           max_new_tokens: int, beam_size: int,
                           lazy_reorder: bool = True,
                           attend_impl: str = "auto"):
    """Eager/jit face of :func:`lm_generate_beam`: ``fn(params, prompt) ->
    (B, max_new) tokens`` over TP-sharded global params."""
    return _make_face(
        mesh, axis_name,
        partial(lm_generate_beam, head_dim=head_dim, axis_name=axis_name,
                max_new_tokens=max_new_tokens, beam_size=beam_size,
                lazy_reorder=lazy_reorder, attend_impl=attend_impl),
        has_rng=False)


def make_lm_generator(mesh: Optional[Mesh] = None, axis_name: str = "model",
                      *, head_dim: int, max_new_tokens: int,
                      temperature: float = 0.0):
    """Eager/jit face: ``fn(params, prompt[, rng]) -> (B, max_new) tokens``
    over TP-sharded global params (``transformer_lm_specs`` layout).

    RNG CONTRACT: with ``temperature > 0`` the ``rng`` argument is
    REQUIRED (``ValueError`` otherwise) — a silent default key would make
    every call sample the identical token sequence.  At ``temperature ==
    0`` (greedy) ``rng`` is ignored and may be omitted."""
    return _make_face(
        mesh, axis_name,
        partial(lm_generate, head_dim=head_dim, axis_name=axis_name,
                max_new_tokens=max_new_tokens, temperature=temperature),
        has_rng=True, requires_rng=temperature > 0.0)
