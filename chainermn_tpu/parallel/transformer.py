"""Tensor-parallel Transformer LM: Megatron-style sharding over one axis.

Reference relationship: the reference shipped the raw differentiable
collectives that make intra-layer model parallelism *expressible*
(SURVEY.md §2.8 "TP: expressible manually via functions.allgather/alltoall;
no library support") but no transformer and no TP library.  This module is
that missing layer, built TPU-first:

* **Attention**: QKV projections are column-parallel (heads sharded over
  the model axis — each chip owns ``H/P`` heads and attends them with the
  in-tree flash kernel or plain XLA attention), the output projection is
  row-parallel.  ONE psum of cross-chip traffic per attention block.
* **MLP**: column→gelu→row (:func:`tensor_parallel.tp_mlp`), one psum.
* **Embedding / LM head**: vocab-parallel (each chip owns a vocab shard);
  the logits stay vocab-sharded and the cross-entropy computes from the
  sharded logits with two scalar-sized psums (max and log-sum-exp legs) —
  the full ``(B, S, V)`` logits never materialize on one chip.
* **LayerNorms, residuals**: replicated compute (cheap, bandwidth-bound).

Compose with data parallelism over a ``('data', 'model')`` mesh via
``parallel.hybrid.make_hybrid_shard_map_step`` — the loss below is per-token
mean over the LOCAL batch shard, exactly what that builder pmeans.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .._compat import pcast_varying, typeof as _typeof
from .tensor_parallel import column_parallel_dense, row_parallel_dense, tp_mlp


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def apply_rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding over ``(B, S, H, head_dim)``.

    Beyond-reference (learned absolute positions were already beyond the
    2017 reference; RoPE is the long-context-era standard — relative
    attention decay, extrapolation-friendly): rotate each head-dim pair by
    ``position · base^(-2i/d)``.  ``positions (S,)`` are GLOBAL token
    positions, so sequence-parallel shards pass ``my_shard_offset +
    arange(S_local)`` and the ring stays exact.  A 2-D ``positions
    (B, S)`` rotates each batch row at its OWN positions — the serving
    tick's contract, where every slot sits at a different sequence
    length.  ``head_dim`` must be even.
    """
    half = x.shape[-1] // 2
    if x.shape[-1] % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {x.shape[-1]}")
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    if positions.ndim == 2:                                  # per-row (B, S)
        ang = positions.astype(jnp.float32)[..., None] * freqs  # (B, S, half)
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    else:
        ang = positions.astype(jnp.float32)[:, None] * freqs[None]  # (S, half)
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def _project_qkv(h, a, head_dim: int, axis_name: str):
    """Shared QKV projection for both attention param layouts: returns
    local ``q (B, S, Hl, hd)`` and ``k, v (B, S, Hkv_l, hd)``.

    Works for TP-sharded weights (column shards produce local heads) and
    replicated weights (SP blocks — full heads) alike, since
    ``column_parallel_dense`` is a local matmul.  Single home for the
    fused-``wqkv`` vs GQA-``wq``/``wkv`` branch used by ``tp_attention``,
    ``sp_block`` and the KV-cache decoder.
    """
    b, s, _ = h.shape
    if "wq" in a:
        q = column_parallel_dense(h, a["wq"], a["bq"], axis_name=axis_name)
        q = q.reshape(b, s, -1, head_dim)
        kv = column_parallel_dense(h, a["wkv"], a["bkv"], axis_name=axis_name)
        if kv.shape[-1] % (2 * head_dim):
            raise ValueError(
                f"local wkv shard width {kv.shape[-1]} is not a whole "
                f"number of KV heads (2*head_dim={2 * head_dim}) — "
                f"n_kv_heads must be divisible by the model-axis size")
        kv = kv.reshape(b, s, -1, 2, head_dim)
        return q, kv[..., 0, :], kv[..., 1, :]
    qkv = column_parallel_dense(h, a["wqkv"], a["bqkv"], axis_name=axis_name)
    qkv = qkv.reshape(b, s, -1, 3, head_dim)
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def tp_attention(x, params, *, head_dim: int, axis_name: str,
                 causal: bool = True, attn_impl: str = "auto",
                 positions=None):
    """Multi-head self-attention with heads sharded over ``axis_name``.

    ``x``: replicated-local ``(B, S, D)``; ``params``: local shards
    ``wqkv (D, 3·D/P)`` laid out HEAD-MAJOR (columns grouped per head as
    ``[q_h | k_h | v_h]`` so a contiguous column shard is whole heads —
    see :func:`init_tp_transformer_lm`), ``bqkv (3·D/P,)``,
    ``wo (D/P, D)``, replicated ``bo (D,)``.  One psum (in the
    row-parallel output projection) per call.
    """
    from ..ops.flash_attention import resolve_attn_impl

    b, s, d = x.shape
    attn_impl = resolve_attn_impl(attn_impl, s)
    q, k, v = _project_qkv(x, params, head_dim, axis_name)
    h_local = q.shape[2]

    if positions is not None:  # RoPE (positions are global token indices)
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)

    ctx = _attend_local_heads(q, k, v, causal=causal, attn_impl=attn_impl,
                              head_dim=head_dim)
    ctx = ctx.reshape(b, s, h_local * head_dim)             # (B, S, D/P)
    return row_parallel_dense(ctx, params["wo"], params["bo"],
                              axis_name=axis_name)


def _attend_local_heads(q, k, v, *, causal, attn_impl, head_dim):
    """Attention over this chip's heads, full sequence: ``q (B, S, Hl, hd)``,
    GQA-aware (``k``/``v`` may carry fewer heads).  Shared by the
    replicated-activation (:func:`tp_attention`) and Megatron-SP
    (:func:`tp_attention_sp`) paths."""
    if attn_impl == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    h_local, s = q.shape[2], q.shape[1]
    if k.shape[2] != h_local:  # GQA on the materializing path
        g = h_local // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / (head_dim ** 0.5)
    if causal:
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def tp_block(x, params, *, head_dim: int, axis_name: str, causal: bool = True,
             attn_impl: str = "auto", positions=None):
    """Pre-norm transformer block: LN→attn→residual, LN→MLP→residual."""
    h = _layer_norm(x, params["ln1_scale"], params["ln1_bias"])
    x = x + tp_attention(h, params["attn"], head_dim=head_dim,
                         axis_name=axis_name, causal=causal,
                         attn_impl=attn_impl, positions=positions)
    h = _layer_norm(x, params["ln2_scale"], params["ln2_bias"])
    return x + tp_mlp(h, params["mlp"], axis_name=axis_name)


def tp_attention_sp(x, params, *, head_dim: int, axis_name: str,
                    causal: bool = True, attn_impl: str = "auto",
                    positions=None):
    """Megatron-SP attention: ``x (B, S/P, D)`` SEQUENCE-sharded.

    The entry sequence all-gather fuses into the QKV projection
    (:func:`tensor_parallel.gather_seq_matmul` — ring hops overlap the
    matmul chunks) and the exit is a fused matmul+reduce-scatter back to
    sequence shards, replacing :func:`tp_attention`'s psum.  Heads stay
    TP-sharded; attention itself sees the full sequence.  ``positions``
    must be the GLOBAL ``arange(S)`` (attention runs post-gather).
    """
    from ..ops.flash_attention import resolve_attn_impl

    from .tensor_parallel import gather_seq_matmul, matmul_scatter_seq

    b, s_loc, d = x.shape
    s = s_loc * jax.lax.axis_size(axis_name)
    attn_impl = resolve_attn_impl(attn_impl, s)
    if "wq" in params:
        q = gather_seq_matmul(x, params["wq"], params["bq"],
                              axis_name=axis_name).reshape(b, s, -1, head_dim)
        kv = gather_seq_matmul(x, params["wkv"], params["bkv"],
                               axis_name=axis_name)
        kv = kv.reshape(b, s, -1, 2, head_dim)
        k, v = kv[..., 0, :], kv[..., 1, :]
    else:
        qkv = gather_seq_matmul(x, params["wqkv"], params["bqkv"],
                                axis_name=axis_name)
        qkv = qkv.reshape(b, s, -1, 3, head_dim)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    if positions is not None:
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
    ctx = _attend_local_heads(q, k, v, causal=causal, attn_impl=attn_impl,
                              head_dim=head_dim)
    ctx = ctx.reshape(b, s, -1)                              # (B, S, D/P)
    return matmul_scatter_seq(ctx, params["wo"], params["bo"],
                              axis_name=axis_name)


def tp_block_sp(x, params, *, head_dim: int, axis_name: str,
                causal: bool = True, attn_impl: str = "auto",
                positions=None):
    """Megatron-SP transformer block over SEQUENCE-sharded ``(B, S/P, D)``.

    Same params/layout as :func:`tp_block`; LayerNorms and residuals are
    per-position so they run on the local shard (1/P the replicated
    compute), and all four cross-chip collectives (attention/MLP entry
    gathers, exit reduce-scatters) ride the overlapped
    ``collective_matmul`` rings.  Numerically equal to :func:`tp_block`
    on the gathered sequence up to reassociation (tests pin it).
    """
    from .tensor_parallel import tp_mlp_sp

    h = _layer_norm(x, params["ln1_scale"], params["ln1_bias"])
    x = x + tp_attention_sp(h, params["attn"], head_dim=head_dim,
                            axis_name=axis_name, causal=causal,
                            attn_impl=attn_impl, positions=positions)
    h = _layer_norm(x, params["ln2_scale"], params["ln2_bias"])
    return x + tp_mlp_sp(h, params["mlp"], axis_name=axis_name)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_vp_nll(h2, table, local_t, axis_name, explicit_psum):
    """Per-row NLL via the fused-CE kernels with VOCAB-SHARDED tables:
    shard-local online stats, pmax/psum combine, global-LSE backward.
    ``h2 (T, D)``, ``table (V/P, D)``, ``local_t (T,)`` already shifted to
    this shard's range (out-of-range ids match nothing — exactly the
    one-hot masking the kernels implement).

    ``explicit_psum``: True when vma tracking is OFF (``check_vma=False``
    contexts) — the backward then hand-psums dh over ``axis_name``; with
    tracking on, the caller's pcast promotions route every cross-shard
    gradient reduction through their transposes instead."""
    return _fused_vp_nll_fwd(h2, table, local_t, axis_name, explicit_psum)[0]


def _fused_vp_nll_fwd(h2, table, local_t, axis_name, explicit_psum):
    from ..ops.fused_ce import ce_stats

    m, l, p = ce_stats(h2, table, local_t)
    gm = jax.lax.pmax(m, axis_name)
    gl = jax.lax.psum(l * jnp.exp(m - gm), axis_name)
    lse = gm + jnp.log(gl)
    picked = jax.lax.psum(p, axis_name)  # owner shard contributes; rest 0
    return lse - picked, (h2, table, local_t, lse)


def _fused_vp_nll_bwd(axis_name, explicit_psum, res, dnll):
    from ..ops.fused_ce import ce_grads

    h2, table, local_t, lse = res
    dh, dtable = ce_grads(h2, table, local_t, lse, dnll)
    if explicit_psum:
        dh = jax.lax.psum(dh.astype(jnp.float32), axis_name).astype(h2.dtype)
    return dh, dtable, None


_fused_vp_nll.defvjp(_fused_vp_nll_fwd, _fused_vp_nll_bwd)

# Auto threshold: switch to the fused kernels when the materialized local
# logits would exceed this many bytes.  Deliberately conservative vs the
# measured standalone crossover (on v5e the XLA path still ran, ~40%
# faster, at 8.6 GB of logits and failed at 34 GB — docs/PERF.md): a
# FULL train step also holds params/activations/optimizer state, so
# 'auto' must flip while the logits still leave that headroom; prefer a
# few ms of CE time over an OOM at compile.  Force ce_impl='xla' to keep
# the materializing path near the boundary.
_FUSED_CE_AUTO_BYTES = 8 << 30


def vocab_parallel_logits_loss(h, table, targets, *, axis_name: str,
                               ce_impl: str = "auto"):
    """Cross-entropy from VOCAB-SHARDED logits — ``(B, S, V)`` never
    materializes unsharded.

    ``h (B, S, D)`` replicated-local; ``table (V/P, D)`` the local vocab
    shard of the (tied) embedding; ``targets (B, S)`` global token ids.
    Three cheap collectives: pmax (stable shift), psum of the local
    exp-sum, psum of the target-logit one-hot pick.

    ``ce_impl``: ``'xla'`` materializes the local ``(B, S, V/P)`` fp32
    logits (fastest when they fit — XLA runs this chain at ~0.8 MFU);
    ``'fused'`` runs the Pallas online-softmax kernels
    (``ops.fused_ce``) — logits tiles never leave VMEM, O(B·S) memory,
    the only path that COMPILES at huge ``T×V`` (docs/PERF.md records
    the 34 GB-logits case); ``'auto'`` picks fused on TPU once the local
    logits buffer would cross ~8 GB (below that XLA is measurably
    faster), xla otherwise.
    """
    vocab_per = table.shape[0]
    start = jax.lax.axis_index(axis_name) * vocab_per
    b, s, d = h.shape
    if ce_impl == "auto":
        big = b * s * vocab_per * 4 > _FUSED_CE_AUTO_BYTES
        on_tpu = jax.default_backend() == "tpu"
        aligned = (b * s) % 8 == 0 and vocab_per % 8 == 0
        ce_impl = "fused" if (big and on_tpu and aligned) else "xla"
    if ce_impl == "fused":
        h2 = h.reshape(b * s, d)
        # The custom_vjp replaces AD's transpose, so every cross-shard
        # gradient reduction must come from varying-axis promotions
        # OUTSIDE it: promote BOTH operands to the union of their varying
        # axes (h gains the model axis, table gains the data axis under
        # DP×TP) — each promotion's transpose then psums the matching
        # cotangent (dh over model, dtable over data) exactly where the
        # bypassed machinery would have.  When vma tracking is off
        # (check_vma=False contexts) there is nothing to promote; the
        # backward hand-psums dh over the model axis instead.
        hv = set(getattr(_typeof(h2), "vma", frozenset()))
        tv = set(getattr(_typeof(table), "vma", frozenset()))
        vma_active = bool(hv or tv)
        if vma_active:
            union = hv | tv | {axis_name}
            for ax in sorted(union - hv):
                h2 = pcast_varying(h2, ax)
            for ax in sorted(union - tv):
                table = pcast_varying(table, ax)
        local_t = (targets - start).reshape(-1)
        nll = _fused_vp_nll(h2, table, local_t, axis_name, not vma_active)
        return jnp.mean(nll)
    if ce_impl != "xla":
        raise ValueError(
            f"ce_impl must be 'auto', 'xla' or 'fused', got {ce_impl!r}")
    logits = jnp.einsum("bsd,vd->bsv", h, table,
                        preferred_element_type=jnp.float32)  # (B, S, V/P)

    # The max shift is numerics-only: its gradient contribution cancels
    # analytically (d/dx of m + log Σ exp(x−m) ignores m), and pmax has no
    # differentiation rule — so cut it out of the tangent graph entirely.
    m = jax.lax.pmax(jax.lax.stop_gradient(logits).max(-1), axis_name)  # (B, S)
    sumexp = jax.lax.psum(
        jnp.exp(logits - m[..., None]).sum(-1), axis_name)   # (B, S)
    local_t = targets - start
    in_range = (local_t >= 0) & (local_t < vocab_per)
    picked = jnp.take_along_axis(
        logits, jnp.clip(local_t, 0, vocab_per - 1)[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(in_range, picked, 0.0), axis_name)
    return jnp.mean(m + jnp.log(sumexp) - target_logit)


def tp_transformer_lm_loss(params, batch, *, head_dim: int, axis_name: str,
                           causal: bool = True, attn_impl: str = "auto",
                           ce_impl: str = "auto"):
    """Per-token mean NLL of a decoder-only LM over the LOCAL batch shard.

    ``batch``: ``(tokens (B, S+1) int32,)`` — inputs are ``[:, :-1]``,
    targets ``[:, 1:]``.  Feed to ``make_hybrid_shard_map_step`` for DP×TP
    (``functools.partial`` the static args first).  ``ce_impl`` selects
    the loss path (see :func:`vocab_parallel_logits_loss`).
    """
    tokens = batch[0]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    from .tensor_parallel import vocab_parallel_embedding

    x = vocab_parallel_embedding(inputs, params["embed"], axis_name=axis_name)
    x = x * (params["embed"].shape[1] ** 0.5)
    positions = None
    if "pos_embed" in params:
        x = x + params["pos_embed"][: x.shape[1]][None]
    else:  # RoPE model (init with pos_impl='rope'): rotate inside attention
        positions = jnp.arange(x.shape[1])
    for blk in params["blocks"]:
        x = tp_block(x, blk, head_dim=head_dim, axis_name=axis_name,
                     causal=causal, attn_impl=attn_impl, positions=positions)
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    return vocab_parallel_logits_loss(x, params["embed"], targets,
                                      axis_name=axis_name, ce_impl=ce_impl)


def sp_block(x, params, *, head_dim: int, axis_name: str, causal: bool = True,
             attn_impl: str = "auto", sp_impl: str = "ring", positions=None):
    """Transformer block with the SEQUENCE sharded over ``axis_name``.

    The long-context configuration (first-class per the rebuild brief;
    absent from the 2017 reference — SURVEY.md §5): ``x`` is the local
    sequence shard ``(B, S/P, D)`` with params REPLICATED; attention runs
    over ``sp_impl`` — ``'ring'`` (ppermute K/V rotation, O(S/P) keys per
    chip, any head count) or ``'ulysses'`` (two all-to-alls swapping the
    sharded axis to heads; needs ``n_heads % P == 0``).  Everything else
    (LN, MLP) is embarrassingly parallel over sequence positions.  Uses the
    same (unsharded) block-param layout as :func:`init_tp_transformer_lm` —
    the head-major wqkv makes the local reshape identical to
    :func:`tp_attention`'s.
    """
    from .ring_attention import ring_attention
    from .ulysses import ulysses_attention

    b, s_local, d = x.shape
    n_heads = d // head_dim
    a = params["attn"]
    h = _layer_norm(x, params["ln1_scale"], params["ln1_bias"])
    # Params are replicated here, so the shared projection yields FULL
    # heads (GQA: fewer KV heads ride the ring / all-to-all).
    q, k, v = _project_qkv(h, a, head_dim, axis_name)
    if positions is not None:
        # RoPE with GLOBAL positions: each shard rotates by its own offsets
        # before K/V ride the ring, so relative phases stay exact.
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
    if sp_impl == "ring":
        ctx = ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                             attn_impl=attn_impl)
    elif sp_impl == "ulysses":
        ctx = ulysses_attention(q, k, v, axis_name=axis_name, causal=causal,
                                attn_impl=attn_impl)
    else:
        raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {sp_impl!r}")
    ctx = ctx.reshape(b, s_local, d)
    attn_out = jnp.matmul(ctx, a["wo"],
                          preferred_element_type=jnp.float32).astype(x.dtype)
    x = x + attn_out + a["bo"]
    h = _layer_norm(x, params["ln2_scale"], params["ln2_bias"])
    mlp = params["mlp"]
    y = jax.nn.gelu(jnp.matmul(h, mlp["wi"],
                    preferred_element_type=jnp.float32).astype(x.dtype)
                    + mlp["bi"])
    y = jnp.matmul(y, mlp["wo"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    return x + y + mlp["bo"]


def sp_transformer_lm_loss(params, batch, *, head_dim: int, axis_name: str,
                           causal: bool = True, attn_impl: str = "auto",
                           sp_impl: str = "ring"):
    """Per-token mean NLL with the SEQUENCE sharded over ``axis_name``.

    ``batch``: ``(inputs (B, S/P), targets (B, S/P))`` — the caller shards
    a ``(B, S)`` token array over its sequence axis (``P(None, axis)``) and
    shifts globally BEFORE sharding, so each chip's targets line up with
    its inputs.  Params replicated; the ring carries the only cross-chip
    traffic.  Gradient sync composes exactly like data parallelism: pmean
    the loss over the axis and let autodiff insert the cotangent psum.
    """
    inputs, targets = batch
    my = jax.lax.axis_index(axis_name)
    s_local = inputs.shape[1]
    s_global = jax.lax.axis_size(axis_name) * s_local
    pos = my * s_local + jnp.arange(s_local)
    x = jnp.take(params["embed"], inputs, axis=0)
    x = x * (params["embed"].shape[1] ** 0.5)
    positions = None
    if "pos_embed" in params:
        max_len = params["pos_embed"].shape[0]
        if s_global > max_len:
            # jnp.take would silently CLAMP out-of-range positions to the
            # last pos_embed row — degenerate positional info, no error.
            raise ValueError(
                f"global sequence {s_global} exceeds pos_embed max_len "
                f"{max_len}; re-init the model with max_len >= {s_global}")
        x = x + jnp.take(params["pos_embed"], pos, axis=0)[None]
    else:  # RoPE: no length cap, rotation happens inside attention
        positions = pos
    for blk in params["blocks"]:
        x = sp_block(x, blk, head_dim=head_dim, axis_name=axis_name,
                     causal=causal, attn_impl=attn_impl, sp_impl=sp_impl,
                     positions=positions)
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"],
                        preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# ---- init + specs (GLOBAL params; shard with transformer_lm_specs) ----

def init_tp_transformer_lm(rng, vocab: int, d_model: int, n_heads: int,
                           n_layers: int, d_hidden: Optional[int] = None,
                           max_len: int = 512, dtype=jnp.float32,
                           n_kv_heads: Optional[int] = None,
                           pos_impl: str = "learned") -> Dict[str, Any]:
    """GLOBAL (unsharded) parameter pytree for the TP transformer LM.

    ``n_kv_heads`` (GQA/MQA): when set below ``n_heads``, attention carries
    separate ``wq`` and fused ``wkv`` projections (both head-major) instead
    of the fused ``wqkv``; the KV cache and projection shrink by
    ``n_heads / n_kv_heads``.  Under TP, ``n_kv_heads`` must stay divisible
    by the model-axis size.

    ``pos_impl``: ``'learned'`` (absolute ``pos_embed`` table, capped at
    ``max_len``) or ``'rope'`` (rotary, :func:`apply_rope` — no table, no
    length cap; the loss builders detect the absent ``pos_embed`` key).
    """
    if pos_impl not in ("learned", "rope"):
        raise ValueError(f"pos_impl must be 'learned' or 'rope', got {pos_impl!r}")
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by n_heads {n_heads}")
    if n_kv_heads is not None and n_heads % n_kv_heads:
        raise ValueError(
            f"n_heads {n_heads} not a multiple of n_kv_heads {n_kv_heads}")
    gqa = n_kv_heads is not None and n_kv_heads != n_heads
    d_hidden = d_hidden or 4 * d_model
    head_dim = d_model // n_heads
    keys = jax.random.split(rng, 2 + 4 * n_layers)
    scale = lambda fan_in: (2.0 / fan_in) ** 0.5

    def dense(key, n_in, n_out):
        return (jax.random.normal(key, (n_in, n_out)) * scale(n_in)).astype(dtype)

    blocks = []
    for i in range(n_layers):
        k1, k2, k3, k4 = keys[2 + 4 * i: 6 + 4 * i]
        if gqa:
            kq, kk, kv_ = jax.random.split(k1, 3)
            d_kv = n_kv_heads * head_dim
            # kv-head-major: columns are [head0: k|v, head1: k|v, …] so a
            # contiguous column shard over the model axis is whole KV heads.
            wk = dense(kk, d_model, d_kv).reshape(d_model, n_kv_heads, head_dim)
            wv = dense(kv_, d_model, d_kv).reshape(d_model, n_kv_heads, head_dim)
            attn = {
                "wq": dense(kq, d_model, d_model),
                "bq": jnp.zeros((d_model,), dtype),
                "wkv": jnp.stack([wk, wv], axis=2).reshape(d_model, 2 * d_kv),
                "bkv": jnp.zeros((2 * d_kv,), dtype),
                "wo": dense(k2, d_model, d_model),
                "bo": jnp.zeros((d_model,), dtype),
            }
        else:
            # Head-major qkv layout: columns are [head0: q|k|v, head1:
            # q|k|v, …] so a contiguous column shard is whole heads.
            wq, wk, wv = (dense(kk, d_model, d_model).reshape(
                d_model, n_heads, head_dim) for kk in jax.random.split(k1, 3))
            attn = {
                "wqkv": jnp.stack([wq, wk, wv], axis=2).reshape(
                    d_model, 3 * d_model),
                "bqkv": jnp.zeros((3 * d_model,), dtype),
                "wo": dense(k2, d_model, d_model),
                "bo": jnp.zeros((d_model,), dtype),
            }
        blocks.append({
            "ln1_scale": jnp.ones((d_model,), dtype),
            "ln1_bias": jnp.zeros((d_model,), dtype),
            "ln2_scale": jnp.ones((d_model,), dtype),
            "ln2_bias": jnp.zeros((d_model,), dtype),
            "attn": attn,
            "mlp": {
                "wi": dense(k3, d_model, d_hidden),
                "bi": jnp.zeros((d_hidden,), dtype),
                "wo": dense(k4, d_hidden, d_model),
                "bo": jnp.zeros((d_model,), dtype),
            },
        })
    out = {
        "embed": (jax.random.normal(keys[0], (vocab, d_model))
                  * scale(d_model)).astype(dtype),
        "blocks": blocks,
        "lnf_scale": jnp.ones((d_model,), dtype),
        "lnf_bias": jnp.zeros((d_model,), dtype),
    }
    if pos_impl == "learned":
        out["pos_embed"] = (jax.random.normal(keys[1], (max_len, d_model))
                            * 0.02).astype(dtype)
    return out


def transformer_lm_specs(params, axis_name: str = "model"):
    """PartitionSpecs matching :func:`init_tp_transformer_lm`'s pytree.

    QKV / MLP-in are column-sharded, attention-out / MLP-out row-sharded,
    the tied embedding vocab-sharded, norms/positions replicated.  ``wqkv``
    column-sharding is head-granular automatically because heads are the
    fastest-varying dim of its 3·D output.
    """
    ax = axis_name

    def block_specs(blk):
        if "wq" in blk["attn"]:  # GQA: separate q / fused kv projections
            attn = {"wq": P(None, ax), "bq": P(ax),
                    "wkv": P(None, ax), "bkv": P(ax),
                    "wo": P(ax, None), "bo": P()}
        else:
            attn = {"wqkv": P(None, ax), "bqkv": P(ax),
                    "wo": P(ax, None), "bo": P()}
        return {
            "ln1_scale": P(), "ln1_bias": P(),
            "ln2_scale": P(), "ln2_bias": P(),
            "attn": attn,
            "mlp": {"wi": P(None, ax), "bi": P(ax),
                    "wo": P(ax, None), "bo": P()},
        }

    out = {
        "embed": P(ax, None),
        "blocks": [block_specs(b) for b in params["blocks"]],
        "lnf_scale": P(),
        "lnf_bias": P(),
    }
    if "pos_embed" in params:
        out["pos_embed"] = P()
    return out
