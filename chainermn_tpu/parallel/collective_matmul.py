"""Collective matmul: overlap TP collectives with the matmuls they feed.

Beyond-reference perf primitives (the reference's only overlap was the
double-buffered gradient allreduce): the scaling-book / Wang-et-al.
"collective einsum" decompositions, built from ``ppermute`` + per-chunk
matmuls so XLA:TPU can run each hop's ICI transfer concurrently with the
current chunk's MXU work instead of serializing
``all_gather → matmul`` / ``matmul → reduce_scatter``:

* :func:`all_gather_matmul` — ``all_gather(x) @ w`` for row-sharded ``x``:
  the ring rotates activation chunks; every step matmuls the chunk in hand
  while the next one is in flight.  This is the Megatron-SP forward of a
  column-parallel layer (sequence-sharded activations entering a
  TP-sharded weight).
* :func:`matmul_reduce_scatter` — ``reduce_scatter(x @ w)`` for
  contraction-sharded ``x``/``w``: partial outputs are produced chunk by
  chunk and folded into an accumulator that rides the ring; each step's
  hop overlaps the next chunk's matmul.  The Megatron-SP backward-symmetric
  projection of a row-parallel layer.

Both are plain compositions of differentiable jax ops (no custom_vjp):
autodiff of the unrolled ring yields the transposed ring automatically, and
the unrolled Python loop (P is static) leaves XLA free to software-pipeline
the hops.  Numerically each equals its unfused two-op form up to the usual
reassociation tolerance; tests pin both forward and gradients against the
unfused oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _shift(x, axis_name: str, offset: int = 1):
    size = jax.lax.axis_size(axis_name)
    perm = [(i, (i + offset) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name, perm=perm)


def all_gather_matmul(x_local, w_local, *, axis_name: str):
    """``all_gather(x, axis) @ w`` with ring/compute overlap.

    Call INSIDE ``shard_map``.  ``x_local (S_loc, D)``: this rank's rows of
    a leading-dim-sharded activation; ``w_local (D, F_loc)``: any weight
    resident on this rank (typically the column-parallel shard).  Returns
    ``(P*S_loc, F_loc)`` — the full gathered rows times the local weight,
    bitwise-independent of P only up to matmul reassociation.
    """
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x_local @ w_local
    idx = jax.lax.axis_index(axis_name)
    s_loc = x_local.shape[0]
    out = jnp.zeros((p, s_loc, w_local.shape[1]),
                    jnp.promote_types(x_local.dtype, w_local.dtype))
    chunk = x_local
    for k in range(p):
        if k + 1 < p:
            # Launch the hop FIRST: the transfer of the next chunk has no
            # dependence on this step's matmul, so XLA may overlap them.
            nxt = _shift(chunk, axis_name)
        # The chunk in hand originated at rank (idx - k): deposit its rows
        # at that global position.
        row = jnp.mod(idx - k, p)
        out = jax.lax.dynamic_update_index_in_dim(
            out, (chunk @ w_local).astype(out.dtype), row, axis=0)
        if k + 1 < p:
            chunk = nxt
    return out.reshape(p * s_loc, w_local.shape[1])


def matmul_reduce_scatter(x_local, w_local, *, axis_name: str):
    """``reduce_scatter(x @ w, axis)`` with ring/compute overlap.

    Call INSIDE ``shard_map``.  ``x_local (S, D_loc)`` and ``w_local
    (D_loc, F)`` hold this rank's shard of the CONTRACTION dimension; the
    full product would need a psum.  Instead the output rows are reduced
    chunkwise around the ring: returns ``(S/P, F)`` — this rank's rows of
    the summed product (``jax.lax.psum_scatter`` semantics, tiled).
    """
    p = jax.lax.axis_size(axis_name)
    if p == 1:
        return x_local @ w_local
    idx = jax.lax.axis_index(axis_name)
    s = x_local.shape[0]
    if s % p:
        raise ValueError(f"leading dim {s} not divisible by axis size {p}")
    s_loc = s // p
    out_dtype = jnp.promote_types(x_local.dtype, w_local.dtype)
    # Accumulate in at least fp32 (bf16 inputs must not sum in bf16), but
    # never BELOW the promoted input precision (f64 stays f64).
    acc_dtype = jnp.promote_types(jnp.float32, out_dtype)
    acc = jnp.zeros((s_loc, w_local.shape[1]), acc_dtype)
    for k in range(p):
        if k > 0:
            # The accumulator for chunk j travels j+1 → j+2 → … → j; each
            # hop is independent of the chunk matmul that follows it.
            acc = _shift(acc, axis_name)
        j = jnp.mod(idx - 1 - k, p)
        rows = jax.lax.dynamic_slice_in_dim(x_local, j * s_loc, s_loc, axis=0)
        acc = acc + (rows @ w_local).astype(acc_dtype)
    return acc.astype(out_dtype)


def make_all_gather_matmul(mesh: Optional[Mesh] = None,
                           axis_name: Optional[str] = None):
    """Eager/jit face: ``fn(x, w) -> y`` over globals; ``x`` row-sharded,
    ``w`` column-sharded, ``y`` column-sharded (rows full)."""
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    return make_global_apply(
        partial(all_gather_matmul, axis_name=ax),
        mesh, (P(ax), P(None, ax)), P(None, ax))


def make_matmul_reduce_scatter(mesh: Optional[Mesh] = None,
                               axis_name: Optional[str] = None):
    """Eager/jit face: ``fn(x, w) -> y`` over globals; ``x`` sharded on its
    second (contraction) dim, ``w`` on its first, ``y`` row-sharded."""
    from ._factory import make_global_apply, resolve_mesh_axis

    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    return make_global_apply(
        partial(matmul_reduce_scatter, axis_name=ax),
        mesh, (P(None, ax), P(ax)), P(ax))
