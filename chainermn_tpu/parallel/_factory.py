"""Shared eager/jit factory for sequence-parallel attention kernels."""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def make_sp_attention(kernel: Callable, mesh: Optional[Mesh],
                      axis_name: Optional[str], causal: bool):
    """Wrap an inside-shard_map attention kernel ``kernel(q, k, v,
    axis_name=..., causal=...)`` into ``fn(q, k, v)`` over GLOBAL
    ``(B, S, H, D)`` arrays sequence-sharded over the mesh axis; compiles
    once per shape."""
    from ..topology import DEFAULT_AXIS_NAME, make_mesh

    if mesh is None:
        mesh = make_mesh(axis_name=axis_name or DEFAULT_AXIS_NAME)
    ax = axis_name or mesh.axis_names[0]
    spec = P(None, ax)  # shard the sequence axis

    fn = shard_map(
        partial(kernel, axis_name=ax, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    jitted = jax.jit(fn)
    sharding = NamedSharding(mesh, spec)

    def apply(q, k, v):
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return jitted(q, k, v)

    return apply
