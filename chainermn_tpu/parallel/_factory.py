"""Shared eager/jit factory plumbing for the parallel-strategy modules.

Every strategy here exposes two faces (SURVEY.md §7): an inside-shard_map
kernel and an eager/jit wrapper over GLOBAL arrays.  The wrapper recipe is
always the same — resolve mesh/axis, ``shard_map`` + ``jit`` once, shard the
global args on the way in — so it lives here once.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import shard_map

NEG_INF = -1e30


def resolve_mesh_axis(mesh: Optional[Mesh], axis_name: Optional[str]):
    """Default mesh = all devices, 1-D; axis = first mesh axis."""
    from ..topology import DEFAULT_AXIS_NAME, make_mesh

    if mesh is None:
        mesh = make_mesh(axis_name=axis_name or DEFAULT_AXIS_NAME)
    return mesh, axis_name or mesh.axis_names[0]


def make_global_apply(kernel: Callable, mesh: Mesh, in_specs, out_specs,
                      check_vma: bool = True):
    """``apply(*args)`` over global arrays: device_put each arg per its
    in_spec (pytree-prefix shardings allowed), run the jitted shard_map'd
    kernel; compiles once per shape."""
    jitted = jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma))
    shardings = [
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), spec,
                               is_leaf=lambda s: isinstance(s, P))
        for spec in in_specs
    ]

    def apply(*args):
        if len(args) != len(shardings):
            raise TypeError(f"expected {len(shardings)} args, got {len(args)}")
        return jitted(*jax.device_put(list(args), shardings))

    return apply


def make_sp_attention(kernel: Callable, mesh: Optional[Mesh],
                      axis_name: Optional[str], causal: bool,
                      check_vma: bool = True):
    """Wrap an inside-shard_map attention kernel ``kernel(q, k, v,
    axis_name=..., causal=...)`` into ``fn(q, k, v)`` over GLOBAL
    ``(B, S, H, D)`` arrays sequence-sharded over the mesh axis."""
    mesh, ax = resolve_mesh_axis(mesh, axis_name)
    spec = P(None, ax)  # shard the sequence axis
    return make_global_apply(
        partial(kernel, axis_name=ax, causal=causal),
        mesh, (spec, spec, spec), spec, check_vma=check_vma)
