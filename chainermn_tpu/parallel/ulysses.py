"""Ulysses-style sequence parallelism: head↔sequence all-to-all.

Technique: DeepSpeed-Ulysses (Jacobs et al.) — to attend over a sequence
sharded across ``P`` devices, swap the sharded axis from *sequence* to
*heads* with one all-to-all, run ordinary full-sequence attention on the
local ``H/P`` heads, and swap back.  Two XLA ``all_to_all`` collectives
total, both riding ICI; between them the attention is completely local, so
any attention kernel (including a Pallas flash kernel) drops in unchanged.

Reference relationship: the reference shipped the raw differentiable
``alltoall`` (``functions/collective_communication.py`` [uv], SURVEY.md
§2.8 "EP substrate") but no sequence parallelism on top; this module is
that missing layer, built on the same primitive's XLA form.

Constraint: ``heads % axis_size == 0`` (head-granular sharding) — the same
constraint Ulysses itself has.  For head counts below the mesh size use
ring attention instead (``ring_attention.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ._factory import NEG_INF as _NEG_INF, make_sp_attention


def _full_attention(q, k, v, causal: bool):
    """Plain softmax attention; (B, S, h, D) layout.  Scores and the PV
    product accumulate in fp32 (``preferred_element_type``) while the
    matmul operands keep their input dtype — bf16 MXU rate, fp32 sums —
    matching ring_attention's numerics.  GQA inputs (fewer KV heads) are
    expanded here; the flash path shares them without expansion."""
    if k.shape[2] != q.shape[2]:
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / (d ** 0.5)
    if causal:
        s_q, s_k = s.shape[-2:]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = False,
                      attn_impl: str = "auto") -> jnp.ndarray:
    """Exact attention over a sequence-sharded axis via two all-to-alls.

    Call INSIDE ``shard_map``: ``q,k,v`` local shards ``(B, S_local, H, D)``
    with ``H`` divisible by the axis size; returns the local output shard.
    ``attn_impl``: ``'xla'`` (plain softmax attention), ``'flash'`` (the
    Pallas kernel from ``ops.flash_attention`` — O(block) memory for the
    local full-sequence attention, the long-context configuration), or
    ``'auto'`` (flash on TPU at non-trivial GLOBAL sequence length — the
    post-all-to-all attention sees the full sequence).
    """
    from ..ops.flash_attention import resolve_attn_impl

    p_size = jax.lax.psum(1, axis_name)
    # post-all-to-all attention sees the GLOBAL sequence
    attn_impl = resolve_attn_impl(attn_impl, q.shape[1] * p_size)
    b, s_local, h, d = q.shape
    h_kv = k.shape[2]
    if h % p_size != 0:
        raise ValueError(
            f"Ulysses needs heads ({h}) divisible by axis size ({p_size}); "
            "use ring_attention for small head counts")
    if h % h_kv or h_kv % p_size:
        raise ValueError(
            f"GQA under Ulysses needs q heads ({h}) a multiple of kv heads "
            f"({h_kv}) and kv heads divisible by the axis size ({p_size}); "
            "use ring_attention otherwise")

    def seq_to_heads(x):
        # (B, S_local, H, D) → (B, S_global, H/P, D): hand each device the
        # full sequence of its H/P heads.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_impl == "flash":
        from ..ops.flash_attention import flash_attention
        out = flash_attention(qg, kg, vg, causal=causal)
    elif attn_impl == "xla":
        out = _full_attention(qg, kg, vg, causal)
    else:
        raise ValueError(
            f"attn_impl must be 'auto', 'xla' or 'flash', got {attn_impl!r}")
    return heads_to_seq(out)


def make_ulysses_attention(mesh: Optional[Mesh] = None,
                           axis_name: Optional[str] = None,
                           causal: bool = False, attn_impl: str = "auto"):
    """Eager/jit face over GLOBAL sequence-sharded arrays (see
    ``_factory.make_sp_attention``)."""
    # check_vma off only for INTERPRETED flash (CPU tests): pallas interpret
    # mode can't propagate varying-axes through its internal interpreter yet
    # (JAX limitation).  The compiled TPU path keeps the check.
    interpreted_flash = (attn_impl == "flash"
                         and jax.default_backend() != "tpu")
    return make_sp_attention(
        partial(ulysses_attention, attn_impl=attn_impl),
        mesh, axis_name, causal, check_vma=not interpreted_flash)
