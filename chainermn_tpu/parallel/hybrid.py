"""Hybrid data x model parallelism: one jitted step over an N-D mesh.

Reference parity: SURVEY.md §2.8 "Hybrid DP×MP" — the reference composed
2-D layouts by hand from ``CommunicatorBase.split(color, key)``
sub-communicators (``communicator_base.py :: split`` [uv]) and the
``examples/model_parallel`` graphs [uv]: a data-parallel allreduce among
same-position ranks x an activation pipeline among same-replica ranks.

TPU-native there are two faces, both over one :func:`topology.make_nd_mesh`
``('data', 'model')`` mesh:

* **pjit face** (:func:`make_hybrid_train_step`) — the idiomatic one.
  Params are placed with per-leaf ``NamedSharding`` (model-dim sharded,
  data-replicated; see :func:`shard_pytree`), the batch is sharded over
  ``'data'``, and the step is a *plain* ``jax.jit``: XLA's sharding
  propagation (GSPMD) inserts the TP psums/all-gathers AND the DP gradient
  reduce-scatter from the shardings alone — the scaling-book recipe ("pick
  a mesh, annotate shardings, let XLA insert collectives").
* **shard_map face** (:func:`make_hybrid_shard_map_step`) — the explicit
  one, for models written against ``parallel.tensor_parallel``'s per-rank
  layers: both axes are bound, TP layers psum over ``'model'`` themselves,
  and the loss is pmean'd over ``'data'`` so autodiff inserts the DP
  gradient reduction exactly like the 1-D :func:`train.make_train_step`.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import shard_map


def _data_axis(mesh: Mesh, axis_name: Optional[str]) -> str:
    """Resolve the DP axis: explicit name, or the mesh's sole axis.

    ``make_mesh()`` names its 1-D axis ``'mn'`` while the hybrid builders
    historically defaulted to ``'data'`` — resolving against the mesh kills
    that trap: a 1-D mesh needs no axis argument at all, an N-D mesh demands
    an explicit one.
    """
    if axis_name is not None:
        if axis_name not in mesh.axis_names:
            raise ValueError(
                f"axis {axis_name!r} not in mesh axes {mesh.axis_names}")
        return axis_name
    if len(mesh.axis_names) == 1:
        return mesh.axis_names[0]
    raise ValueError(
        f"mesh has axes {mesh.axis_names}; pass axis_name= explicitly")


def shard_pytree(tree, mesh: Mesh, specs):
    """Place ``tree`` on ``mesh`` with a matching pytree of PartitionSpecs.

    ``specs`` may be a single spec (applied to every leaf) or a pytree
    matching ``tree``'s structure.
    """
    if isinstance(specs, P):
        specs = jax.tree_util.tree_map(lambda _: specs, tree)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)


def make_hybrid_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    has_aux: bool = False,
    donate: bool = True,
):
    """Hybrid-parallel train step, pjit face.

    ``loss_fn(params, batch)`` is written over the GLOBAL logical batch
    (plain jnp ops; sprinkle ``jax.lax.with_sharding_constraint`` on
    activations to pin layouts).  Parallelism comes entirely from the
    shardings the caller placed on ``params`` (via :func:`shard_pytree`)
    and ``batch`` — XLA derives the TP collectives and the DP gradient
    reduction, so the same step runs 1-D DP, 1-D TP, or 2-D DP×TP
    depending only on how the arrays are laid out.

    ``opt_state`` should be created with ``jax.jit(optimizer.init)(params)``
    so its shardings are inferred to follow the params.
    """

    def step(params, opt_state, batch):
        def global_loss(p):
            out = loss_fn(p, batch)
            if has_aux:
                return out
            return out, None

        (loss, aux), grads = jax.value_and_grad(global_loss, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def state_specs_like(optimizer: optax.GradientTransformation, params,
                     param_specs):
    """PartitionSpecs for ``optimizer.init(params)``'s state pytree.

    Optax states nest sub-pytrees structurally identical to ``params``
    (momentum/trace, Adam's mu/nu); each such subtree inherits
    ``param_specs`` wholesale, every other leaf (step counts, scalars) is
    replicated.  This is what lets the shard_map face wrap arbitrary optax
    optimizers without per-optimizer spec plumbing.
    """
    state = jax.eval_shape(optimizer.init, params)
    pdef = jax.tree_util.tree_structure(params)

    def params_like(node):
        try:
            return jax.tree_util.tree_structure(node) == pdef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda sub: (param_specs if params_like(sub)
                     else jax.tree_util.tree_map(lambda _: P(), sub)),
        state, is_leaf=params_like)


def zero1_specs(params, mesh: Mesh, axis_name: Optional[str] = None):
    """ZeRO-1 PartitionSpecs: each param-shaped leaf sharded over
    ``axis_name`` on its first divisible dimension, scalars/indivisible
    leaves replicated.

    Beyond-reference (the reference replicated optimizer state on every
    rank): with ``P`` data-parallel chips, Adam's m/v live ``1/P`` per chip.

    .. note:: breaking default change (round 2): ``axis_name`` defaults to
       ``None`` — resolved to the mesh's only axis, raising on multi-axis
       meshes instead of silently assuming ``'data'``.  Callers on N-D
       meshes must name the axis explicitly.
    """
    axis_name = _data_axis(mesh, axis_name)
    n = mesh.shape[axis_name]

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        for d, s in enumerate(shape):
            if s % n == 0 and s >= n:
                return P(*([None] * d + [axis_name]))
        return P()

    return jax.tree_util.tree_map(spec_for, params)


def init_zero1_state(optimizer: optax.GradientTransformation, params,
                     mesh: Mesh, axis_name: Optional[str] = None):
    """Optimizer state laid out ZeRO-1: param-shaped subtrees sharded per
    :func:`zero1_specs`, everything else replicated."""
    pspecs = zero1_specs(params, mesh, axis_name)
    sspecs = state_specs_like(optimizer, params, pspecs)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), sspecs)
    return jax.jit(optimizer.init, out_shardings=shardings)(params)


def make_zero1_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: Optional[str] = None,
    has_aux: bool = False,
    donate: bool = True,
):
    """ZeRO-1 data-parallel train step (pjit face).

    The gradient all-reduce becomes a REDUCE-SCATTER (each chip receives
    only its ``1/P`` gradient shard), the optimizer update runs on sharded
    state (:func:`init_zero1_state`), and the parameter delta is
    all-gathered back to replicated — reduce_scatter + update/P + all_gather
    instead of all_reduce + P× redundant update, with optimizer memory cut
    by ``P``.  All three collectives are GSPMD-inserted from the sharding
    constraints; params stay replicated at the step boundary so everything
    else (checkpointing, eval, export) is unchanged.
    """
    def step(params, opt_state, batch):
        pspecs = zero1_specs(params, mesh, axis_name)

        def global_loss(p):
            out = loss_fn(p, batch)
            if has_aux:
                return out
            return out, None

        (loss, aux), grads = jax.value_and_grad(global_loss, has_aux=True)(params)
        # Shard the grads like the state: AD's cross-batch reduction + this
        # constraint lower to one reduce_scatter per leaf.
        grads = jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, s)),
            grads, pspecs)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        # All-gather the delta, keep params replicated at the boundary.
        updates = jax.tree_util.tree_map(
            lambda u: jax.lax.with_sharding_constraint(
                u, NamedSharding(mesh, P())),
            updates)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def init_fsdp_params(params, mesh: Mesh, axis_name: Optional[str] = None):
    """Place ``params`` FSDP-style: each leaf sharded over ``axis_name`` on
    its first divisible dimension (:func:`zero1_specs` layout), so parameter
    memory per chip is ``1/P`` of the model.  Returns the sharded pytree."""
    return shard_pytree(params, mesh, zero1_specs(params, mesh, axis_name))


def init_fsdp_state(optimizer: optax.GradientTransformation, params,
                    mesh: Mesh, axis_name: Optional[str] = None):
    """Optimizer state matching :func:`init_fsdp_params`'s layout: the
    param-shaped subtrees (momentum, Adam m/v) shard exactly like the
    params, scalars replicated."""
    return init_zero1_state(optimizer, params, mesh, axis_name)


def make_fsdp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: Optional[str] = None,
    has_aux: bool = False,
    donate: bool = True,
):
    """FSDP / ZeRO-3 data-parallel train step (pjit face).

    Beyond-reference (SURVEY.md §2.8 lists only replicated-parameter DP):
    parameters, gradients AND optimizer state all live sharded ``1/P`` per
    chip over ``axis_name`` (:func:`zero1_specs` layout) — the full ZeRO-3
    memory split, the TPU-idiomatic way:

    * forward/backward: ``loss_fn`` is written over global logical arrays;
      GSPMD sees sharded params meeting a ``'data'``-sharded batch and
      inserts the per-use **all-gather** of each weight (and, in the
      backward, the matching **reduce-scatter** of its gradient) — the
      hand-written bucketing/prefetch machinery of GPU FSDP is the
      compiler's job here.
    * the gradient constraint to the param layout makes the cross-replica
      reduction a reduce-scatter (never a full all-reduce), and the update
      runs on ``1/P`` of the state per chip.
    * params stay sharded at the step boundary — peak HBM is
      O(model/P + largest gathered layer), which is what lets a model
      ``P×`` bigger than one chip train at all.

    Wrap big ``loss_fn`` blocks in ``jax.checkpoint`` with a
    ``save_only_these_names``/dots policy to avoid re-gathering weights in
    the backward if XLA's rematerialisation choices need steering.
    """
    def step(params, opt_state, batch):
        pspecs = zero1_specs(params, mesh, axis_name)

        def global_loss(p):
            out = loss_fn(p, batch)
            if has_aux:
                return out
            return out, None

        (loss, aux), grads = jax.value_and_grad(global_loss, has_aux=True)(params)
        # Reduce-scatter: grads land in the same 1/P layout as the state.
        grads = jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, s)),
            grads, pspecs)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Keep params sharded at the boundary (the ZeRO-3 point — contrast
        # make_zero1_train_step, which all-gathers them back to replicated).
        params = jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, s)),
            params, pspecs)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_hybrid_shard_map_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params,
    param_specs,
    data_axis: str = "data",
    batch_spec: Optional[P] = None,
    has_aux: bool = False,
    donate: bool = True,
):
    """Hybrid-parallel train step, explicit shard_map face.

    ``loss_fn(params, local_batch)`` runs with BOTH mesh axes bound: TP
    layers (``parallel.tensor_parallel``) psum over the model axis
    themselves; this builder pmeans the loss over ``data_axis`` so autodiff
    inserts the cross-replica gradient reduction (and ONLY that — params
    varying over the model axis get no spurious model-axis psum).

    ``params``/``param_specs``: the TP layout (e.g. ``wi`` sharded on its
    output dim over ``'model'``); used to derive optimizer-state specs via
    :func:`state_specs_like`.  ``batch_spec`` defaults to sharding the
    leading axis over ``data_axis``.
    """
    if batch_spec is None:
        batch_spec = P(data_axis)
    st_specs = state_specs_like(optimizer, params, param_specs)

    def spmd(params, opt_state, batch):
        def global_loss(p):
            out = loss_fn(p, batch)
            if has_aux:
                local, aux = out
            else:
                local, aux = out, None
            return jax.lax.pmean(local, data_axis), aux

        (loss, aux), grads = jax.value_and_grad(global_loss, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, jax.lax.pmean(aux, data_axis)
        return params, opt_state, loss

    out_specs = ((param_specs, st_specs, P(), P()) if has_aux
                 else (param_specs, st_specs, P()))
    smapped = shard_map(
        spmd, mesh=mesh,
        in_specs=(param_specs, st_specs, batch_spec),
        out_specs=out_specs,
    )
    return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())
