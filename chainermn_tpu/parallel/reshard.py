"""Portable array redistribution: ``reshard(tree, src_spec, dst_spec)``.

The elastic/disaggregation primitive ROADMAP items 4 and 5 both need —
"Memory-efficient array redistribution through portable collective
communication" (arxiv 2112.01075, PAPERS.md) distilled to the 1-D mesh
this repo's data/TP axes use: a redistribution between two partition
specs lowers to the MINIMAL collective for the (src, dst) pair instead
of the naive all_gather-then-slice (which moves P× the necessary bytes
and materializes the full array on every rank):

    ==================  =====================  =======================
    src → dst           collective             per-rank wire bytes
    ==================  =====================  =======================
    R → R               (none)                 0
    R → S(a)            local slice            0
    S(a) → S(a)         (none)                 0
    S(a) → R            all_gather             block × (P-1)
    S(a) → S(b), a≠b    all_to_all             block × (P-1)/P
    ==================  =====================  =======================

where ``R`` is replicated, ``S(a)`` is sharded along logical axis ``a``
across the mesh axis, and "block" is the per-rank shard.  Every wire leg
routes through the ACCOUNTED collective face (``ops.collective``), so
the PR 1 comm ledger books each call and the PR 6 shard-flow static
model reconciles the traced equations byte-exactly — the cost of a
reshard is never invisible (``reshard_cost`` is the same formula the
bench gate and the property tests read).

Two faces, one spec language:

* :func:`reshard` — the in-SPMD primitive: call inside ``shard_map``
  with the axis bound, on per-rank blocks.  :func:`make_reshard` wraps
  it into a jitted whole-array program (the train→serve weight-handoff
  / KV-slab-transfer building block).
* :func:`reshard_host` — the device-free twin for checkpoint shards:
  re-partitions a list of per-process host pytrees from one world
  size/layout to another (the elastic-restore path of
  ``extensions/checkpoint.py``; no jax required at call time).

Spec language (`ShardSpec`): ``None`` = replicated; an ``int`` = that
logical axis is evenly partitioned across the mesh axis.  A spec may be
a single value (applied to every leaf) or a pytree matching ``tree``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

ShardSpec = Union[None, int]

__all__ = [
    "ShardSpec", "reshard", "make_reshard", "reshard_host", "reshard_cost",
    "partition_spec_of", "validate_spec", "lower_schedule",
]


def validate_spec(spec: ShardSpec, ndim: Optional[int] = None,
                  what: str = "spec") -> ShardSpec:
    """Normalize/validate one leaf spec: None, or an in-range axis int."""
    if spec is None:
        return None
    if isinstance(spec, bool) or not isinstance(spec, int):
        raise TypeError(
            f"{what} must be None (replicated) or an int logical axis, "
            f"got {spec!r}")
    if ndim is not None and not -ndim <= spec < ndim:
        raise ValueError(
            f"{what}={spec} out of range for a rank-{ndim} array")
    if ndim is not None and spec < 0:
        spec += ndim
    return spec


def _spec_tree(tree, spec):
    """Broadcast a single spec over a pytree, or validate a spec pytree."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if spec is None or isinstance(spec, int):
        return [spec] * len(leaves), leaves, treedef
    spec_leaves = jax.tree_util.tree_leaves(
        spec, is_leaf=lambda x: x is None)
    if len(spec_leaves) != len(leaves):
        raise ValueError(
            f"spec pytree has {len(spec_leaves)} leaves but the array "
            f"tree has {len(leaves)}")
    return list(spec_leaves), leaves, treedef


def partition_spec_of(spec: ShardSpec, ndim: int, axis_name: str):
    """The ``jax.sharding.PartitionSpec`` a leaf spec denotes — the glue
    between this module's spec language and shard_map in/out specs."""
    from jax.sharding import PartitionSpec as P

    spec = validate_spec(spec, ndim)
    if spec is None:
        return P()
    return P(*([None] * spec + [axis_name]))


def _reshard_leaf(x, src: ShardSpec, dst: ShardSpec, axis_name: str):
    """One leaf's redistribution, on the per-rank block, inside SPMD."""
    import jax

    from ..ops import collective as _col

    ndim = x.ndim
    # src/dst describe the LOGICAL array; the block has the same rank.
    src = validate_spec(src, ndim, "src_spec")
    dst = validate_spec(dst, ndim, "dst_spec")
    if src == dst:
        return x
    p = _col.axis_size(axis_name)
    if src is None and dst is not None:
        # replicated → sharded: a local slice, zero wire bytes.  The
        # result must be typed VARYING over the axis (each rank holds a
        # different block) — axis_index makes that so.
        if x.shape[dst] % p:
            raise ValueError(
                f"cannot shard axis {dst} of shape {x.shape} across "
                f"{p} ranks: {x.shape[dst]} % {p} != 0")
        block = x.shape[dst] // p
        idx = _col.axis_index(axis_name)
        return jax.lax.dynamic_slice_in_dim(x, idx * block, block, axis=dst)
    if dst is None:
        # sharded → replicated: the textbook all_gather, tiled so the
        # blocks concatenate back along the source axis.
        return _col.all_gather(x, axis_name, axis=src, tiled=True)
    # sharded(a) → sharded(b): ONE all_to_all — each rank keeps 1/P of
    # its block and receives 1/P from every peer; (P-1)/P of the payload
    # crosses the wire, vs (P-1)× for gather-then-slice.
    if x.shape[dst] % p:
        raise ValueError(
            f"cannot reshard to axis {dst}: block shape {x.shape} has "
            f"{x.shape[dst]} % {p} != 0")
    return _col.all_to_all(x, axis_name, split_axis=dst, concat_axis=src,
                           tiled=True)


def reshard(tree, src_spec, dst_spec, axis_name: str = "mn"):
    """Redistribute ``tree`` from ``src_spec`` to ``dst_spec`` — call
    inside ``shard_map`` with ``axis_name`` bound; leaves are per-rank
    blocks.  Specs are single values or pytrees matching ``tree``."""
    import jax

    src_leaves, leaves, treedef = _spec_tree(tree, src_spec)
    dst_leaves, _, _ = _spec_tree(tree, dst_spec)
    out = [
        _reshard_leaf(x, s, d, axis_name)
        for x, s, d in zip(leaves, src_leaves, dst_leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def make_reshard(mesh, src_spec, dst_spec, axis_name: Optional[str] = None,
                 example=None) -> Callable:
    """Compile a whole-array redistribution program over ``mesh``.

    Returns ``fn(global_tree) -> global_tree`` where the input carries
    ``src_spec``'s sharding and the output ``dst_spec``'s — the callable
    form the KV-slab transfer and train→serve weight handoff use.  One
    compiled program per (shape, dtype, spec-pair); indices are static
    by construction, so repeated transfers hit the jit cache.

    ``example`` (optional pytree of shapes/arrays) pins the spec-pytree
    structure early with a clear error instead of at first call.
    """
    import jax

    from .._compat import shard_map

    ax = axis_name or mesh.axis_names[0]
    # one compiled program per (tree structure, leaf shapes/dtypes):
    # repeated transfers of same-shaped state reuse it (the jit objects
    # live here, not per call, so the cache actually holds)
    programs = {}

    def fn(tree):
        src_leaves, leaves, treedef = _spec_tree(tree, src_spec)
        dst_leaves, _, _ = _spec_tree(tree, dst_spec)
        key = (treedef,
               tuple((tuple(x.shape), str(getattr(x, "dtype", "?")))
                     for x in leaves))
        jitted = programs.get(key)
        if jitted is None:
            in_specs = jax.tree_util.tree_unflatten(
                treedef,
                [partition_spec_of(s, x.ndim, ax)
                 for s, x in zip(src_leaves, leaves)])
            out_specs = jax.tree_util.tree_unflatten(
                treedef,
                [partition_spec_of(d, x.ndim, ax)
                 for d, x in zip(dst_leaves, leaves)])

            def body(t):
                return reshard(t, src_spec, dst_spec, ax)

            jitted = jax.jit(shard_map(body, mesh=mesh,
                                       in_specs=(in_specs,),
                                       out_specs=out_specs))
            programs[key] = jitted
        return jitted(tree)

    fn.programs = programs  # the analysis/recompile probes read this
    if example is not None:
        _spec_tree(example, src_spec)
        _spec_tree(example, dst_spec)
    return fn


def reshard_cost(shape: Sequence[int], dtype, src: ShardSpec,
                 dst: ShardSpec, axis_size: int) -> dict:
    """Static prediction for one leaf's redistribution: which collective,
    its LEDGER payload bytes (``observability.comm.payload_info``'s
    convention — the per-rank input block of the call), and the physical
    ring wire bytes via ``ops.collective.collective_wire_cost``.  This is
    the number the comm ledger must book and the shard-flow model must
    derive — the property tests hold all three to each other."""
    import numpy as np

    from ..ops.collective import collective_wire_cost

    ndim = len(shape)
    src = validate_spec(src, ndim, "src")
    dst = validate_spec(dst, ndim, "dst")
    p = int(axis_size)
    item = np.dtype(dtype).itemsize
    total = int(np.prod(shape)) * item if shape else item
    block = total // p if p else total

    def out(primitive, ledger_bytes):
        wire = (collective_wire_cost(primitive, ledger_bytes, p)
                if primitive else {"wire_bytes": 0, "messages": 0})
        return {"primitive": primitive, "ledger_bytes": int(ledger_bytes),
                "wire_bytes": int(wire["wire_bytes"]),
                "messages": int(wire["messages"])}

    if src == dst or p <= 1:
        return out(None, 0)
    if src is None and dst is not None:
        return out(None, 0)          # local slice
    if dst is None:
        return out("all_gather", block)
    return out("all_to_all", block)


def reshard_tree_cost(tree, src_spec, dst_spec, axis_size: int) -> dict:
    """Sum of :func:`reshard_cost` over a pytree — the whole transfer's
    predicted ledger/wire bytes (bench's elastic section reads this)."""
    import jax

    src_leaves, leaves, _ = _spec_tree(tree, src_spec)
    dst_leaves, _, _ = _spec_tree(tree, dst_spec)
    total = {"ledger_bytes": 0, "wire_bytes": 0, "messages": 0,
             "per_primitive": {}}
    for x, s, d in zip(leaves, src_leaves, dst_leaves):
        c = reshard_cost(x.shape, x.dtype, s, d, axis_size)
        total["ledger_bytes"] += c["ledger_bytes"]
        total["wire_bytes"] += c["wire_bytes"]
        total["messages"] += c["messages"]
        if c["primitive"]:
            row = total["per_primitive"].setdefault(
                c["primitive"], {"ledger_bytes": 0, "calls": 0})
            row["ledger_bytes"] += c["ledger_bytes"]
            row["calls"] += 1
    return total


# ---------------------------------------------------------------------------
# host-side twin: checkpoint shard re-partitioning (numpy only, no devices)
# ---------------------------------------------------------------------------

def _split_even(n: int, parts: int, what: str) -> int:
    if parts < 1:
        raise ValueError(f"{what}: need at least 1 partition, got {parts}")
    if n % parts:
        raise ValueError(
            f"{what}: axis length {n} does not divide evenly into "
            f"{parts} partitions")
    return n // parts


def lower_schedule(shape, dtype, src_spec, dst_spec, src_world: int,
                   dst_world: int, kind: str = "auto", topology=None,
                   n_chunks: int = 2, depth: int = 2):
    """Lower one (src,dst) spec pair to a VERIFIED collective schedule
    (ISSUE 19 / ROADMAP item 3).

    ``kind`` names a generator (``single`` — the monolithic lowering
    :func:`reshard` performs today — ``chunked``, ``pipelined``,
    ``hierarchical``) or ``"auto"`` to pick the cheapest verified
    candidate under the r04 cost model.  Every returned schedule has
    passed the full :mod:`~chainermn_tpu.analysis.schedule_check`
    verifier (coverage vs the array_split statics, exhaustive BFS of
    the start/done machine, interpreter byte-exactness) — an
    unverifiable schedule raises instead of escaping.
    """
    from ..analysis.schedule_check import verified_schedule

    return verified_schedule(kind, shape, dtype, src_spec, dst_spec,
                             src_world, dst_world, topology,
                             n_chunks=n_chunks, depth=depth)


def _emit_schedule_exec(prof) -> None:
    """Fan one profiled execution's records out to the observability
    plane (ISSUE 20): every op becomes an HLC-stamped journal line
    (``kind="schedule_exec"``, fingerprint-keyed), a tracer complete
    event on the live trace, and a ``schedule_exec/*`` counter bump in
    the comm ledger; one flight-note summary rides the /statusz ring.
    """
    from ..observability import comm as _comm
    from ..observability import journal as _journal
    from ..observability import trace as _trace

    recs = prof.run_records()
    if not recs:
        return
    if _journal.enabled():
        for rec in recs:
            _journal.emit("schedule_exec",
                          **{k: v for k, v in rec.items()
                             if k != "schema"})
    _comm.record_schedule_exec(recs)
    if _trace.enabled():
        # the run just finished: back-date each op from "now" so the
        # lane lines up with the surrounding spans.
        base = _trace.now_us() - prof.wall_us()
        for rec in recs:
            _trace.complete_event(
                f"sched/{rec['op']}({rec['arg']})",
                int(base + rec["t_us"]), max(1, int(rec["wall_us"])),
                cat="schedule_exec", link=rec["link"],
                rank=rec["rank"], bytes=rec["bytes"],
                fingerprint=rec["fingerprint"])


def _scheduled_leaf(vals, src_axis: int, dst_spec, dst_count: int,
                    kind: str, topology):
    """Route one sharded leaf through a verified schedule's interpreter.

    Returns the per-destination blocks, or ``None`` when the leaf falls
    outside the schedule geometry (unequal source blocks, uneven
    destination split, mixed dtypes) — the caller then takes the direct
    concatenate/slice path, which is byte-identical by the verifier's
    own oracle.

    When the journal or tracer is live the execution runs under a
    :class:`~chainermn_tpu.analysis.schedule_check.ScheduleExecProfile`
    and every op lands in the observability plane (see
    :func:`_emit_schedule_exec`); with both off, not a single record is
    built — the PR 17 zero-overhead-off discipline.
    """
    import numpy as np

    from ..analysis.schedule import block_shape
    from ..analysis.schedule_check import run_schedule

    arrs = [np.asarray(v) for v in vals]
    first = arrs[0]
    if any(a.shape != first.shape or a.dtype != first.dtype
           for a in arrs[1:]):
        return None
    if not 0 <= src_axis < first.ndim:
        return None
    shape = list(first.shape)
    shape[src_axis] = shape[src_axis] * len(arrs)
    shape = tuple(shape)
    if isinstance(dst_spec, int):
        if not 0 <= dst_spec < first.ndim:
            return None
        if shape[dst_spec] % dst_count:
            return None                  # direct path raises the error
    sched = lower_schedule(shape, str(first.dtype), src_axis, dst_spec,
                           len(arrs), dst_count, kind=kind,
                           topology=topology)
    profiler = None
    from ..observability import journal as _journal
    from ..observability import trace as _trace
    if _journal.enabled() or _trace.enabled():
        from ..analysis.schedule_check import ScheduleExecProfile
        profiler = ScheduleExecProfile(sched)
    outs = run_schedule(sched, [np.ascontiguousarray(a).reshape(-1)
                                for a in arrs], profiler=profiler)
    if profiler is not None:
        _emit_schedule_exec(profiler)
    return [outs[r].reshape(block_shape(shape, dst_spec, r, dst_count))
            for r in range(dst_count)]


def reshard_host(shards: Sequence[Any], src_layout, dst_layout,
                 dst_count: int, *, schedule: Optional[str] = None,
                 topology=None) -> List[Any]:
    """Re-partition per-process host pytrees between world sizes.

    ``shards`` is the COMPLETE old-world list (one pytree per source
    process, rank order); ``src_layout``/``dst_layout`` follow the same
    spec language as :func:`reshard` (single spec or spec pytree), with
    one host-side addition: the string ``"per_rank"`` marks state that
    is rank-SPECIFIC rather than a partition of a logical array — new
    rank ``r`` inherits old rank ``r % len(shards)``'s value (iterator
    cursors and RNG must be re-derived by the caller; the multi-node
    iterator installs the master's broadcast state, which tolerates
    this).  Returns ``dst_count`` pytrees.

    Exactness contract: for replicated leaves the output is shard 0's
    value bit-for-bit on every destination; for sharded leaves the
    concatenation of destination blocks equals the concatenation of
    source blocks (numpy arrays throughout; nothing touches a device).

    ``schedule`` (ISSUE 19) routes sharded-source array leaves through
    a VERIFIED collective schedule instead of the direct
    concatenate/slice: ``"auto"`` picks the cheapest candidate under
    the r04 cost model, or name a generator (``"single"``,
    ``"chunked"``, ``"pipelined"``, ``"hierarchical"`` — the latter
    staging cross-slice bytes over a gateway when ``topology`` has a
    DCN tier).  Every schedule has passed
    :func:`~chainermn_tpu.analysis.schedule_check.verify_schedule`
    (coverage reconciled against the same split statics, exhaustive
    BFS of its start/done machine, interpreter byte-exactness), so the
    result is bit-identical to the direct path; leaves outside the
    schedule geometry (replicated/``per_rank`` sources, unequal blocks)
    keep the direct path.
    """
    import numpy as np

    if not shards:
        raise ValueError("reshard_host: empty shard list")
    if dst_count < 1:
        raise ValueError(f"reshard_host: dst_count must be >= 1, got "
                         f"{dst_count}")
    src_count = len(shards)

    import jax

    def norm(layout):
        if layout is None or isinstance(layout, (int, str)):
            leaves0, treedef = jax.tree_util.tree_flatten(shards[0])
            return [layout] * len(leaves0), treedef
        leaves = jax.tree_util.tree_leaves(
            layout, is_leaf=lambda x: x is None)
        _, treedef = jax.tree_util.tree_flatten(shards[0])
        if len(leaves) != treedef.num_leaves:
            raise ValueError(
                f"layout has {len(leaves)} leaves but state has "
                f"{treedef.num_leaves}")
        return list(leaves), treedef

    src_specs, treedef = norm(src_layout)
    dst_specs, _ = norm(dst_layout)
    shard_leaves = [jax.tree_util.tree_flatten(s)[0] for s in shards]
    for i, ls in enumerate(shard_leaves):
        if len(ls) != len(shard_leaves[0]):
            raise ValueError(
                f"shard {i} has {len(ls)} leaves, shard 0 has "
                f"{len(shard_leaves[0])} — shards disagree on structure")

    out_leaves: List[List[Any]] = [[] for _ in range(dst_count)]
    for li in range(len(shard_leaves[0])):
        src = src_specs[li]
        dst = dst_specs[li]
        vals = [shard_leaves[p][li] for p in range(src_count)]
        if src == "per_rank" or dst == "per_rank":
            if src != dst:
                raise ValueError(
                    "per_rank state cannot be resharded to/from an array "
                    f"partition (leaf {li}: src={src!r}, dst={dst!r})")
            for r in range(dst_count):
                out_leaves[r].append(vals[r % src_count])
            continue
        if src is None:
            full = vals[0]
        else:
            src = validate_spec(src, np.asarray(vals[0]).ndim, "src_layout")
            if schedule is not None and dst != "per_rank":
                blocks = _scheduled_leaf(vals, src, dst, dst_count,
                                         schedule, topology)
                if blocks is not None:
                    for r in range(dst_count):
                        out_leaves[r].append(blocks[r])
                    continue
            full = np.concatenate([np.asarray(v) for v in vals], axis=src)
        if dst is None:
            for r in range(dst_count):
                out_leaves[r].append(full)
            continue
        full = np.asarray(full)
        dst = validate_spec(dst, full.ndim, "dst_layout")
        block = _split_even(full.shape[dst], dst_count,
                            f"reshard_host leaf {li}")
        for r in range(dst_count):
            idx = [slice(None)] * full.ndim
            idx[dst] = slice(r * block, (r + 1) * block)
            out_leaves[r].append(full[tuple(idx)])
    return [jax.tree_util.tree_unflatten(treedef, ls) for ls in out_leaves]
