"""Parallelism strategies beyond data parallel: sequence/context and tensor.

The reference predates long-context techniques entirely (SURVEY.md §5
"long-context: absent — 2017-era codebase"), but its L1/L3 primitives
(`alltoall`, ring `send/recv`) are exactly the substrate they need; per the
rebuild brief these are FIRST-CLASS here, built the TPU way: ring attention
as a ``ppermute`` ring over ICI neighbors (the physical torus topology) with
online-softmax accumulation, and Ulysses-style head↔sequence swaps as one
XLA ``all_to_all``.
"""

from .ring_attention import (  # noqa: F401
    make_ring_attention,
    ring_attention,
)
from .ulysses import (  # noqa: F401
    make_ulysses_attention,
    ulysses_attention,
)
from .moe import (  # noqa: F401
    init_moe_mlp_params,
    make_moe_mlp,
    moe_mlp,
    moe_mlp_specs,
)
from .pipeline import (  # noqa: F401
    make_pipeline,
    make_pipeline_1f1b,
    pipeline_1f1b_grads,
    pipeline_apply,
    stack_stage_params,
)
from .collective_matmul import (  # noqa: F401
    all_gather_matmul,
    make_all_gather_matmul,
    make_matmul_reduce_scatter,
    matmul_reduce_scatter,
)
from .hybrid import (  # noqa: F401
    init_fsdp_params,
    init_fsdp_state,
    init_zero1_state,
    make_fsdp_train_step,
    make_hybrid_shard_map_step,
    make_hybrid_train_step,
    make_zero1_train_step,
    shard_pytree,
    state_specs_like,
    zero1_specs,
)
from .decode import (  # noqa: F401
    lm_generate,
    lm_generate_beam,
    make_lm_beam_generator,
    make_lm_generator,
)
from .transformer import (  # noqa: F401
    apply_rope,
    init_tp_transformer_lm,
    sp_block,
    sp_transformer_lm_loss,
    tp_attention,
    tp_attention_sp,
    tp_block,
    tp_block_sp,
    tp_transformer_lm_loss,
    transformer_lm_specs,
    vocab_parallel_logits_loss,
)
from .reshard import (  # noqa: F401
    make_reshard,
    reshard,
    reshard_cost,
    reshard_host,
    reshard_tree_cost,
)
from .tensor_parallel import (  # noqa: F401
    column_parallel_dense,
    init_tp_mlp_params,
    make_tensor_parallel_mlp,
    row_parallel_dense,
    gather_seq_matmul,
    matmul_scatter_seq,
    tp_mlp,
    tp_mlp_sp,
    tp_mlp_specs,
    vocab_parallel_embedding,
)

__all__ = [
    "reshard",
    "make_reshard",
    "reshard_host",
    "reshard_cost",
    "reshard_tree_cost",
    "ring_attention",
    "make_ring_attention",
    "ulysses_attention",
    "make_ulysses_attention",
    "pipeline_apply",
    "stack_stage_params",
    "make_pipeline",
    "make_pipeline_1f1b",
    "pipeline_1f1b_grads",
    "moe_mlp",
    "init_moe_mlp_params",
    "moe_mlp_specs",
    "make_moe_mlp",
    "column_parallel_dense",
    "row_parallel_dense",
    "vocab_parallel_embedding",
    "tp_mlp",
    "tp_mlp_sp",
    "gather_seq_matmul",
    "matmul_scatter_seq",
    "init_tp_mlp_params",
    "tp_mlp_specs",
    "make_tensor_parallel_mlp",
    "all_gather_matmul",
    "matmul_reduce_scatter",
    "make_all_gather_matmul",
    "make_matmul_reduce_scatter",
    "make_hybrid_train_step",
    "make_hybrid_shard_map_step",
    "make_zero1_train_step",
    "make_fsdp_train_step",
    "init_fsdp_params",
    "init_fsdp_state",
    "init_zero1_state",
    "zero1_specs",
    "shard_pytree",
    "state_specs_like",
    "apply_rope",
    "lm_generate",
    "lm_generate_beam",
    "make_lm_beam_generator",
    "make_lm_generator",
    "init_tp_transformer_lm",
    "sp_block",
    "sp_transformer_lm_loss",
    "tp_attention",
    "tp_attention_sp",
    "tp_block",
    "tp_block_sp",
    "tp_transformer_lm_loss",
    "transformer_lm_specs",
    "vocab_parallel_logits_loss",
]
