"""Sequence/context parallelism for long-context attention.

The reference predates long-context techniques entirely (SURVEY.md §5
"long-context: absent — 2017-era codebase"), but its L1/L3 primitives
(`alltoall`, ring `send/recv`) are exactly the substrate they need; per the
rebuild brief these are FIRST-CLASS here, built the TPU way: ring attention
as a ``ppermute`` ring over ICI neighbors (the physical torus topology) with
online-softmax accumulation, and Ulysses-style head↔sequence swaps as one
XLA ``all_to_all``.
"""

from .ring_attention import (  # noqa: F401
    make_ring_attention,
    ring_attention,
)
from .ulysses import (  # noqa: F401
    make_ulysses_attention,
    ulysses_attention,
)

__all__ = [
    "ring_attention",
    "make_ring_attention",
    "ulysses_attention",
    "make_ulysses_attention",
]
