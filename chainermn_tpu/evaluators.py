"""Distributed evaluation.

Reference parity: ``chainermn/evaluators/__init__.py ::
create_multi_node_evaluator`` [uv] (SURVEY.md §2.6) — each rank evaluates
its dataset shard, then the results dict is allreduce-averaged so every rank
reports the global metrics.

TPU-native: an evaluator is any callable ``(shard) -> dict[str, float]``;
the wrapper runs it per rank shard and averages (weighted by shard example
counts, so unequal shards don't bias the mean).  The reference subclassed
Chainer's Evaluator dynamically; here composition replaces inheritance.
"""

from __future__ import annotations

from typing import Callable, Dict, Mapping, Sequence

import numpy as np

from .communicators.base import CommunicatorBase
from .datasets import ScatteredDataset


def _as_shards(scattered, communicator) -> Sequence:
    """Normalize evaluator input to the list of shards THIS process should
    evaluate: all ranks' shards single-controller; under multi-controller,
    the shards of EVERY rank this process owns (one per local device — not
    just ``local()``'s first rank), so the cross-process combine pools each
    shard exactly once and nobody re-evaluates the whole corpus P times."""
    if isinstance(scattered, ScatteredDataset):
        if communicator.inter_size > 1:
            owned = [r for r in range(min(len(scattered), communicator.size))
                     if communicator.owns_rank(r)]
            # owned may be empty when len(scattered) < communicator.size
            # (more processes than shards): contribute NOTHING rather than
            # re-evaluating another process's shard — the allreduce_obj
            # combine tolerates zero local shards, and a fallback to
            # ``scattered.local()`` would double-count that shard's
            # statistics (its owner evaluates it too).
            return [scattered.shard(r) for r in owned]
        return [scattered.shard(r) for r in range(len(scattered))]
    return list(scattered)


def create_multi_node_evaluator(actual_evaluator: Callable, communicator: CommunicatorBase):
    """Wrap ``actual_evaluator`` for multi-rank evaluation.

    ``actual_evaluator(shard) -> Mapping[str, float]`` evaluates one rank's
    data.  The returned wrapper accepts a :class:`ScatteredDataset` (or a
    sequence of per-rank shards) and returns the cross-rank weighted mean of
    every metric — what each reference rank would see after
    ``allreduce_obj`` averaging.
    """

    def evaluate(scattered) -> Dict[str, float]:
        shards = _as_shards(scattered, communicator)
        totals: Dict[str, float] = {}
        weights: Dict[str, float] = {}
        for shard in shards:
            result: Mapping[str, float] = actual_evaluator(shard)
            w = float(len(shard)) if hasattr(shard, "__len__") else 1.0
            for k, v in result.items():
                totals[k] = totals.get(k, 0.0) + float(v) * w
                weights[k] = weights.get(k, 0.0) + w
        # Cross-process combine: ship (weighted-sum, weight) pairs so the
        # global mean stays example-weighted even when hosts hold unequal
        # shard counts.  Identity single-process (all shards local).
        if communicator.inter_size > 1:
            # Union of keys with (0, 0) identity: a process that owns no
            # shard (more processes than shards) contributes an empty dict
            # and must not erase everyone else's metrics.
            def combine(a, b):
                zero = (0.0, 0.0)
                return {k: (a.get(k, zero)[0] + b.get(k, zero)[0],
                            a.get(k, zero)[1] + b.get(k, zero)[1])
                        for k in set(a) | set(b)}

            summed = communicator.allreduce_obj(
                {k: (totals[k], weights[k]) for k in totals}, op=combine)
            return {k: s / w for k, (s, w) in summed.items()}
        return {k: totals[k] / weights[k] for k in totals}

    return evaluate


def accuracy_evaluator(predict_fn: Callable, batch_size: int = 256):
    """Convenience: classification loss/accuracy evaluator over a shard.

    ``predict_fn(xs) -> logits``.  Shard items must be ``(x, label)`` pairs.
    """

    def evaluate(shard) -> Dict[str, float]:
        n = len(shard)
        correct, total, loss_sum = 0, 0, 0.0
        for start in range(0, n, batch_size):
            items = [shard[i] for i in range(start, min(start + batch_size, n))]
            xs = np.stack([x for x, _ in items])
            ys = np.asarray([y for _, y in items])
            logits = np.asarray(predict_fn(xs))
            shifted = logits - logits.max(axis=-1, keepdims=True)
            logp = shifted - np.log(np.exp(shifted).sum(axis=-1, keepdims=True))
            loss_sum += float(-logp[np.arange(len(ys)), ys].sum())
            correct += int((logits.argmax(-1) == ys).sum())
            total += len(ys)
        return {"validation/loss": loss_sum / max(total, 1),
                "validation/accuracy": correct / max(total, 1)}

    return evaluate


def _bleu_counts(references, hypotheses, max_n):
    """Sufficient statistics for corpus BLEU: clipped n-gram matches,
    totals, and lengths — these POOL ADDITIVELY across data shards, which
    is what lets the distributed evaluator combine processes exactly."""
    from collections import Counter

    hyp_len = ref_len = 0
    match = [0] * max_n
    total = [0] * max_n
    for ref, hyp in zip(references, hypotheses):
        ref, hyp = list(ref), list(hyp)
        hyp_len += len(hyp)
        ref_len += len(ref)
        for n in range(1, max_n + 1):
            h_ngrams = Counter(tuple(hyp[i:i + n])
                               for i in range(len(hyp) - n + 1))
            r_ngrams = Counter(tuple(ref[i:i + n])
                               for i in range(len(ref) - n + 1))
            total[n - 1] += max(len(hyp) - n + 1, 0)
            match[n - 1] += sum((h_ngrams & r_ngrams).values())
    return match, total, hyp_len, ref_len


def _bleu_from_counts(match, total, hyp_len, ref_len, max_n, smooth):
    import math

    log_p = 0.0
    for n in range(max_n):
        m, t = match[n], total[n]
        if smooth and n > 0:
            m, t = m + 1, t + 1
        if m == 0 or t == 0:
            return 0.0
        log_p += math.log(m / t)
    bp = (1.0 if hyp_len >= ref_len
          else math.exp(1.0 - ref_len / max(hyp_len, 1)))
    return bp * math.exp(log_p / max_n)


def corpus_bleu(references: Sequence[Sequence[int]],
                hypotheses: Sequence[Sequence[int]],
                max_n: int = 4, smooth: bool = True) -> float:
    """Corpus-level BLEU over token-id sequences (no nltk dependency).

    Reference parity: the reference's seq2seq example scored translations
    with BLEU via an nltk-backed trainer extension (``examples/seq2seq``
    [uv], SURVEY.md §2.9 BASELINE config #3).  Standard Papineni BLEU:
    clipped modified n-gram precision up to ``max_n``, geometric mean,
    brevity penalty; ``smooth`` adds +1 smoothing on n>1 precisions so one
    missing 4-gram doesn't zero a short corpus.
    """
    if len(references) != len(hypotheses):
        raise ValueError(f"{len(references)} references vs "
                         f"{len(hypotheses)} hypotheses")
    counts = _bleu_counts(references, hypotheses, max_n)
    return _bleu_from_counts(*counts, max_n, smooth)


def bleu_evaluator(translate_fn: Callable, communicator: CommunicatorBase,
                   max_n: int = 4, smooth: bool = True):
    """Distributed BLEU: each rank translates its shard, n-gram COUNT
    statistics pool across processes (BLEU does not decompose into a
    per-shard mean), one corpus score comes back everywhere.

    ``translate_fn(sources) -> list of token-id lists``.  Returns a
    callable ``(scattered_pairs) -> {"bleu": float}`` where each example is
    ``(source_tokens, reference_tokens)``.
    """

    def evaluate(scattered) -> Dict[str, float]:
        shards = _as_shards(scattered, communicator)
        refs: list = []
        hyps: list = []
        for shard in shards:
            srcs = [ex[0] for ex in shard]
            outs = [list(h) for h in translate_fn(srcs)]
            if len(outs) != len(srcs):
                raise ValueError(
                    f"translate_fn returned {len(outs)} hypotheses for "
                    f"{len(srcs)} sources — a silent zip would misalign "
                    f"every later pair")
            refs.extend([list(ex[1]) for ex in shard])
            hyps.extend(outs)
        match, total, hyp_len, ref_len = _bleu_counts(refs, hyps, max_n)
        if communicator.inter_size > 1:
            # Pool the additive statistics across processes (same combine
            # pattern as create_multi_node_evaluator).
            match, total, hyp_len, ref_len = communicator.allreduce_obj(
                (match, total, hyp_len, ref_len),
                op=lambda a, b: (
                    [x + y for x, y in zip(a[0], b[0])],
                    [x + y for x, y in zip(a[1], b[1])],
                    a[2] + b[2], a[3] + b[3]),
            )
        return {"bleu": _bleu_from_counts(match, total, hyp_len, ref_len,
                                          max_n, smooth)}

    return evaluate
