"""Distributed evaluation.

Reference parity: ``chainermn/evaluators/__init__.py ::
create_multi_node_evaluator`` [uv] (SURVEY.md §2.6) — each rank evaluates
its dataset shard, then the results dict is allreduce-averaged so every rank
reports the global metrics.

TPU-native: an evaluator is any callable ``(shard) -> dict[str, float]``;
the wrapper runs it per rank shard and averages (weighted by shard example
counts, so unequal shards don't bias the mean).  The reference subclassed
Chainer's Evaluator dynamically; here composition replaces inheritance.
"""

from __future__ import annotations

from typing import Callable, Dict, Mapping, Sequence

import numpy as np

from .communicators.base import CommunicatorBase
from .datasets import ScatteredDataset


def create_multi_node_evaluator(actual_evaluator: Callable, communicator: CommunicatorBase):
    """Wrap ``actual_evaluator`` for multi-rank evaluation.

    ``actual_evaluator(shard) -> Mapping[str, float]`` evaluates one rank's
    data.  The returned wrapper accepts a :class:`ScatteredDataset` (or a
    sequence of per-rank shards) and returns the cross-rank weighted mean of
    every metric — what each reference rank would see after
    ``allreduce_obj`` averaging.
    """

    def evaluate(scattered) -> Dict[str, float]:
        shards: Sequence = (
            [scattered.shard(r) for r in range(len(scattered))]
            if isinstance(scattered, ScatteredDataset)
            else list(scattered)
        )
        totals: Dict[str, float] = {}
        weights: Dict[str, float] = {}
        for shard in shards:
            result: Mapping[str, float] = actual_evaluator(shard)
            w = float(len(shard)) if hasattr(shard, "__len__") else 1.0
            for k, v in result.items():
                totals[k] = totals.get(k, 0.0) + float(v) * w
                weights[k] = weights.get(k, 0.0) + w
        # Cross-process combine: ship (weighted-sum, weight) pairs so the
        # global mean stays example-weighted even when hosts hold unequal
        # shard counts.  Identity single-process (all shards local).
        if communicator.inter_size > 1:
            summed = communicator.allreduce_obj(
                {k: (totals[k], weights[k]) for k in totals},
                op=lambda a, b: {k: (a[k][0] + b[k][0], a[k][1] + b[k][1]) for k in a},
            )
            return {k: s / w for k, (s, w) in summed.items()}
        return {k: totals[k] / weights[k] for k in totals}

    return evaluate


def accuracy_evaluator(predict_fn: Callable, batch_size: int = 256):
    """Convenience: classification loss/accuracy evaluator over a shard.

    ``predict_fn(xs) -> logits``.  Shard items must be ``(x, label)`` pairs.
    """

    def evaluate(shard) -> Dict[str, float]:
        n = len(shard)
        correct, total, loss_sum = 0, 0, 0.0
        for start in range(0, n, batch_size):
            items = [shard[i] for i in range(start, min(start + batch_size, n))]
            xs = np.stack([x for x, _ in items])
            ys = np.asarray([y for _, y in items])
            logits = np.asarray(predict_fn(xs))
            shifted = logits - logits.max(axis=-1, keepdims=True)
            logp = shifted - np.log(np.exp(shifted).sum(axis=-1, keepdims=True))
            loss_sum += float(-logp[np.arange(len(ys)), ys].sum())
            correct += int((logits.argmax(-1) == ys).sum())
            total += len(ys)
        return {"validation/loss": loss_sum / max(total, 1),
                "validation/accuracy": correct / max(total, 1)}

    return evaluate
