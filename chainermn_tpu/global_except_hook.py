"""Global exception hook: one rank's crash kills the whole job, loudly.

Reference parity: ``chainermn/global_except_hook.py`` [uv] (SURVEY.md §2.6,
§5 "race detection") — installs a ``sys.excepthook`` that prints the
traceback then calls ``MPI_Abort`` so an uncaught exception on any rank
aborts the gang instead of leaving the other ranks deadlocked inside a
collective.

TPU adaptation: under multi-controller JAX the failure-propagation channel
is the coordinator — a process that exits non-zero is detected by the
coordinator's heartbeat and the remaining processes' blocked collectives
fail with a distributed-runtime error.  The hook prints a rank-prefixed
traceback, asks the distributed runtime to shut down, then hard-exits so
the coordinator notices immediately rather than after a collective timeout.
Single-process behavior is the stock traceback (nothing to abort).
"""

from __future__ import annotations

import os
import sys
import traceback

_installed = False
_orig_hook = None


def _flight_dump(exc_type, exc_value) -> None:
    """Best-effort debug bundle before the process dies (flight recorder
    — ISSUE 5).  Bounded side thread: the bundle writes files, and a
    wedged filesystem must not turn the loud abort into a hang."""
    import threading

    def run():
        try:
            from .observability import flight
            flight.dump_on_crash(exc_type, exc_value)
        except Exception:
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=10.0)


def _global_except_hook(exc_type, exc_value, tb) -> None:
    import jax

    _flight_dump(exc_type, exc_value)
    try:
        nproc = jax.process_count()
    except Exception:
        nproc = 1
    if nproc <= 1:
        (_orig_hook or sys.__excepthook__)(exc_type, exc_value, tb)
        return
    rank = jax.process_index()
    sys.stderr.write(
        f"[chainermn_tpu] uncaught exception on process {rank}/{nproc} — "
        "aborting the whole job (reference analog: MPI_Abort):\n")
    sys.stderr.write("".join(traceback.format_exception(exc_type, exc_value, tb)))
    sys.stderr.flush()
    # Ask the coordinator to shut down, but NEVER let that block the abort:
    # shutdown() itself can wait on peers that are wedged in the very
    # collective this crash abandoned, which would turn the loud abort into
    # the silent hang the hook exists to prevent.  Bounded side thread, then
    # hard exit (not sys.exit) regardless.
    import threading

    def _shutdown():
        try:
            jax.distributed.shutdown()
        except Exception:
            pass

    t = threading.Thread(target=_shutdown, daemon=True)
    t.start()
    t.join(timeout=5.0)
    os._exit(1)


def add_hook() -> None:
    """Install the hook (idempotent).  The reference auto-installed at
    ``import chainermn`` [uv]; here installation is explicit via
    ``chainermn_tpu.init_distributed`` or a direct call, so importing the
    library never mutates interpreter state."""
    global _installed, _orig_hook
    if _installed:
        return
    _orig_hook = sys.excepthook
    sys.excepthook = _global_except_hook
    _installed = True


def remove_hook() -> None:
    global _installed
    if _installed:
        sys.excepthook = _orig_hook or sys.__excepthook__
        _installed = False
