"""Model-parallel graph container.

Reference parity: ``chainermn/links/multi_node_chain_list.py ::
MultiNodeChainList`` [uv] (SURVEY.md §2.3, §3.5, BASELINE config #5).  The
reference registers sub-chains annotated with ``rank_in``/``rank_out``;
forward interleaves blocking MPI ``recv → chain → send`` with
pseudo_connect threading, and autograd replays the messages in reverse.

TPU-native (single-controller), two execution faces:

* **Eager (placed)** — the default, closest to the reference's execution
  model: each stage's params are pinned to its rank's chip at
  registration (``device_put``), ``_to_rank`` edges are real cross-chip
  copies (ICI transfers), and each stage's compute runs on its own chip
  because its operands are committed there.  Still differentiable
  end-to-end — ``jax.grad`` replays the transfers in reverse
  (``device_put``'s transpose moves the cotangent back), which is the
  reference's "autograd crosses process boundaries" (§3.5) for free.
* **Traced (fused)** — call the instance inside ``jax.jit``: the graph
  becomes one differentiable program, routing stays logical, and XLA
  places the fused program (in-jit ``device_put`` is a scheduling hint at
  best).  Use this when single-executable fusion matters more than
  explicit placement.

The high-throughput microbatched SPMD pipeline lives in
``chainermn_tpu.parallel.pipeline`` (the reference had no schedule at all —
SURVEY.md §2.8 "PP: absent").  The message routing table (who consumes
whose output) is exactly the reference's:

* ``rank_in=None``  → stage consumes the model input ``x``
* ``rank_in=r``     → stage consumes the pending message addressed to its
  rank by an earlier stage with ``rank_out`` covering it
* ``rank_in=[r...]``→ stage consumes a list of messages (graph join)
* ``rank_out=None`` → stage's output is the model output
* ``rank_out=r`` / ``[r...]`` → output is addressed to those ranks (fan-out)
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax

from ..communicators.base import CommunicatorBase

Rank = Optional[Union[int, Sequence[int]]]


class _Stage:
    def __init__(self, apply_fn, params, rank: int, rank_in: Rank, rank_out: Rank):
        self.apply_fn = apply_fn
        self.params = params
        self.rank = rank
        self.rank_in = rank_in
        self.rank_out = rank_out


class MultiNodeChainList:
    """Sequentially-registered model-parallel graph (reference semantics).

    ``add_link(apply_fn, params, rank, rank_in, rank_out)`` registers a
    stage owned by chip ``rank``; ``apply_fn(params, x)`` is any jittable
    callable (a flax ``Module.apply`` closure, a plain function over a
    pytree, ...).  Stages execute in registration order, exactly like the
    reference's forward loop.  Call the instance inside ``jax.jit`` for one
    fused multi-chip executable.
    """

    def __init__(self, comm: CommunicatorBase):
        self._comm = comm
        self._stages: List[_Stage] = []

    def add_link(self, apply_fn: Callable, params: Any, rank: int,
                 rank_in: Rank = None, rank_out: Rank = None) -> None:
        if not 0 <= rank < self._comm.size:
            raise ValueError(f"rank {rank} out of range for size {self._comm.size}")
        device = self._comm.device_of(rank)
        if device is not None:
            # Pin the stage's params to its chip — with its operands
            # committed there, the stage's compute lands on that chip
            # (reference: "rank → intra_rank-th GPU" placement, SURVEY.md §1).
            params = jax.device_put(params, device)
        self._stages.append(_Stage(apply_fn, params, rank, rank_in, rank_out))

    def _to_rank(self, value, rank: int):
        """The transfer edge →rank.  Eager: a real cross-chip copy (ICI)
        committing ``value`` to rank's chip, differentiable (the transpose
        copies the cotangent back).  Inside jit (tracing): a no-op hint —
        the fused program's placement belongs to XLA."""
        device = self._comm.device_of(rank)
        if device is None:
            return value
        return jax.device_put(value, device)

    def params(self, placed: bool = False) -> List[Any]:
        """Per-stage parameter pytrees (differentiable argument list for
        ``__call__(x, params=...)``).

        ``placed=False`` (default): uncommitted host copies — safe as an
        argument of ONE fused ``jax.jit`` (jit rejects arguments committed
        to different chips), and the pre-placement behavior callers relied
        on.  ``placed=True``: each stage's pytree committed to its rank's
        chip, for driving the eager placed face explicitly.  Either way the
        *internally stored* stage params stay pinned, so ``mnc(x)`` without
        a params override always executes placed.
        """
        if placed:
            return [s.params for s in self._stages]
        return [jax.tree_util.tree_map(lambda v: jax.device_get(v), s.params)
                for s in self._stages]

    def __call__(self, x, params: Optional[List[Any]] = None):
        """Run the graph.  ``params`` overrides stage parameters (so the
        whole list can be a differentiable argument of a jitted loss)."""
        if params is None:
            params = [s.params for s in self._stages]
        # mailbox[r] = queue of (source_rank, activation) addressed to rank
        # r, in send order — mirrors the reference's tag-matched MPI recv:
        # a stage pops the first pending message FROM its declared source
        mailbox = {r: [] for r in range(self._comm.size)}

        def pop_from(rank: int, source: int):
            for i, (src, v) in enumerate(mailbox[rank]):
                if src == source:
                    return mailbox[rank].pop(i)[1]
            raise RuntimeError(
                f"stage on rank {rank} expects a message from rank {source} "
                "but none is pending — check registration order (reference: "
                "forward order must match the send/recv pairing)")

        output = None
        for stage, p in zip(self._stages, params):
            if stage.rank_in is None:
                inp = self._to_rank(x, stage.rank)
            elif isinstance(stage.rank_in, int):
                inp = self._to_rank(pop_from(stage.rank, stage.rank_in),
                                    stage.rank)
            else:  # join: one message per listed source rank, in declared order
                inp = [self._to_rank(pop_from(stage.rank, src), stage.rank)
                       for src in stage.rank_in]
            y = stage.apply_fn(p, inp)
            if stage.rank_out is None:
                output = y
            elif isinstance(stage.rank_out, int):
                mailbox[stage.rank_out].append((stage.rank, y))
            else:  # fan-out
                for r in stage.rank_out:
                    mailbox[r].append((stage.rank, y))
        if output is None:
            raise RuntimeError("no stage declared rank_out=None (model output)")
        return output
