"""Model-parallel graph container.

Reference parity: ``chainermn/links/multi_node_chain_list.py ::
MultiNodeChainList`` [uv] (SURVEY.md §2.3, §3.5, BASELINE config #5).  The
reference registers sub-chains annotated with ``rank_in``/``rank_out``;
forward interleaves blocking MPI ``recv → chain → send`` with
pseudo_connect threading, and autograd replays the messages in reverse.

TPU-native (single-controller): the whole graph traces into ONE
differentiable jitted program — stage boundaries are data edges, not
blocking messages, so "autograd across the process boundary" (the
reference's hard part, §3.5) is just autodiff.  Routing is logical: this
container preserves the reference's message-passing semantics; *physical*
placement comes from the shardings of the enclosing jit (pin stage params
with device_put/shardings at the top level), and the high-throughput
microbatched SPMD pipeline lives in ``chainermn_tpu.parallel.pipeline``
(the reference had no schedule at all — SURVEY.md §2.8 "PP: absent").
The message routing table (who consumes whose output) is exactly the
reference's:

* ``rank_in=None``  → stage consumes the model input ``x``
* ``rank_in=r``     → stage consumes the pending message addressed to its
  rank by an earlier stage with ``rank_out`` covering it
* ``rank_in=[r...]``→ stage consumes a list of messages (graph join)
* ``rank_out=None`` → stage's output is the model output
* ``rank_out=r`` / ``[r...]`` → output is addressed to those ranks (fan-out)
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax

from ..communicators.base import CommunicatorBase

Rank = Optional[Union[int, Sequence[int]]]


class _Stage:
    def __init__(self, apply_fn, params, rank: int, rank_in: Rank, rank_out: Rank):
        self.apply_fn = apply_fn
        self.params = params
        self.rank = rank
        self.rank_in = rank_in
        self.rank_out = rank_out


class MultiNodeChainList:
    """Sequentially-registered model-parallel graph (reference semantics).

    ``add_link(apply_fn, params, rank, rank_in, rank_out)`` registers a
    stage owned by chip ``rank``; ``apply_fn(params, x)`` is any jittable
    callable (a flax ``Module.apply`` closure, a plain function over a
    pytree, ...).  Stages execute in registration order, exactly like the
    reference's forward loop.  Call the instance inside ``jax.jit`` for one
    fused multi-chip executable.
    """

    def __init__(self, comm: CommunicatorBase):
        self._comm = comm
        self._stages: List[_Stage] = []

    def add_link(self, apply_fn: Callable, params: Any, rank: int,
                 rank_in: Rank = None, rank_out: Rank = None) -> None:
        if not 0 <= rank < self._comm.size:
            raise ValueError(f"rank {rank} out of range for size {self._comm.size}")
        self._stages.append(_Stage(apply_fn, params, rank, rank_in, rank_out))

    def _to_rank(self, value, rank: int):
        """The logical transfer edge rank→rank.  Placement is decided by the
        enclosing jit's shardings; inside the traced program this edge is
        where XLA emits the ICI copy when stages are pinned to chips."""
        del rank
        return value

    def params(self) -> List[Any]:
        """Per-stage parameter pytrees (differentiable argument list for
        ``__call__(x, params=...)``)."""
        return [s.params for s in self._stages]

    def __call__(self, x, params: Optional[List[Any]] = None):
        """Run the graph.  ``params`` overrides stage parameters (so the
        whole list can be a differentiable argument of a jitted loss)."""
        if params is None:
            params = [s.params for s in self._stages]
        # mailbox[r] = queue of (source_rank, activation) addressed to rank
        # r, in send order — mirrors the reference's tag-matched MPI recv:
        # a stage pops the first pending message FROM its declared source
        mailbox = {r: [] for r in range(self._comm.size)}

        def pop_from(rank: int, source: int):
            for i, (src, v) in enumerate(mailbox[rank]):
                if src == source:
                    return mailbox[rank].pop(i)[1]
            raise RuntimeError(
                f"stage on rank {rank} expects a message from rank {source} "
                "but none is pending — check registration order (reference: "
                "forward order must match the send/recv pairing)")

        output = None
        for stage, p in zip(self._stages, params):
            if stage.rank_in is None:
                inp = self._to_rank(x, stage.rank)
            elif isinstance(stage.rank_in, int):
                inp = self._to_rank(pop_from(stage.rank, stage.rank_in),
                                    stage.rank)
            else:  # join: one message per listed source rank, in declared order
                inp = [self._to_rank(pop_from(stage.rank, src), stage.rank)
                       for src in stage.rank_in]
            y = stage.apply_fn(p, inp)
            if stage.rank_out is None:
                output = y
            elif isinstance(stage.rank_out, int):
                mailbox[stage.rank_out].append((stage.rank, y))
            else:  # fan-out
                for r in stage.rank_out:
                    mailbox[r].append((stage.rank, y))
        if output is None:
            raise RuntimeError("no stage declared rank_out=None (model output)")
        return output
