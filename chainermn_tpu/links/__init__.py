from .multi_node_batch_normalization import MultiNodeBatchNormalization  # noqa: F401
from .multi_node_chain_list import MultiNodeChainList  # noqa: F401
